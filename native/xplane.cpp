// Native XLA-profiler bridge: parse .xplane.pb trace files into
// per-op (name, start_ns, end_ns) interval lists.
//
// This is the TPU-native equivalent of the reference's CUPTI Activity
// bridge (SURVEY.md §2.2 N1; reference utils/cupti.cpp:1-175): where
// CUPTI streamed CUDA kernel records through callback buffers, the XLA
// profiler (driven from Python via jax.profiler.start_trace/stop_trace)
// writes an XSpace protobuf per host; this library decodes it natively
// and exposes a flat event table over a C ABI (ctypes; pybind11 is not
// available in this image).
//
// The decoder is a minimal protobuf wire-format walker — no protobuf
// runtime dependency — using the XSpace schema's stable field numbers
// (verified empirically against traces produced by this image's jax):
//   XSpace.planes = 1
//   XPlane: .name = 2, .lines = 3, .event_metadata = 4 (map: k=1 v=2)
//   XEventMetadata: .name = 2
//   XLine: .name = 2, .timestamp_ns = 3, .events = 4
//   XEvent: .metadata_id = 1, .offset_ps = 2, .duration_ps = 3
// Unknown fields of any wire type are skipped, so schema additions
// don't break the parser.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <string>
#include <vector>

namespace {

struct Slice {
  const uint8_t* p = nullptr;
  size_t len = 0;
};

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint64_t Varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      const uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }

  // Returns false at end of buffer; on success fills field/wire/data.
  bool Next(uint32_t* field, uint32_t* wire, Slice* data,
            uint64_t* scalar) {
    if (p >= end || !ok) return false;
    const uint64_t key = Varint();
    if (!ok) return false;
    *field = static_cast<uint32_t>(key >> 3);
    *wire = static_cast<uint32_t>(key & 7);
    switch (*wire) {
      case 0:  // varint
        *scalar = Varint();
        return ok;
      case 2: {  // length-delimited
        const uint64_t len = Varint();
        // compare against remaining bytes; `p + len` could overflow
        if (!ok || len > static_cast<uint64_t>(end - p))
          return ok = false;
        data->p = p;
        data->len = static_cast<size_t>(len);
        p += len;
        return true;
      }
      case 5:  // fixed32
        if (p + 4 > end) return ok = false;
        p += 4;
        return true;
      case 1:  // fixed64
        if (p + 8 > end) return ok = false;
        p += 8;
        return true;
      default:
        return ok = false;
    }
  }
};

struct Event {
  std::string name;
  std::string plane;
  std::string line;
  long long start_ns;
  long long end_ns;
};

struct Result {
  std::vector<Event> events;
};

void ParsePlane(Slice plane_bytes, const char* plane_filter,
                Result* out) {
  // pass 1: plane name + event-metadata map
  std::string plane_name;
  std::map<uint64_t, std::string> names;
  std::vector<Slice> lines;
  {
    Cursor c{plane_bytes.p, plane_bytes.p + plane_bytes.len};
    uint32_t f, w;
    Slice d;
    uint64_t s;
    while (c.Next(&f, &w, &d, &s)) {
      if (f == 2 && w == 2) {
        plane_name.assign(reinterpret_cast<const char*>(d.p), d.len);
      } else if (f == 3 && w == 2) {
        lines.push_back(d);
      } else if (f == 4 && w == 2) {
        // map entry { key = 1 (varint), value = 2 (XEventMetadata) }
        Cursor m{d.p, d.p + d.len};
        uint64_t key = 0;
        Slice val{};
        uint32_t mf, mw;
        Slice md;
        uint64_t ms;
        while (m.Next(&mf, &mw, &md, &ms)) {
          if (mf == 1 && mw == 0) key = ms;
          else if (mf == 2 && mw == 2) val = md;
        }
        if (val.p) {
          Cursor em{val.p, val.p + val.len};
          while (em.Next(&mf, &mw, &md, &ms)) {
            if (mf == 2 && mw == 2) {
              names[key].assign(reinterpret_cast<const char*>(md.p),
                                md.len);
              break;
            }
          }
        }
      }
    }
  }
  if (plane_filter && *plane_filter &&
      plane_name.find(plane_filter) == std::string::npos)
    return;

  for (const Slice& line_bytes : lines) {
    std::string line_name;
    long long line_ts_ns = 0;
    std::vector<Slice> events;
    Cursor c{line_bytes.p, line_bytes.p + line_bytes.len};
    uint32_t f, w;
    Slice d;
    uint64_t s;
    while (c.Next(&f, &w, &d, &s)) {
      if (f == 2 && w == 2)
        line_name.assign(reinterpret_cast<const char*>(d.p), d.len);
      else if (f == 3 && w == 0)
        line_ts_ns = static_cast<long long>(s);
      else if (f == 4 && w == 2)
        events.push_back(d);
    }
    for (const Slice& ev : events) {
      uint64_t metadata_id = 0, offset_ps = 0, duration_ps = 0;
      Cursor e{ev.p, ev.p + ev.len};
      while (e.Next(&f, &w, &d, &s)) {
        if (w != 0) continue;
        if (f == 1) metadata_id = s;
        else if (f == 2) offset_ps = s;
        else if (f == 3) duration_ps = s;
      }
      Event item;
      const auto it = names.find(metadata_id);
      item.name = it != names.end()
                      ? it->second
                      : "metadata:" + std::to_string(metadata_id);
      item.plane = plane_name;
      item.line = line_name;
      item.start_ns =
          line_ts_ns + static_cast<long long>(offset_ps / 1000);
      item.end_ns =
          item.start_ns + static_cast<long long>(duration_ps / 1000);
      out->events.push_back(std::move(item));
    }
  }
}

}  // namespace

extern "C" {

// Parse `path`; keep only planes whose name contains `plane_filter`
// (NULL/"" = all planes).  Returns a handle or NULL on error.
void* rnb_xplane_load(const char* path, const char* plane_filter) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  // fseeko/ftello: off_t stays 64-bit where long may be 32, so a >2GB
  // trace is sized correctly (decode.cpp uses the same probe)
  fseeko(f, 0, SEEK_END);
  const off_t size = ftello(f);
  fseeko(f, 0, SEEK_SET);
  if (size <= 0) {
    fclose(f);
    return nullptr;
  }
  std::vector<uint8_t> buf(static_cast<size_t>(size));
  const bool read_ok =
      fread(buf.data(), 1, buf.size(), f) == buf.size();
  fclose(f);
  if (!read_ok) return nullptr;

  Result* result = new Result;
  Cursor c{buf.data(), buf.data() + buf.size()};
  uint32_t field, wire;
  Slice data;
  uint64_t scalar;
  while (c.Next(&field, &wire, &data, &scalar)) {
    if (field == 1 && wire == 2) ParsePlane(data, plane_filter, result);
  }
  if (!c.ok && result->events.empty()) {
    delete result;
    return nullptr;
  }
  return result;
}

long long rnb_xplane_num_events(void* h) {
  return h ? static_cast<long long>(
                 static_cast<Result*>(h)->events.size())
           : 0;
}

static const Event* GetEvent(void* h, long long i) {
  if (!h) return nullptr;
  Result* r = static_cast<Result*>(h);
  if (i < 0 || static_cast<size_t>(i) >= r->events.size())
    return nullptr;
  return &r->events[static_cast<size_t>(i)];
}

const char* rnb_xplane_event_name(void* h, long long i) {
  const Event* e = GetEvent(h, i);
  return e ? e->name.c_str() : nullptr;
}

const char* rnb_xplane_event_plane(void* h, long long i) {
  const Event* e = GetEvent(h, i);
  return e ? e->plane.c_str() : nullptr;
}

const char* rnb_xplane_event_line(void* h, long long i) {
  const Event* e = GetEvent(h, i);
  return e ? e->line.c_str() : nullptr;
}

long long rnb_xplane_event_start_ns(void* h, long long i) {
  const Event* e = GetEvent(h, i);
  return e ? e->start_ns : -1;
}

long long rnb_xplane_event_end_ns(void* h, long long i) {
  const Event* e = GetEvent(h, i);
  return e ? e->end_ns : -1;
}

void rnb_xplane_free(void* h) { delete static_cast<Result*>(h); }

}  // extern "C"
