// Native host-side video decoder: the TPU-native stand-in for the
// reference's NVVL fork (SURVEY.md §2.2 N2; reference usage at
// models/r2p1d/model.py:123-145).  TPUs have no video ASIC, so decode
// is host CPU work; this library makes it native C++ with a worker
// pool so the decode stage keeps up with the accelerator.
//
// Formats:
//  * Uncompressed YUV4MPEG2 (.y4m), 4:2:0 or 4:4:4 — the format the
//    pure-numpy Y4MDecoder (rnb_tpu/decode/__init__.py) also speaks;
//    the two backends are numerically parity-tested against each
//    other.
//  * MJPEG (.mjpg): concatenated baseline JPEG frames, decoded by the
//    self-contained baseline decoder below (Huffman + dequant + IDCT,
//    4:2:0 or 4:4:4) — REAL codec compute in the measured loop, the
//    role NVDEC played for the reference (README.md:42-110). Parity
//    oracle: PIL/libjpeg in tests/test_mjpeg.py.
// The container is sniffed from the magic bytes; every entry point
// accepts either.
//
// Design notes:
//  * The decode of one output pixel needs exactly one Y/U/V sample
//    (nearest-neighbour chroma upsample + box-resize are both pure
//    index maps), so decode, upsample, convert and resize are fused
//    into a single gather per output pixel — unlike the numpy path,
//    the full frame is never materialized.
//  * C ABI only (consumed via ctypes; pybind11 is not available in
//    this image).  All buffers are caller-owned.
//  * The pool is a plain mutex+condvar job queue; one ticket per
//    submitted decode, waitable from any thread.

#include <sys/stat.h>

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr int kErrIo = -1;        // open/seek/read failure
constexpr int kErrFormat = -2;    // not a y4m / bad header / bad marker
constexpr int kErrColorspace = -3;
constexpr int kErrArg = -4;
constexpr int kErrBudget = -5;    // dct: spectrum exceeds the wire budget

struct Y4mMeta {
  int width = 0;
  int height = 0;
  int subsample = 1;           // 1 = 4:4:4, 2 = 4:2:0
  long long frame_bytes = 0;
  long long data_start = 0;    // offset of first FRAME marker
  long long marker_len = 0;    // length of b"FRAME...\n" incl newline
  long long stride = 0;        // marker + payload
  long long count = 0;         // number of frames
};

// Read one '\n'-terminated line starting at `off`.  Returns false on
// IO error or if no newline is found within `maxlen` bytes.
bool ReadLine(FILE* f, long long off, std::string* line,
              size_t maxlen = 65536) {
  if (fseeko(f, off, SEEK_SET) != 0) return false;
  line->clear();
  int c;
  while (line->size() < maxlen && (c = fgetc(f)) != EOF) {
    line->push_back(static_cast<char>(c));
    if (c == '\n') return true;
  }
  return false;
}

int ProbeFile(const char* path, Y4mMeta* meta) {
  FILE* f = fopen(path, "rb");
  if (!f) return kErrIo;
  std::string header;
  if (!ReadLine(f, 0, &header) || header.rfind("YUV4MPEG2", 0) != 0) {
    fclose(f);
    return kErrFormat;
  }
  meta->width = meta->height = 0;
  std::string cs = "420";
  // tokens after the magic, space-separated, tag = first char
  size_t pos = header.find(' ');
  while (pos != std::string::npos && pos + 1 < header.size()) {
    size_t end = header.find_first_of(" \n", pos + 1);
    std::string token = header.substr(pos + 1, end - pos - 1);
    if (!token.empty()) {
      char tag = token[0];
      std::string val = token.substr(1);
      if (tag == 'W') meta->width = atoi(val.c_str());
      else if (tag == 'H') meta->height = atoi(val.c_str());
      else if (tag == 'C') cs = val;
    }
    pos = (end == std::string::npos || header[end] == '\n')
              ? std::string::npos : end;
  }
  if (meta->width <= 0 || meta->height <= 0) {
    fclose(f);
    return kErrFormat;
  }
  const long long wh =
      static_cast<long long>(meta->width) * meta->height;
  if (cs.rfind("420", 0) == 0) {
    meta->subsample = 2;
    meta->frame_bytes = wh * 3 / 2;
  } else if (cs.rfind("444", 0) == 0) {
    meta->subsample = 1;
    meta->frame_bytes = wh * 3;
  } else {
    fclose(f);
    return kErrColorspace;
  }
  meta->data_start = static_cast<long long>(header.size());
  std::string marker;
  if (!ReadLine(f, meta->data_start, &marker) ||
      marker.rfind("FRAME", 0) != 0) {
    fclose(f);
    return kErrFormat;
  }
  meta->marker_len = static_cast<long long>(marker.size());
  meta->stride = meta->marker_len + meta->frame_bytes;
  if (fseeko(f, 0, SEEK_END) != 0) {
    fclose(f);
    return kErrIo;
  }
  const long long size = ftello(f);
  fclose(f);
  meta->count = (size - meta->data_start) / meta->stride;
  if (meta->count <= 0) return kErrFormat;
  return 0;
}

inline unsigned char ClipByte(float v) {
  if (v < 0.f) v = 0.f;
  if (v > 255.f) v = 255.f;
  return static_cast<unsigned char>(v);  // trunc, matches np.astype(u8)
}

// ---------------------------------------------------------------------------
// Baseline JPEG decoder (ITU T.81 sequential DCT, 8-bit, Huffman).
// Self-contained: no libjpeg in this image. Decodes one frame into
// planar YCbCr at the source geometry (the same payload layout the y4m
// path reads), so the fused convert/gather stages are shared between
// containers. Supports 3-component 4:2:0 (2x2,1x1,1x1) and 4:4:4
// (1x1 x3) sampling, restart markers, multiple DQT/DHT segments.

constexpr int kZigzag[64] = {
    0,  1,  8,  16, 9,  2,  3,  10, 17, 24, 32, 25, 18, 11, 4,  5,
    12, 19, 26, 33, 40, 48, 41, 34, 27, 20, 13, 6,  7,  14, 21, 28,
    35, 42, 49, 56, 57, 50, 43, 36, 29, 22, 15, 23, 30, 37, 44, 51,
    58, 59, 52, 45, 38, 31, 39, 46, 53, 60, 61, 54, 47, 55, 62, 63};

struct HuffTable {
  // canonical decode per ITU T.81 F.2.2.3, plus an 8-bit lookahead
  // table (libjpeg's technique): one Peek(8) resolves the vast
  // majority of symbols without the per-bit walk.
  int mincode[17] = {0};
  int maxcode[17] = {0};  // -1 where no codes of that length
  int valptr[17] = {0};
  unsigned char values[256] = {0};
  unsigned short lut[256] = {0};  // (len << 8) | symbol; 0 = miss
  bool present = false;

  void Build(const unsigned char counts[16], const unsigned char* vals,
             int nvals) {
    for (int i = 0; i < nvals && i < 256; ++i) values[i] = vals[i];
    int code = 0, k = 0;
    std::memset(lut, 0, sizeof(lut));
    for (int l = 1; l <= 16; ++l) {
      valptr[l] = k;
      mincode[l] = code;
      const int n = counts[l - 1];
      if (l <= 8) {
        for (int i = 0; i < n; ++i) {
          const int c = code + i;
          const int base = c << (8 - l);
          for (int fill = 0; fill < (1 << (8 - l)); ++fill)
            lut[base | fill] =
                static_cast<unsigned short>((l << 8) | values[k + i]);
        }
      }
      code += n;
      k += n;
      maxcode[l] = n ? code - 1 : -1;
      code <<= 1;
    }
    present = true;
  }
};

struct BitReader {
  const unsigned char* d;
  size_t n, pos;
  unsigned long long acc = 0;  // MSB-justified within `count` bits
  int count = 0;
  bool starved = false;  // zero bits were synthesized past a marker/EOF

  BitReader(const unsigned char* data, size_t len)
      : d(data), n(len), pos(0) {}

  void Fill() {
    // fast path: when the next 8 bytes hold no 0xFF (no stuffing, no
    // marker — the overwhelmingly common case mid-scan), append all
    // the bytes that fit in one shift instead of branching per byte
    const int want = (64 - count) >> 3;
    if (want > 0 && pos + 8 <= n) {
      unsigned long long v;
      std::memcpy(&v, d + pos, 8);
      const unsigned long long m = ~v;  // 0xFF bytes of v become 0x00
      if (!((m - 0x0101010101010101ull) & ~m & 0x8080808080808080ull)) {
#if !defined(__BYTE_ORDER__) || __BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__
        v = __builtin_bswap64(v);  // byte 0 first -> MSB first
#endif  // big-endian memcpy already has byte 0 in the MSB
        // want == 8 only when count == 0: plain assign (acc << 64 is UB)
        acc = want == 8 ? v
                        : (acc << (want * 8)) | (v >> (64 - want * 8));
        pos += want;
        count += want * 8;
        return;
      }
    }
    while (count <= 56) {
      unsigned char b;
      if (pos >= n) {
        starved = true;
        b = 0;  // zero-pad: the trailing EOB bits of the last MCU may
                // legitimately read a few bits past the data end
      } else {
        b = d[pos];
        if (b == 0xFF) {
          if (pos + 1 < n && d[pos + 1] == 0x00) {
            pos += 2;  // stuffed zero
          } else {
            starved = true;  // a real marker: stop consuming bytes
            b = 0;
          }
        } else {
          ++pos;
        }
      }
      acc = (acc << 8) | b;
      count += 8;
    }
  }

  inline int Peek(int nbits) {
    if (count < nbits) Fill();
    return static_cast<int>((acc >> (count - nbits)) &
                            ((1ull << nbits) - 1));
  }

  inline void Drop(int nbits) { count -= nbits; }

  inline int GetBits(int nbits) {
    if (nbits == 0) return 0;
    const int v = Peek(nbits);
    count -= nbits;
    return v;
  }

  // byte-align and consume an expected RSTn marker (0xD0..0xD7)
  bool ConsumeRestart() {
    count = 0;
    acc = 0;
    starved = false;
    if (pos + 1 >= n || d[pos] != 0xFF) return false;
    const unsigned char m = d[pos + 1];
    if (m < 0xD0 || m > 0xD7) return false;
    pos += 2;
    return true;
  }
};

inline int HuffDecode(BitReader* br, const HuffTable& t) {
  const unsigned short hit = t.lut[br->Peek(8)];
  if (hit) {
    br->Drop(hit >> 8);
    return hit & 0xFF;
  }
  // slow path: codes longer than 8 bits (rare with standard tables)
  int code = br->Peek(8);
  int consumed = 8;
  for (int l = 9; l <= 16; ++l) {
    code = (code << 1) | ((br->Peek(l) & 1));
    consumed = l;
    // both bounds: a malformed DHT can otherwise admit codes below
    // mincode[l], indexing values[] at a negative offset
    if (t.maxcode[l] >= 0 && code >= t.mincode[l] &&
        code <= t.maxcode[l]) {
      br->Drop(consumed);
      return t.values[t.valptr[l] + code - t.mincode[l]];
    }
  }
  return -1;  // invalid code
}

inline int Extend(int v, int s) {
  return (s && v < (1 << (s - 1))) ? v - (1 << s) + 1 : v;
}

// AAN per-coefficient scale factors s[k] = sqrt(2) cos(k pi/16)
// (s[0] = 1), folded into the dequant tables together with the /8
// normalization so the per-block transform needs only 5 multiplies
// per 1-D pass instead of a full 8x8 matrix product.
constexpr float kAanScale[8] = {
    1.0f, 1.387039845f, 1.306562965f, 1.175875602f,
    1.0f, 0.785694958f, 0.541196100f, 0.275899379f};

// FMA contraction is re-enabled here (the file-level -ffp-contract=off
// exists for the y4m RGB conversion's bit-exact numpy parity, which
// the IDCT does not participate in).
#pragma GCC push_options
#pragma GCC optimize("fp-contract=fast")

// One 1-D pass of the AAN inverse (Arai–Agui–Nakajima scaled IDCT):
// inputs are coefficients pre-scaled by kAanScale[u]*kAanScale[v]/8.
// Butterfly validated against the direct cosine-matrix IDCT to float
// precision (see the numpy derivation in tests/test_mjpeg.py history).
inline void AanIdct1D(const float* in, int is, float* out, int os) {
  const float x0 = in[0], x1 = in[1 * is], x2 = in[2 * is],
              x3 = in[3 * is], x4 = in[4 * is], x5 = in[5 * is],
              x6 = in[6 * is], x7 = in[7 * is];
  const float p0 = x0 + x4, p1 = x0 - x4;
  const float p2 = x2 + x6;
  const float p3 = (x2 - x6) * 1.414213562f - p2;
  const float e0 = p0 + p2, e3 = p0 - p2;
  const float e1 = p1 + p3, e2 = p1 - p3;
  const float z13 = x5 + x3, z10 = x5 - x3;
  const float z11 = x1 + x7, z12 = x1 - x7;
  const float t7 = z11 + z13;
  const float t11 = (z11 - z13) * 1.414213562f;
  const float z5 = (z10 + z12) * 1.847759065f;
  const float t10 = 1.082392200f * z12 - z5;
  const float t12 = -2.613125930f * z10 + z5;
  const float t6 = t12 - t7;
  const float t5 = t11 - t6;
  const float t4 = t10 + t5;
  out[0] = e0 + t7;
  out[7 * os] = e0 - t7;
  out[1 * os] = e1 + t6;
  out[6 * os] = e1 - t6;
  out[2 * os] = e2 + t5;
  out[5 * os] = e2 - t5;
  out[4 * os] = e3 + t4;
  out[3 * os] = e3 - t4;
}

// row_mask: bit v set when coefficient row v has any nonzero entry —
// zero rows produce zero intermediate rows and skip their pass-1
// butterfly (most blocks at typical qualities populate only the
// first few rows).
void Idct8x8(const float* blk, int row_mask, unsigned char* out,
             int out_stride) {
  float tmp[64];
  for (int v = 0; v < 8; ++v) {
    if (!(row_mask & (1 << v))) {
      std::memset(tmp + v * 8, 0, 8 * sizeof(float));
      continue;
    }
    AanIdct1D(blk + v * 8, 1, tmp + v * 8, 1);
  }
  float cols[64];  // cols[y][x]
  for (int x = 0; x < 8; ++x)
    AanIdct1D(tmp + x, 8, cols + x, 8);
  for (int y = 0; y < 8; ++y) {
    unsigned char* orow = out + y * out_stride;
    const float* arow = cols + y * 8;
    for (int x = 0; x < 8; ++x) {
      const float px = arow[x] + 128.0f;
      orow[x] = ClipByte(px < 0.f ? 0.f : (px + 0.5f));  // round half up
    }
  }
}
#pragma GCC pop_options

struct JpegComponent {
  int id = 0, h = 1, v = 1, tq = 0, td = 0, ta = 0;
  int plane_w = 0, plane_h = 0;  // MCU-padded
  std::vector<unsigned char> plane;
};

// DCT-coefficient decode mode (pixel_path "dct", rnb_tpu/ops/dct.py):
// the entropy decode stops at dequantized zigzag coefficients — no
// Idct8x8, no pixel planes, the per-pixel host work this mode exists
// to delete. Blocks land plane-major (Y raster, then U, then V) so
// the packed wire stream is container-order independent of the MCU
// interleave.
struct CoeffSink {
  std::vector<short> dense;  // nb x 64, zigzag order within a block
  std::vector<int> last;     // highest zigzag index written per block
  int nb = 0;
  int blocks_w_y = 0;        // luma blocks per row
  int ny = 0;                // luma block count
  int nc = 0;                // per-chroma-plane block count

  void Reset(int w, int h) {
    blocks_w_y = w / 8;
    ny = (h / 8) * blocks_w_y;
    nc = (h / 16) * (w / 16);
    nb = ny + 2 * nc;
    dense.assign(static_cast<size_t>(nb) * 64, 0);
    last.assign(nb, 0);
  }
};

inline short ClampCoeff(float v) {
  if (v < -32768.f) v = -32768.f;
  if (v > 32767.f) v = 32767.f;
  return static_cast<short>(v);
}

// Pack one decoded frame's coefficients into the wire row layout
// (rnb_tpu/ops/dct.py): per-block nonzero counts, then values, then
// zigzag positions, padded with zeros to `capacity` entries each.
// kErrBudget when the frame's spectrum does not fit — truncating it
// would silently change pixels, so the caller surfaces a classified
// error instead.
int PackCoeffFrame(const CoeffSink& sink, int capacity, short* out) {
  const int nb = sink.nb;
  std::memset(out, 0,
              sizeof(short) * (static_cast<size_t>(nb) + 2 * capacity));
  int cursor = 0;
  for (int b = 0; b < nb; ++b) {
    const short* drow = sink.dense.data() + static_cast<size_t>(b) * 64;
    int cnt = 0;
    for (int k = 0; k <= sink.last[b]; ++k) {
      if (!drow[k]) continue;
      if (cursor >= capacity) return kErrBudget;
      out[nb + cursor] = drow[k];
      out[nb + capacity + cursor] = static_cast<short>(k);
      ++cursor;
      ++cnt;
    }
    out[b] = static_cast<short>(cnt);
  }
  return 0;
}

// Decode one baseline JPEG into planar samples at source geometry.
// On success fills width/height/subsample and the payload vector in
// y4m plane order (Y, then Cb, Cr at w/sub x h/sub).
// With `sink` non-null the decode STOPS at entropy-decoded,
// dequantized zigzag coefficients (plain integer dequant, no AAN
// scale fold, no IDCT, no pixel planes) — the pixel_path "dct" cut
// point; payload is untouched and 4:2:0 whole-MCU geometry is
// required.
int DecodeJpegFrame(const unsigned char* data, size_t n, int* width,
                    int* height, int* subsample,
                    std::vector<unsigned char>* payload,
                    CoeffSink* sink = nullptr) {
  if (n < 4 || data[0] != 0xFF || data[1] != 0xD8) return kErrFormat;
  unsigned short qt[4][64];
  bool qt_ok[4] = {false, false, false, false};
  HuffTable hdc[4], hac[4];
  JpegComponent comps[3];
  int ncomp = 0, w = 0, h = 0, restart_interval = 0;
  size_t p = 2;
  bool sos = false;
  size_t scan_start = 0;
  while (!sos) {
    // find the next marker (skip fill bytes)
    while (p < n && data[p] != 0xFF) ++p;
    while (p < n && data[p] == 0xFF) ++p;
    if (p >= n) return kErrFormat;
    const unsigned char m = data[p];
    ++p;
    if (m == 0xD9) return kErrFormat;  // EOI before SOS
    if (m >= 0xD0 && m <= 0xD7) continue;  // stray RST
    if (p + 2 > n) return kErrFormat;
    const size_t seg_len = (data[p] << 8) | data[p + 1];
    if (seg_len < 2 || p + seg_len > n) return kErrFormat;
    const unsigned char* seg = data + p + 2;
    const size_t seg_n = seg_len - 2;
    switch (m) {
      case 0xDB: {  // DQT: one or more tables
        size_t q = 0;
        while (q < seg_n) {
          const int pq = seg[q] >> 4, tq_id = seg[q] & 15;
          ++q;
          if (tq_id > 3) return kErrFormat;
          const size_t need = pq ? 128 : 64;
          if (q + need > seg_n) return kErrFormat;
          for (int k = 0; k < 64; ++k)
            qt[tq_id][k] = pq ? ((seg[q + 2 * k] << 8) | seg[q + 2 * k + 1])
                              : seg[q + k];
          qt_ok[tq_id] = true;
          q += need;
        }
        break;
      }
      case 0xC4: {  // DHT: one or more tables
        size_t q = 0;
        while (q + 17 <= seg_n) {
          const int tc = seg[q] >> 4, th = seg[q] & 15;
          if (th > 3 || tc > 1) return kErrFormat;
          int nvals = 0;
          for (int i = 0; i < 16; ++i) nvals += seg[q + 1 + i];
          if (q + 17 + nvals > seg_n || nvals > 256) return kErrFormat;
          (tc ? hac[th] : hdc[th]).Build(seg + q + 1, seg + q + 17,
                                         nvals);
          q += 17 + nvals;
        }
        break;
      }
      case 0xC0:
      case 0xC1: {  // baseline / extended-sequential Huffman SOF
        if (seg_n < 6 || seg[0] != 8) return kErrFormat;  // 8-bit only
        h = (seg[1] << 8) | seg[2];
        w = (seg[3] << 8) | seg[4];
        ncomp = seg[5];
        if (w <= 0 || h <= 0 || ncomp != 3) return kErrColorspace;
        if (seg_n < 6 + static_cast<size_t>(ncomp) * 3) return kErrFormat;
        for (int c = 0; c < ncomp; ++c) {
          comps[c].id = seg[6 + c * 3];
          comps[c].h = seg[7 + c * 3] >> 4;
          comps[c].v = seg[7 + c * 3] & 15;
          comps[c].tq = seg[8 + c * 3];
          // Tq indexes qt[4]/fq[4]: an unvalidated byte here would be
          // an out-of-bounds indexed WRITE when fq is built
          if (comps[c].tq > 3) return kErrFormat;
        }
        break;
      }
      case 0xC2:
        return kErrColorspace;  // progressive unsupported
      case 0xDD: {  // DRI
        if (seg_n < 2) return kErrFormat;
        restart_interval = (seg[0] << 8) | seg[1];
        break;
      }
      case 0xDA: {  // SOS
        if (seg_n < 1) return kErrFormat;
        const int ns = seg[0];
        if (ns != ncomp || seg_n < 1 + static_cast<size_t>(ns) * 2 + 3)
          return kErrFormat;
        for (int s = 0; s < ns; ++s) {
          const int cs = seg[1 + s * 2];
          const int td = seg[2 + s * 2] >> 4;
          const int ta = seg[2 + s * 2] & 15;
          // Td/Ta index hdc[4]/hac[4]
          if (td > 3 || ta > 3) return kErrFormat;
          for (int c = 0; c < ncomp; ++c)
            if (comps[c].id == cs) {
              comps[c].td = td;
              comps[c].ta = ta;
            }
        }
        sos = true;
        scan_start = p + seg_len;
        break;
      }
      default:
        break;  // APPn / COM / anything else: skip
    }
    p += seg_len;
  }
  if (w <= 0 || h <= 0) return kErrFormat;
  // sampling: 4:2:0 = (2,2)(1,1)(1,1); 4:4:4 = all (1,1)
  int sub;
  if (comps[0].h == 2 && comps[0].v == 2 && comps[1].h == 1 &&
      comps[1].v == 1 && comps[2].h == 1 && comps[2].v == 1) {
    sub = 2;
    if (w % 2 || h % 2) return kErrColorspace;  // match y4m 4:2:0
  } else if (comps[0].h == 1 && comps[0].v == 1 && comps[1].h == 1 &&
             comps[1].v == 1 && comps[2].h == 1 && comps[2].v == 1) {
    sub = 1;
  } else {
    return kErrColorspace;
  }
  if (sink != nullptr) {
    // the coefficient wire format is 4:2:0 whole-MCU only: no resize
    // exists in the coefficient domain, so partial edge blocks would
    // ship spectrum for pixels the consumer never shows
    if (sub != 2) return kErrColorspace;
    if (w % 16 || h % 16) return kErrColorspace;
    sink->Reset(w, h);
  }
  const int maxh = comps[0].h, maxv = comps[0].v;
  const int mcus_x = (w + 8 * maxh - 1) / (8 * maxh);
  const int mcus_y = (h + 8 * maxv - 1) / (8 * maxv);
  for (int c = 0; c < ncomp; ++c) {
    if (!qt_ok[comps[c].tq] || !hdc[comps[c].td].present ||
        !hac[comps[c].ta].present)
      return kErrFormat;
    if (sink != nullptr) continue;  // no pixel planes in coeff mode
    comps[c].plane_w = mcus_x * comps[c].h * 8;
    comps[c].plane_h = mcus_y * comps[c].v * 8;
    comps[c].plane.assign(
        static_cast<size_t>(comps[c].plane_w) * comps[c].plane_h, 0);
  }
  // dequant tables, indexed in zigzag scan order like the raw tables;
  // pixel mode folds in the AAN scale factors and /8 normalization,
  // coefficient mode keeps the RAW quantizer (plain integer dequant —
  // the values are exact small integers in float). Built AFTER the
  // qt_ok validation so an undefined table never feeds the fold.
  float fq[4][64];
  for (int c = 0; c < ncomp; ++c) {
    const int tq_id = comps[c].tq;
    for (int k = 0; k < 64; ++k) {
      const int nat = kZigzag[k];
      fq[tq_id][k] = sink != nullptr
                         ? static_cast<float>(qt[tq_id][k])
                         : static_cast<float>(qt[tq_id][k]) *
                               kAanScale[nat >> 3] * kAanScale[nat & 7] /
                               8.0f;
    }
  }
  BitReader br(data + scan_start, n - scan_start);
  int dc_pred[3] = {0, 0, 0};
  float blk[64];
  int mcus_until_restart = restart_interval;
  for (int my = 0; my < mcus_y; ++my) {
    for (int mx = 0; mx < mcus_x; ++mx) {
      if (restart_interval && mcus_until_restart == 0) {
        if (!br.ConsumeRestart()) return kErrFormat;
        dc_pred[0] = dc_pred[1] = dc_pred[2] = 0;
        mcus_until_restart = restart_interval;
      }
      if (restart_interval) --mcus_until_restart;
      for (int c = 0; c < ncomp; ++c) {
        JpegComponent& comp = comps[c];
        const float* q = fq[comp.tq];
        for (int by = 0; by < comp.v; ++by) {
          for (int bx = 0; bx < comp.h; ++bx) {
            // entropy-decode one block
            const int t = HuffDecode(&br, hdc[comp.td]);
            if (t < 0 || t > 11) return kErrFormat;
            const int diff = Extend(br.GetBits(t), t);
            dc_pred[c] += diff;
            std::memset(blk, 0, sizeof(blk));
            blk[0] = static_cast<float>(dc_pred[c]) * q[0];
            int k = 1, row_mask = 1, last_k = 0;
            bool ac_any = false;
            const HuffTable& act = hac[comp.ta];
            while (k < 64) {
              // fused lookahead: symbol AND its value bits from one
              // 24-bit peek when the 8-bit LUT hits (libjpeg-turbo's
              // arrangement); falls back to the generic path otherwise
              int rs;
              const int look = br.Peek(24);
              const unsigned short hit = act.lut[look >> 16];
              if (hit) {
                const int hlen = hit >> 8;
                rs = hit & 0xFF;
                const int s_ = rs & 15;
                if (s_) {
                  const int r_ = rs >> 4;
                  k += r_;
                  if (k > 63) return kErrFormat;
                  const int vraw =
                      (look >> (24 - hlen - s_)) & ((1 << s_) - 1);
                  br.Drop(hlen + s_);
                  const int nat = kZigzag[k];
                  blk[nat] =
                      static_cast<float>(Extend(vraw, s_)) * q[k];
                  row_mask |= 1 << (nat >> 3);
                  ac_any = true;
                  last_k = k;
                  ++k;
                  continue;
                }
                br.Drop(hlen);
              } else {
                rs = HuffDecode(&br, act);
                if (rs < 0) return kErrFormat;
                const int s_ = rs & 15;
                if (s_) {
                  k += rs >> 4;
                  if (k > 63) return kErrFormat;
                  const int nat = kZigzag[k];
                  blk[nat] = static_cast<float>(
                      Extend(br.GetBits(s_), s_)) * q[k];
                  row_mask |= 1 << (nat >> 3);
                  ac_any = true;
                  last_k = k;
                  ++k;
                  continue;
                }
              }
              if ((rs >> 4) == 15) {
                k += 16;  // ZRL
                continue;
              }
              break;  // EOB
            }
            if (sink != nullptr) {
              // coefficient mode: the block's dequantized zigzag
              // prefix IS the output — blk holds exact integers
              // (raw value x raw quantizer) in natural order
              const int bidx =
                  c == 0 ? (my * comp.v + by) * sink->blocks_w_y +
                               (mx * comp.h + bx)
                         : sink->ny + (c - 1) * sink->nc +
                               my * mcus_x + mx;
              short* drow =
                  sink->dense.data() + static_cast<size_t>(bidx) * 64;
              for (int k2 = 0; k2 <= last_k; ++k2)
                drow[k2] = ClampCoeff(blk[kZigzag[k2]]);
              sink->last[bidx] = last_k;
              continue;
            }
            const int px = (mx * comp.h + bx) * 8;
            const int py = (my * comp.v + by) * 8;
            unsigned char* dst8 =
                comp.plane.data() +
                static_cast<size_t>(py) * comp.plane_w + px;
            if (!ac_any) {
              // DC-only block: the IDCT collapses to a flat fill
              // the folded dequant already carries the /8
              const float px0 = blk[0] + 128.0f;
              const unsigned char flat =
                  ClipByte(px0 < 0.f ? 0.f : (px0 + 0.5f));
              for (int ry = 0; ry < 8; ++ry)
                std::memset(dst8 + static_cast<size_t>(ry) * comp.plane_w,
                            flat, 8);
            } else {
              Idct8x8(blk, row_mask, dst8, comp.plane_w);
            }
          }
        }
      }
    }
  }
  if (sink != nullptr) {
    // coefficient mode: no pixel payload to crop
    *width = w;
    *height = h;
    *subsample = sub;
    return 0;
  }
  // crop the MCU-padded planes into the packed y4m payload layout
  const int cw = w / sub, chh = h / sub;
  payload->resize(static_cast<size_t>(w) * h +
                  2 * static_cast<size_t>(cw) * chh);
  unsigned char* dst = payload->data();
  for (int r = 0; r < h; ++r)
    std::memcpy(dst + static_cast<size_t>(r) * w,
                comps[0].plane.data() +
                    static_cast<size_t>(r) * comps[0].plane_w,
                w);
  dst += static_cast<size_t>(w) * h;
  for (int c = 1; c < 3; ++c) {
    for (int r = 0; r < chh; ++r)
      std::memcpy(dst + static_cast<size_t>(r) * cw,
                  comps[c].plane.data() +
                      static_cast<size_t>(r) * comps[c].plane_w,
                  cw);
    dst += static_cast<size_t>(cw) * chh;
  }
  *width = w;
  *height = h;
  *subsample = sub;
  return 0;
}

// ---------------------------------------------------------------------------
// MJPEG container: concatenated baseline JPEGs. Frame boundaries are
// found by walking the marker structure: length-prefixed segments are
// skipped whole (an APPn/EXIF payload may legally contain FF D9 — a
// thumbnail's EOI — so a raw byte scan would split mid-frame), and
// only inside entropy-coded data (where every 0xFF is 0x00-stuffed or
// an RST) is FF D9 unambiguous.

// -> offset one past this frame's EOI, or 0 when the frame structure
// is corrupt/truncated. d[p..] must start at an SOI.
size_t JpegFrameEnd(const unsigned char* d, size_t n, size_t p) {
  p += 2;  // SOI
  while (p + 1 < n) {
    if (d[p] != 0xFF) return 0;
    while (p < n && d[p] == 0xFF) ++p;  // fill bytes
    if (p >= n) return 0;
    const unsigned char m = d[p++];
    if (m == 0xD9) return p;  // EOI
    if (m == 0x01 || (m >= 0xD0 && m <= 0xD7)) continue;  // TEM/RSTn
    if (p + 2 > n) return 0;
    const size_t len = (static_cast<size_t>(d[p]) << 8) | d[p + 1];
    if (len < 2 || p + len > n) return 0;
    const bool is_sos = (m == 0xDA);
    p += len;
    if (is_sos) {
      // entropy-coded data: advance to the next real marker
      while (p + 1 < n) {
        if (d[p] != 0xFF) {
          ++p;
        } else if (d[p + 1] == 0x00 ||
                   (d[p + 1] >= 0xD0 && d[p + 1] <= 0xD7)) {
          p += 2;  // stuffing / restart
        } else if (d[p + 1] == 0xFF) {
          ++p;  // fill byte
        } else {
          break;  // real marker: handled by the loop top
        }
      }
      if (p + 1 >= n) return 0;
    }
  }
  return 0;
}

struct MjpegIndex {
  int width = 0, height = 0, subsample = 1;
  std::vector<long long> offsets;  // frame start (SOI)
  std::vector<long long> lengths;  // through EOI
  long long file_size = 0;
  long long mtime_ns = 0;
};

int ScanMjpeg(const char* path, MjpegIndex* idx) {
  FILE* f = fopen(path, "rb");
  if (!f) return kErrIo;
  if (fseeko(f, 0, SEEK_END) != 0) {
    fclose(f);
    return kErrIo;
  }
  const long long size = ftello(f);
  std::vector<unsigned char> data(static_cast<size_t>(size));
  if (fseeko(f, 0, SEEK_SET) != 0 ||
      fread(data.data(), 1, data.size(), f) != data.size()) {
    fclose(f);
    return kErrIo;
  }
  fclose(f);
  idx->offsets.clear();
  idx->lengths.clear();
  size_t p = 0;
  const size_t n = data.size();
  while (p + 3 < n) {
    if (data[p] == 0xFF && data[p + 1] == 0xD8 && data[p + 2] == 0xFF) {
      const size_t end = JpegFrameEnd(data.data(), n, p);
      if (!end) break;  // truncated trailing frame: drop it
      idx->offsets.push_back(static_cast<long long>(p));
      idx->lengths.push_back(static_cast<long long>(end - p));
      p = end;
    } else {
      ++p;
    }
  }
  if (idx->offsets.empty()) return kErrFormat;
  // geometry from the first frame (MJPEG semantics: constant geometry)
  int w, h, sub;
  std::vector<unsigned char> payload;
  const int rc = DecodeJpegFrame(
      data.data() + idx->offsets[0],
      static_cast<size_t>(idx->lengths[0]), &w, &h, &sub, &payload);
  if (rc != 0) return rc;
  idx->width = w;
  idx->height = h;
  idx->subsample = sub;
  idx->file_size = size;
  return 0;
}

// index cache: rescanning a multi-MB file per decode call would cost
// more than the decode of a short clip list. Entries are validated by
// (size, mtime) so an in-place regeneration of the file — even to the
// same byte count — invalidates the cached frame offsets.
std::mutex g_mjpeg_mu;
std::map<std::string, MjpegIndex> g_mjpeg_cache;

int StatFile(const char* path, long long* size, long long* mtime_ns) {
  struct stat st;
  if (stat(path, &st) != 0) return kErrIo;
  *size = static_cast<long long>(st.st_size);
  *mtime_ns = static_cast<long long>(st.st_mtim.tv_sec) * 1000000000ll +
              st.st_mtim.tv_nsec;
  return 0;
}

int GetMjpegIndex(const char* path, MjpegIndex* out) {
  long long size, mtime_ns;
  int rc = StatFile(path, &size, &mtime_ns);
  if (rc != 0) return rc;
  {
    std::lock_guard<std::mutex> lk(g_mjpeg_mu);
    auto it = g_mjpeg_cache.find(path);
    if (it != g_mjpeg_cache.end() && it->second.file_size == size &&
        it->second.mtime_ns == mtime_ns) {
      *out = it->second;
      return 0;
    }
  }
  MjpegIndex idx;
  rc = ScanMjpeg(path, &idx);
  if (rc != 0) return rc;
  idx.mtime_ns = mtime_ns;
  {
    std::lock_guard<std::mutex> lk(g_mjpeg_mu);
    g_mjpeg_cache[path] = idx;
  }
  *out = idx;
  return 0;
}

// 0 = y4m, 1 = mjpeg, <0 = error
int SniffContainer(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return kErrIo;
  unsigned char magic[9] = {0};
  const size_t got = fread(magic, 1, sizeof(magic), f);
  fclose(f);
  if (got >= 9 && std::memcmp(magic, "YUV4MPEG2", 9) == 0) return 0;
  if (got >= 3 && magic[0] == 0xFF && magic[1] == 0xD8 &&
      magic[2] == 0xFF)
    return 1;
  return kErrFormat;
}

int DecodeClipsMjpeg(const char* path, const long long* clip_starts,
                     int num_clips, int consecutive, int out_w,
                     int out_h, unsigned char* out, int pixfmt,
                     int dct_capacity);

// Convert one source frame payload into the caller's RGB output tile,
// fusing nearest chroma upsample + box resize (out[r][c] samples
// source pixel (r*h/out_h, c*w/out_w) — the numpy backend's index map).
// The column index maps are loop-invariant across rows (and frames of
// the same geometry), so they are hoisted: the hot loop was paying a
// 64-bit division per output pixel, which dominated decode on the
// 1-core benchmark host.
void ConvertFrame(const unsigned char* payload, const Y4mMeta& m,
                  int out_w, int out_h, unsigned char* out,
                  std::vector<int>* col_map_storage) {
  const int w = m.width, h = m.height, sub = m.subsample;
  const int cw = w / sub;
  const unsigned char* yp = payload;
  const unsigned char* up = payload + static_cast<long long>(w) * h;
  const unsigned char* vp = up + static_cast<long long>(cw) * (h / sub);
  // [0..out_w) luma column, [out_w..2*out_w) chroma column, then the
  // 3-entry cache key (w, sub, out_w) — the map depends on all three,
  // so geometry changes between calls rebuild instead of silently
  // reusing stale indices
  std::vector<int>& cols = *col_map_storage;
  if (cols.size() != static_cast<size_t>(out_w) * 2 + 3 ||
      cols[out_w * 2] != w || cols[out_w * 2 + 1] != sub ||
      cols[out_w * 2 + 2] != out_w) {
    cols.resize(static_cast<size_t>(out_w) * 2 + 3);
    for (int c = 0; c < out_w; ++c) {
      const int sx = static_cast<int>(
          static_cast<long long>(c) * w / out_w);
      cols[c] = sx;
      cols[out_w + c] = sx / sub;
    }
    cols[out_w * 2] = w;
    cols[out_w * 2 + 1] = sub;
    cols[out_w * 2 + 2] = out_w;
  }
  const int* lcol = cols.data();
  const int* ccol = cols.data() + out_w;
  // chroma contributions depend only on the 8-bit sample: precompute
  // the four products once (bit-identical to the inline multiplies,
  // and the additions keep the numpy backend's left-to-right order so
  // the two backends stay bit-exact)
  static const struct ChromaLut {
    float rv[256], gu[256], gv[256], bu[256];
    ChromaLut() {
      for (int i = 0; i < 256; ++i) {
        const float f = static_cast<float>(i) - 128.0f;
        rv[i] = 1.402f * f;
        gu[i] = -0.344136f * f;
        gv[i] = -0.714136f * f;
        bu[i] = 1.772f * f;
      }
    }
  } lut;
  for (int r = 0; r < out_h; ++r) {
    const int sy = static_cast<int>(
        static_cast<long long>(r) * h / out_h);
    const unsigned char* yrow = yp + static_cast<long long>(sy) * w;
    const unsigned char* urow = up + static_cast<long long>(sy / sub) * cw;
    const unsigned char* vrow = vp + static_cast<long long>(sy / sub) * cw;
    unsigned char* orow = out + static_cast<long long>(r) * out_w * 3;
    for (int c = 0; c < out_w; ++c) {
      const float yf = static_cast<float>(yrow[lcol[c]]);
      const unsigned char u = urow[ccol[c]];
      const unsigned char v = vrow[ccol[c]];
      orow[c * 3 + 0] = ClipByte(yf + lut.rv[v]);
      orow[c * 3 + 1] = ClipByte((yf + lut.gu[u]) + lut.gv[v]);
      orow[c * 3 + 2] = ClipByte(yf + lut.bu[u]);
    }
  }
}

// Gather one source frame into packed output-resolution 4:2:0 planes:
// Y (out_h x out_w) then U, V (out_h/2 x out_w/2 each), concatenated.
// No float math happens on the host in this pixel path — chroma
// upsample + BT.601 conversion run on the accelerator, fused into the
// ingest preprocess (rnb_tpu/ops/yuv.py). Luma uses the same
// nearest-neighbour index map as ConvertFrame (bit-exact with the RGB
// path); chroma keeps its own nearest map at half output resolution,
// the standard 4:2:0 semantics.
void GatherFrameYUV(const unsigned char* payload, const Y4mMeta& m,
                    int out_w, int out_h, unsigned char* out,
                    std::vector<int>* col_map_storage) {
  const int w = m.width, h = m.height, sub = m.subsample;
  const int cw = w / sub, ch = h / sub;
  const int half_w = out_w / 2, half_h = out_h / 2;
  const unsigned char* yp = payload;
  const unsigned char* up = payload + static_cast<long long>(w) * h;
  const unsigned char* vp = up + static_cast<long long>(cw) * ch;
  // [0..out_w) luma column map, [out_w..out_w+half_w) chroma column
  // map (against the source chroma plane), then the cache key — one
  // extra sentinel vs the RGB path's key so the two layouts can never
  // alias in a shared storage vector
  std::vector<int>& cols = *col_map_storage;
  const size_t want = static_cast<size_t>(out_w) + half_w + 4;
  if (cols.size() != want || cols[out_w + half_w] != w ||
      cols[out_w + half_w + 1] != sub ||
      cols[out_w + half_w + 2] != out_w ||
      cols[out_w + half_w + 3] != -2) {
    cols.resize(want);
    for (int c = 0; c < out_w; ++c)
      cols[c] = static_cast<int>(static_cast<long long>(c) * w / out_w);
    for (int c = 0; c < half_w; ++c)
      cols[out_w + c] =
          static_cast<int>(static_cast<long long>(c) * cw / half_w);
    cols[out_w + half_w] = w;
    cols[out_w + half_w + 1] = sub;
    cols[out_w + half_w + 2] = out_w;
    cols[out_w + half_w + 3] = -2;
  }
  const int* lcol = cols.data();
  const int* ccol = cols.data() + out_w;
  unsigned char* oy = out;
  unsigned char* ou = out + static_cast<long long>(out_h) * out_w;
  unsigned char* ov = ou + static_cast<long long>(half_h) * half_w;
  for (int r = 0; r < out_h; ++r) {
    const int sy = static_cast<int>(
        static_cast<long long>(r) * h / out_h);
    const unsigned char* yrow = yp + static_cast<long long>(sy) * w;
    unsigned char* orow = oy + static_cast<long long>(r) * out_w;
    for (int c = 0; c < out_w; ++c) orow[c] = yrow[lcol[c]];
  }
  for (int r = 0; r < half_h; ++r) {
    const int sy = static_cast<int>(
        static_cast<long long>(r) * ch / half_h);
    const unsigned char* urow = up + static_cast<long long>(sy) * cw;
    const unsigned char* vrow = vp + static_cast<long long>(sy) * cw;
    unsigned char* our = ou + static_cast<long long>(r) * half_w;
    unsigned char* ovr = ov + static_cast<long long>(r) * half_w;
    for (int c = 0; c < half_w; ++c) {
      our[c] = urow[ccol[c]];
      ovr[c] = vrow[ccol[c]];
    }
  }
}

constexpr int kPixRgb = 0;     // fused convert+resize, RGB u8 out
constexpr int kPixYuv420 = 1;  // gather-only, packed 4:2:0 planes out
constexpr int kPixDct = 2;     // dequantized coefficients, int16 rows

int DecodeClips(const char* path, const long long* clip_starts,
                int num_clips, int consecutive, int out_w, int out_h,
                unsigned char* out, int pixfmt = kPixRgb,
                int dct_capacity = 0) {
  if (num_clips < 0 || consecutive <= 0 || out_w <= 0 || out_h <= 0 ||
      out == nullptr)
    return kErrArg;
  if (pixfmt != kPixRgb && pixfmt != kPixYuv420 && pixfmt != kPixDct)
    return kErrArg;
  if (pixfmt == kPixYuv420 && (out_w % 2 != 0 || out_h % 2 != 0))
    return kErrArg;  // packed 4:2:0 needs even output geometry
  if (pixfmt == kPixDct &&
      (dct_capacity < 1 || out_w % 16 != 0 || out_h % 16 != 0))
    return kErrArg;  // coefficient rows need whole-MCU geometry
  const int container = SniffContainer(path);
  if (container < 0) return container;
  if (container == 1)
    return DecodeClipsMjpeg(path, clip_starts, num_clips, consecutive,
                            out_w, out_h, out, pixfmt, dct_capacity);
  if (pixfmt == kPixDct)
    return kErrFormat;  // uncompressed y4m carries no coefficients
  Y4mMeta m;
  int rc = ProbeFile(path, &m);
  if (rc != 0) return rc;
  FILE* f = fopen(path, "rb");
  if (!f) return kErrIo;
  std::vector<unsigned char> payload(
      static_cast<size_t>(m.frame_bytes));
  std::vector<int> col_map;  // reused across every frame of this call
  const long long frame_out =
      pixfmt == kPixYuv420
          ? static_cast<long long>(out_h) * out_w * 3 / 2
          : static_cast<long long>(out_h) * out_w * 3;
  long long last_idx = -1;
  for (int ci = 0; ci < num_clips; ++ci) {
    if (clip_starts[ci] < 0) {
      fclose(f);
      return kErrArg;  // numpy backend rejects these too
    }
    for (int fi = 0; fi < consecutive; ++fi) {
      long long idx = clip_starts[ci] + fi;
      if (idx > m.count - 1) idx = m.count - 1;  // clamp like numpy
      unsigned char* dst =
          out + (static_cast<long long>(ci) * consecutive + fi) * frame_out;
      if (idx != last_idx) {
        if (fseeko(f, m.data_start + idx * m.stride + m.marker_len,
                   SEEK_SET) != 0 ||
            fread(payload.data(), 1, payload.size(), f) !=
                payload.size()) {
          fclose(f);
          return kErrIo;
        }
        last_idx = idx;
        if (pixfmt == kPixYuv420)
          GatherFrameYUV(payload.data(), m, out_w, out_h, dst, &col_map);
        else
          ConvertFrame(payload.data(), m, out_w, out_h, dst, &col_map);
      } else {
        // consecutive repeats of the clamped last frame: copy the
        // previous converted output instead of re-decoding
        std::memcpy(dst, dst - frame_out, frame_out);
      }
    }
  }
  fclose(f);
  return 0;
}

// MJPEG leg of DecodeClips: per needed frame, Huffman+IDCT-decode the
// JPEG into a planar payload, then run the SAME fused convert/gather
// as the y4m path. Clamp-past-end and repeat-frame memcpy semantics
// are identical to the y4m leg (and the numpy backend).
int DecodeClipsMjpeg(const char* path, const long long* clip_starts,
                     int num_clips, int consecutive, int out_w,
                     int out_h, unsigned char* out, int pixfmt,
                     int dct_capacity) {
  MjpegIndex idx;
  int rc = GetMjpegIndex(path, &idx);
  if (rc != 0) return rc;
  if (pixfmt == kPixDct &&
      (idx.width != out_w || idx.height != out_h))
    // no resize exists in the coefficient domain: the caller must ask
    // for exactly the source geometry
    return kErrColorspace;
  FILE* f = fopen(path, "rb");
  if (!f) return kErrIo;
  Y4mMeta m;  // geometry carrier for the shared convert/gather stages
  m.width = idx.width;
  m.height = idx.height;
  m.subsample = idx.subsample;
  m.count = static_cast<long long>(idx.offsets.size());
  std::vector<unsigned char> compressed, payload;
  std::vector<int> col_map;
  CoeffSink sink;
  const long long frame_out =
      pixfmt == kPixDct
          ? (static_cast<long long>((out_h / 8) * (out_w / 8) +
                                    2 * (out_h / 16) * (out_w / 16)) +
             2 * dct_capacity) *
                static_cast<long long>(sizeof(short))
          : pixfmt == kPixYuv420
                ? static_cast<long long>(out_h) * out_w * 3 / 2
                : static_cast<long long>(out_h) * out_w * 3;
  long long last_idx = -1;
  for (int ci = 0; ci < num_clips; ++ci) {
    if (clip_starts[ci] < 0) {
      fclose(f);
      return kErrArg;
    }
    for (int fi = 0; fi < consecutive; ++fi) {
      long long idx_f = clip_starts[ci] + fi;
      if (idx_f > m.count - 1) idx_f = m.count - 1;
      unsigned char* dst =
          out + (static_cast<long long>(ci) * consecutive + fi) * frame_out;
      if (idx_f != last_idx) {
        compressed.resize(static_cast<size_t>(idx.lengths[idx_f]));
        if (fseeko(f, idx.offsets[idx_f], SEEK_SET) != 0 ||
            fread(compressed.data(), 1, compressed.size(), f) !=
                compressed.size()) {
          fclose(f);
          return kErrIo;
        }
        int w, h, sub;
        rc = DecodeJpegFrame(compressed.data(), compressed.size(), &w,
                             &h, &sub, &payload,
                             pixfmt == kPixDct ? &sink : nullptr);
        if (rc != 0 || w != m.width || h != m.height ||
            sub != m.subsample) {
          fclose(f);
          return rc != 0 ? rc : kErrFormat;
        }
        last_idx = idx_f;
        if (pixfmt == kPixDct) {
          rc = PackCoeffFrame(sink, dct_capacity,
                              reinterpret_cast<short*>(dst));
          if (rc != 0) {
            fclose(f);
            return rc;
          }
        } else if (pixfmt == kPixYuv420) {
          GatherFrameYUV(payload.data(), m, out_w, out_h, dst, &col_map);
        } else {
          ConvertFrame(payload.data(), m, out_w, out_h, dst, &col_map);
        }
      } else {
        std::memcpy(dst, dst - frame_out, frame_out);
      }
    }
  }
  fclose(f);
  return 0;
}

// ---------------------------------------------------------------------------
// Worker pool: submit() -> ticket, wait(ticket) -> rc.

struct Job {
  long long ticket;
  std::string path;
  std::vector<long long> starts;
  int consecutive, out_w, out_h;
  int pixfmt = kPixRgb;
  int dct_capacity = 0;  // per-frame coefficient budget (kPixDct only)
  unsigned char* out;
};

struct Pool {
  std::vector<std::thread> workers;
  std::deque<Job> jobs;
  std::map<long long, int> done;  // ticket -> rc
  std::mutex mu;
  std::condition_variable cv_job, cv_done;
  long long next_ticket = 1;
  bool stopping = false;

  explicit Pool(int n) {
    for (int i = 0; i < n; ++i)
      workers.emplace_back([this] { Run(); });
  }

  void Run() {
    for (;;) {
      Job job;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_job.wait(lk, [this] { return stopping || !jobs.empty(); });
        if (jobs.empty()) return;  // stopping
        job = std::move(jobs.front());
        jobs.pop_front();
      }
      const int rc = DecodeClips(
          job.path.c_str(), job.starts.data(),
          static_cast<int>(job.starts.size()), job.consecutive,
          job.out_w, job.out_h, job.out, job.pixfmt,
          job.dct_capacity);
      {
        std::lock_guard<std::mutex> lk(mu);
        done[job.ticket] = rc;
      }
      cv_done.notify_all();
    }
  }

  long long Submit(Job job) {
    long long t;
    {
      std::lock_guard<std::mutex> lk(mu);
      t = next_ticket++;
      job.ticket = t;
      jobs.push_back(std::move(job));
    }
    cv_job.notify_one();
    return t;
  }

  int Wait(long long ticket) {
    std::unique_lock<std::mutex> lk(mu);
    cv_done.wait(lk, [&] { return done.count(ticket) > 0; });
    const int rc = done[ticket];
    done.erase(ticket);
    return rc;
  }

  // Non-blocking: has this ticket finished? Does NOT retire it — the
  // result code stays queued for a later Wait().
  bool Peek(long long ticket) {
    std::lock_guard<std::mutex> lk(mu);
    return done.count(ticket) > 0;
  }

  ~Pool() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stopping = true;
    }
    cv_job.notify_all();
    for (auto& w : workers) w.join();
  }
};

}  // namespace

extern "C" {

int rnb_y4m_probe(const char* path, int* width, int* height,
                  long long* num_frames) {
  const int container = SniffContainer(path);
  if (container < 0) return container;
  if (container == 1) {
    MjpegIndex idx;
    const int rc = GetMjpegIndex(path, &idx);
    if (rc != 0) return rc;
    if (width) *width = idx.width;
    if (height) *height = idx.height;
    if (num_frames)
      *num_frames = static_cast<long long>(idx.offsets.size());
    return 0;
  }
  Y4mMeta m;
  const int rc = ProbeFile(path, &m);
  if (rc != 0) return rc;
  if (width) *width = m.width;
  if (height) *height = m.height;
  if (num_frames) *num_frames = m.count;
  return 0;
}

// container-agnostic alias (y4m or mjpeg; sniffed). New export so a
// stale prebuilt library (without mjpeg support) fails the symbol
// check in rnb_tpu/decode/native.py and degrades cleanly.
int rnb_video_probe(const char* path, int* width, int* height,
                    long long* num_frames) {
  return rnb_y4m_probe(path, width, height, num_frames);
}

int rnb_y4m_decode_clips(const char* path, const long long* clip_starts,
                         int num_clips, int consecutive, int out_w,
                         int out_h, unsigned char* out) {
  return DecodeClips(path, clip_starts, num_clips, consecutive, out_w,
                     out_h, out);
}

// pixfmt: 0 = RGB (fused convert+resize), 1 = packed 4:2:0 planes
// (gather-only; out gets out_h*out_w*3/2 bytes per frame).
int rnb_y4m_decode_clips_fmt(const char* path,
                             const long long* clip_starts, int num_clips,
                             int consecutive, int out_w, int out_h,
                             int pixfmt, unsigned char* out) {
  return DecodeClips(path, clip_starts, num_clips, consecutive, out_w,
                     out_h, out, pixfmt);
}

void* rnb_pool_create(int num_threads) {
  if (num_threads <= 0) num_threads = 1;
  return new Pool(num_threads);
}

void rnb_pool_destroy(void* pool) { delete static_cast<Pool*>(pool); }

long long rnb_pool_submit(void* pool, const char* path,
                          const long long* clip_starts, int num_clips,
                          int consecutive, int out_w, int out_h,
                          unsigned char* out) {
  if (!pool || num_clips < 0) return -1;
  Job job;
  job.path = path;
  job.starts.assign(clip_starts, clip_starts + num_clips);
  job.consecutive = consecutive;
  job.out_w = out_w;
  job.out_h = out_h;
  job.out = out;
  return static_cast<Pool*>(pool)->Submit(std::move(job));
}

long long rnb_pool_submit_fmt(void* pool, const char* path,
                              const long long* clip_starts,
                              int num_clips, int consecutive, int out_w,
                              int out_h, int pixfmt,
                              unsigned char* out) {
  if (!pool || num_clips < 0) return -1;
  if (pixfmt != kPixRgb && pixfmt != kPixYuv420) return -1;
  Job job;
  job.path = path;
  job.starts.assign(clip_starts, clip_starts + num_clips);
  job.consecutive = consecutive;
  job.out_w = out_w;
  job.out_h = out_h;
  job.pixfmt = pixfmt;
  job.out = out;
  return static_cast<Pool*>(pool)->Submit(std::move(job));
}

// pixel_path "dct" (rnb_tpu/ops/dct.py): decode MJPEG clips stopping
// at dequantized DCT coefficients, packed into int16 wire rows of
// (num_blocks + 2 * coeff_capacity) elements per frame. out_w/out_h
// must equal the source geometry (divisible by 16, 4:2:0 only). New
// export: a stale prebuilt library fails the symbol check in
// rnb_tpu/decode/native.py and degrades cleanly.
int rnb_y4m_decode_clips_dct(const char* path,
                             const long long* clip_starts,
                             int num_clips, int consecutive, int out_w,
                             int out_h, int coeff_capacity,
                             short* out) {
  return DecodeClips(path, clip_starts, num_clips, consecutive, out_w,
                     out_h, reinterpret_cast<unsigned char*>(out),
                     kPixDct, coeff_capacity);
}

long long rnb_pool_submit_dct(void* pool, const char* path,
                              const long long* clip_starts,
                              int num_clips, int consecutive,
                              int out_w, int out_h, int coeff_capacity,
                              short* out) {
  if (!pool || num_clips < 0 || coeff_capacity < 1) return -1;
  Job job;
  job.path = path;
  job.starts.assign(clip_starts, clip_starts + num_clips);
  job.consecutive = consecutive;
  job.out_w = out_w;
  job.out_h = out_h;
  job.pixfmt = kPixDct;
  job.dct_capacity = coeff_capacity;
  job.out = reinterpret_cast<unsigned char*>(out);
  return static_cast<Pool*>(pool)->Submit(std::move(job));
}

int rnb_pool_wait(void* pool, long long ticket) {
  if (!pool || ticket <= 0) return kErrArg;
  return static_cast<Pool*>(pool)->Wait(ticket);
}

// 1 = done (result still pending retrieval via wait), 0 = in flight.
int rnb_pool_peek(void* pool, long long ticket) {
  if (!pool || ticket <= 0) return kErrArg;
  return static_cast<Pool*>(pool)->Peek(ticket) ? 1 : 0;
}

}  // extern "C"
