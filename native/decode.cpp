// Native host-side video decoder: the TPU-native stand-in for the
// reference's NVVL fork (SURVEY.md §2.2 N2; reference usage at
// models/r2p1d/model.py:123-145).  TPUs have no video ASIC, so decode
// is host CPU work; this library makes it native C++ with a worker
// pool so the decode stage keeps up with the accelerator.
//
// Format: uncompressed YUV4MPEG2 (.y4m), 4:2:0 or 4:4:4 — the format
// the pure-numpy Y4MDecoder (rnb_tpu/decode/__init__.py) also speaks;
// the two backends are numerically parity-tested against each other.
//
// Design notes:
//  * The decode of one output pixel needs exactly one Y/U/V sample
//    (nearest-neighbour chroma upsample + box-resize are both pure
//    index maps), so decode, upsample, convert and resize are fused
//    into a single gather per output pixel — unlike the numpy path,
//    the full frame is never materialized.
//  * C ABI only (consumed via ctypes; pybind11 is not available in
//    this image).  All buffers are caller-owned.
//  * The pool is a plain mutex+condvar job queue; one ticket per
//    submitted decode, waitable from any thread.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr int kErrIo = -1;        // open/seek/read failure
constexpr int kErrFormat = -2;    // not a y4m / bad header / bad marker
constexpr int kErrColorspace = -3;
constexpr int kErrArg = -4;

struct Y4mMeta {
  int width = 0;
  int height = 0;
  int subsample = 1;           // 1 = 4:4:4, 2 = 4:2:0
  long long frame_bytes = 0;
  long long data_start = 0;    // offset of first FRAME marker
  long long marker_len = 0;    // length of b"FRAME...\n" incl newline
  long long stride = 0;        // marker + payload
  long long count = 0;         // number of frames
};

// Read one '\n'-terminated line starting at `off`.  Returns false on
// IO error or if no newline is found within `maxlen` bytes.
bool ReadLine(FILE* f, long long off, std::string* line,
              size_t maxlen = 65536) {
  if (fseeko(f, off, SEEK_SET) != 0) return false;
  line->clear();
  int c;
  while (line->size() < maxlen && (c = fgetc(f)) != EOF) {
    line->push_back(static_cast<char>(c));
    if (c == '\n') return true;
  }
  return false;
}

int ProbeFile(const char* path, Y4mMeta* meta) {
  FILE* f = fopen(path, "rb");
  if (!f) return kErrIo;
  std::string header;
  if (!ReadLine(f, 0, &header) || header.rfind("YUV4MPEG2", 0) != 0) {
    fclose(f);
    return kErrFormat;
  }
  meta->width = meta->height = 0;
  std::string cs = "420";
  // tokens after the magic, space-separated, tag = first char
  size_t pos = header.find(' ');
  while (pos != std::string::npos && pos + 1 < header.size()) {
    size_t end = header.find_first_of(" \n", pos + 1);
    std::string token = header.substr(pos + 1, end - pos - 1);
    if (!token.empty()) {
      char tag = token[0];
      std::string val = token.substr(1);
      if (tag == 'W') meta->width = atoi(val.c_str());
      else if (tag == 'H') meta->height = atoi(val.c_str());
      else if (tag == 'C') cs = val;
    }
    pos = (end == std::string::npos || header[end] == '\n')
              ? std::string::npos : end;
  }
  if (meta->width <= 0 || meta->height <= 0) {
    fclose(f);
    return kErrFormat;
  }
  const long long wh =
      static_cast<long long>(meta->width) * meta->height;
  if (cs.rfind("420", 0) == 0) {
    meta->subsample = 2;
    meta->frame_bytes = wh * 3 / 2;
  } else if (cs.rfind("444", 0) == 0) {
    meta->subsample = 1;
    meta->frame_bytes = wh * 3;
  } else {
    fclose(f);
    return kErrColorspace;
  }
  meta->data_start = static_cast<long long>(header.size());
  std::string marker;
  if (!ReadLine(f, meta->data_start, &marker) ||
      marker.rfind("FRAME", 0) != 0) {
    fclose(f);
    return kErrFormat;
  }
  meta->marker_len = static_cast<long long>(marker.size());
  meta->stride = meta->marker_len + meta->frame_bytes;
  if (fseeko(f, 0, SEEK_END) != 0) {
    fclose(f);
    return kErrIo;
  }
  const long long size = ftello(f);
  fclose(f);
  meta->count = (size - meta->data_start) / meta->stride;
  if (meta->count <= 0) return kErrFormat;
  return 0;
}

inline unsigned char ClipByte(float v) {
  if (v < 0.f) v = 0.f;
  if (v > 255.f) v = 255.f;
  return static_cast<unsigned char>(v);  // trunc, matches np.astype(u8)
}

// Convert one source frame payload into the caller's RGB output tile,
// fusing nearest chroma upsample + box resize (out[r][c] samples
// source pixel (r*h/out_h, c*w/out_w) — the numpy backend's index map).
// The column index maps are loop-invariant across rows (and frames of
// the same geometry), so they are hoisted: the hot loop was paying a
// 64-bit division per output pixel, which dominated decode on the
// 1-core benchmark host.
void ConvertFrame(const unsigned char* payload, const Y4mMeta& m,
                  int out_w, int out_h, unsigned char* out,
                  std::vector<int>* col_map_storage) {
  const int w = m.width, h = m.height, sub = m.subsample;
  const int cw = w / sub;
  const unsigned char* yp = payload;
  const unsigned char* up = payload + static_cast<long long>(w) * h;
  const unsigned char* vp = up + static_cast<long long>(cw) * (h / sub);
  // [0..out_w) luma column, [out_w..2*out_w) chroma column, then the
  // 3-entry cache key (w, sub, out_w) — the map depends on all three,
  // so geometry changes between calls rebuild instead of silently
  // reusing stale indices
  std::vector<int>& cols = *col_map_storage;
  if (cols.size() != static_cast<size_t>(out_w) * 2 + 3 ||
      cols[out_w * 2] != w || cols[out_w * 2 + 1] != sub ||
      cols[out_w * 2 + 2] != out_w) {
    cols.resize(static_cast<size_t>(out_w) * 2 + 3);
    for (int c = 0; c < out_w; ++c) {
      const int sx = static_cast<int>(
          static_cast<long long>(c) * w / out_w);
      cols[c] = sx;
      cols[out_w + c] = sx / sub;
    }
    cols[out_w * 2] = w;
    cols[out_w * 2 + 1] = sub;
    cols[out_w * 2 + 2] = out_w;
  }
  const int* lcol = cols.data();
  const int* ccol = cols.data() + out_w;
  // chroma contributions depend only on the 8-bit sample: precompute
  // the four products once (bit-identical to the inline multiplies,
  // and the additions keep the numpy backend's left-to-right order so
  // the two backends stay bit-exact)
  static const struct ChromaLut {
    float rv[256], gu[256], gv[256], bu[256];
    ChromaLut() {
      for (int i = 0; i < 256; ++i) {
        const float f = static_cast<float>(i) - 128.0f;
        rv[i] = 1.402f * f;
        gu[i] = -0.344136f * f;
        gv[i] = -0.714136f * f;
        bu[i] = 1.772f * f;
      }
    }
  } lut;
  for (int r = 0; r < out_h; ++r) {
    const int sy = static_cast<int>(
        static_cast<long long>(r) * h / out_h);
    const unsigned char* yrow = yp + static_cast<long long>(sy) * w;
    const unsigned char* urow = up + static_cast<long long>(sy / sub) * cw;
    const unsigned char* vrow = vp + static_cast<long long>(sy / sub) * cw;
    unsigned char* orow = out + static_cast<long long>(r) * out_w * 3;
    for (int c = 0; c < out_w; ++c) {
      const float yf = static_cast<float>(yrow[lcol[c]]);
      const unsigned char u = urow[ccol[c]];
      const unsigned char v = vrow[ccol[c]];
      orow[c * 3 + 0] = ClipByte(yf + lut.rv[v]);
      orow[c * 3 + 1] = ClipByte((yf + lut.gu[u]) + lut.gv[v]);
      orow[c * 3 + 2] = ClipByte(yf + lut.bu[u]);
    }
  }
}

// Gather one source frame into packed output-resolution 4:2:0 planes:
// Y (out_h x out_w) then U, V (out_h/2 x out_w/2 each), concatenated.
// No float math happens on the host in this pixel path — chroma
// upsample + BT.601 conversion run on the accelerator, fused into the
// ingest preprocess (rnb_tpu/ops/yuv.py). Luma uses the same
// nearest-neighbour index map as ConvertFrame (bit-exact with the RGB
// path); chroma keeps its own nearest map at half output resolution,
// the standard 4:2:0 semantics.
void GatherFrameYUV(const unsigned char* payload, const Y4mMeta& m,
                    int out_w, int out_h, unsigned char* out,
                    std::vector<int>* col_map_storage) {
  const int w = m.width, h = m.height, sub = m.subsample;
  const int cw = w / sub, ch = h / sub;
  const int half_w = out_w / 2, half_h = out_h / 2;
  const unsigned char* yp = payload;
  const unsigned char* up = payload + static_cast<long long>(w) * h;
  const unsigned char* vp = up + static_cast<long long>(cw) * ch;
  // [0..out_w) luma column map, [out_w..out_w+half_w) chroma column
  // map (against the source chroma plane), then the cache key — one
  // extra sentinel vs the RGB path's key so the two layouts can never
  // alias in a shared storage vector
  std::vector<int>& cols = *col_map_storage;
  const size_t want = static_cast<size_t>(out_w) + half_w + 4;
  if (cols.size() != want || cols[out_w + half_w] != w ||
      cols[out_w + half_w + 1] != sub ||
      cols[out_w + half_w + 2] != out_w ||
      cols[out_w + half_w + 3] != -2) {
    cols.resize(want);
    for (int c = 0; c < out_w; ++c)
      cols[c] = static_cast<int>(static_cast<long long>(c) * w / out_w);
    for (int c = 0; c < half_w; ++c)
      cols[out_w + c] =
          static_cast<int>(static_cast<long long>(c) * cw / half_w);
    cols[out_w + half_w] = w;
    cols[out_w + half_w + 1] = sub;
    cols[out_w + half_w + 2] = out_w;
    cols[out_w + half_w + 3] = -2;
  }
  const int* lcol = cols.data();
  const int* ccol = cols.data() + out_w;
  unsigned char* oy = out;
  unsigned char* ou = out + static_cast<long long>(out_h) * out_w;
  unsigned char* ov = ou + static_cast<long long>(half_h) * half_w;
  for (int r = 0; r < out_h; ++r) {
    const int sy = static_cast<int>(
        static_cast<long long>(r) * h / out_h);
    const unsigned char* yrow = yp + static_cast<long long>(sy) * w;
    unsigned char* orow = oy + static_cast<long long>(r) * out_w;
    for (int c = 0; c < out_w; ++c) orow[c] = yrow[lcol[c]];
  }
  for (int r = 0; r < half_h; ++r) {
    const int sy = static_cast<int>(
        static_cast<long long>(r) * ch / half_h);
    const unsigned char* urow = up + static_cast<long long>(sy) * cw;
    const unsigned char* vrow = vp + static_cast<long long>(sy) * cw;
    unsigned char* our = ou + static_cast<long long>(r) * half_w;
    unsigned char* ovr = ov + static_cast<long long>(r) * half_w;
    for (int c = 0; c < half_w; ++c) {
      our[c] = urow[ccol[c]];
      ovr[c] = vrow[ccol[c]];
    }
  }
}

constexpr int kPixRgb = 0;     // fused convert+resize, RGB u8 out
constexpr int kPixYuv420 = 1;  // gather-only, packed 4:2:0 planes out

int DecodeClips(const char* path, const long long* clip_starts,
                int num_clips, int consecutive, int out_w, int out_h,
                unsigned char* out, int pixfmt = kPixRgb) {
  if (num_clips < 0 || consecutive <= 0 || out_w <= 0 || out_h <= 0 ||
      out == nullptr)
    return kErrArg;
  if (pixfmt != kPixRgb && pixfmt != kPixYuv420) return kErrArg;
  if (pixfmt == kPixYuv420 && (out_w % 2 != 0 || out_h % 2 != 0))
    return kErrArg;  // packed 4:2:0 needs even output geometry
  Y4mMeta m;
  int rc = ProbeFile(path, &m);
  if (rc != 0) return rc;
  FILE* f = fopen(path, "rb");
  if (!f) return kErrIo;
  std::vector<unsigned char> payload(
      static_cast<size_t>(m.frame_bytes));
  std::vector<int> col_map;  // reused across every frame of this call
  const long long frame_out =
      pixfmt == kPixYuv420
          ? static_cast<long long>(out_h) * out_w * 3 / 2
          : static_cast<long long>(out_h) * out_w * 3;
  long long last_idx = -1;
  for (int ci = 0; ci < num_clips; ++ci) {
    if (clip_starts[ci] < 0) {
      fclose(f);
      return kErrArg;  // numpy backend rejects these too
    }
    for (int fi = 0; fi < consecutive; ++fi) {
      long long idx = clip_starts[ci] + fi;
      if (idx > m.count - 1) idx = m.count - 1;  // clamp like numpy
      unsigned char* dst =
          out + (static_cast<long long>(ci) * consecutive + fi) * frame_out;
      if (idx != last_idx) {
        if (fseeko(f, m.data_start + idx * m.stride + m.marker_len,
                   SEEK_SET) != 0 ||
            fread(payload.data(), 1, payload.size(), f) !=
                payload.size()) {
          fclose(f);
          return kErrIo;
        }
        last_idx = idx;
        if (pixfmt == kPixYuv420)
          GatherFrameYUV(payload.data(), m, out_w, out_h, dst, &col_map);
        else
          ConvertFrame(payload.data(), m, out_w, out_h, dst, &col_map);
      } else {
        // consecutive repeats of the clamped last frame: copy the
        // previous converted output instead of re-decoding
        std::memcpy(dst, dst - frame_out, frame_out);
      }
    }
  }
  fclose(f);
  return 0;
}

// ---------------------------------------------------------------------------
// Worker pool: submit() -> ticket, wait(ticket) -> rc.

struct Job {
  long long ticket;
  std::string path;
  std::vector<long long> starts;
  int consecutive, out_w, out_h;
  int pixfmt = kPixRgb;
  unsigned char* out;
};

struct Pool {
  std::vector<std::thread> workers;
  std::deque<Job> jobs;
  std::map<long long, int> done;  // ticket -> rc
  std::mutex mu;
  std::condition_variable cv_job, cv_done;
  long long next_ticket = 1;
  bool stopping = false;

  explicit Pool(int n) {
    for (int i = 0; i < n; ++i)
      workers.emplace_back([this] { Run(); });
  }

  void Run() {
    for (;;) {
      Job job;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_job.wait(lk, [this] { return stopping || !jobs.empty(); });
        if (jobs.empty()) return;  // stopping
        job = std::move(jobs.front());
        jobs.pop_front();
      }
      const int rc = DecodeClips(
          job.path.c_str(), job.starts.data(),
          static_cast<int>(job.starts.size()), job.consecutive,
          job.out_w, job.out_h, job.out, job.pixfmt);
      {
        std::lock_guard<std::mutex> lk(mu);
        done[job.ticket] = rc;
      }
      cv_done.notify_all();
    }
  }

  long long Submit(Job job) {
    long long t;
    {
      std::lock_guard<std::mutex> lk(mu);
      t = next_ticket++;
      job.ticket = t;
      jobs.push_back(std::move(job));
    }
    cv_job.notify_one();
    return t;
  }

  int Wait(long long ticket) {
    std::unique_lock<std::mutex> lk(mu);
    cv_done.wait(lk, [&] { return done.count(ticket) > 0; });
    const int rc = done[ticket];
    done.erase(ticket);
    return rc;
  }

  // Non-blocking: has this ticket finished? Does NOT retire it — the
  // result code stays queued for a later Wait().
  bool Peek(long long ticket) {
    std::lock_guard<std::mutex> lk(mu);
    return done.count(ticket) > 0;
  }

  ~Pool() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stopping = true;
    }
    cv_job.notify_all();
    for (auto& w : workers) w.join();
  }
};

}  // namespace

extern "C" {

int rnb_y4m_probe(const char* path, int* width, int* height,
                  long long* num_frames) {
  Y4mMeta m;
  const int rc = ProbeFile(path, &m);
  if (rc != 0) return rc;
  if (width) *width = m.width;
  if (height) *height = m.height;
  if (num_frames) *num_frames = m.count;
  return 0;
}

int rnb_y4m_decode_clips(const char* path, const long long* clip_starts,
                         int num_clips, int consecutive, int out_w,
                         int out_h, unsigned char* out) {
  return DecodeClips(path, clip_starts, num_clips, consecutive, out_w,
                     out_h, out);
}

// pixfmt: 0 = RGB (fused convert+resize), 1 = packed 4:2:0 planes
// (gather-only; out gets out_h*out_w*3/2 bytes per frame).
int rnb_y4m_decode_clips_fmt(const char* path,
                             const long long* clip_starts, int num_clips,
                             int consecutive, int out_w, int out_h,
                             int pixfmt, unsigned char* out) {
  return DecodeClips(path, clip_starts, num_clips, consecutive, out_w,
                     out_h, out, pixfmt);
}

void* rnb_pool_create(int num_threads) {
  if (num_threads <= 0) num_threads = 1;
  return new Pool(num_threads);
}

void rnb_pool_destroy(void* pool) { delete static_cast<Pool*>(pool); }

long long rnb_pool_submit(void* pool, const char* path,
                          const long long* clip_starts, int num_clips,
                          int consecutive, int out_w, int out_h,
                          unsigned char* out) {
  if (!pool || num_clips < 0) return -1;
  Job job;
  job.path = path;
  job.starts.assign(clip_starts, clip_starts + num_clips);
  job.consecutive = consecutive;
  job.out_w = out_w;
  job.out_h = out_h;
  job.out = out;
  return static_cast<Pool*>(pool)->Submit(std::move(job));
}

long long rnb_pool_submit_fmt(void* pool, const char* path,
                              const long long* clip_starts,
                              int num_clips, int consecutive, int out_w,
                              int out_h, int pixfmt,
                              unsigned char* out) {
  if (!pool || num_clips < 0) return -1;
  if (pixfmt != kPixRgb && pixfmt != kPixYuv420) return -1;
  Job job;
  job.path = path;
  job.starts.assign(clip_starts, clip_starts + num_clips);
  job.consecutive = consecutive;
  job.out_w = out_w;
  job.out_h = out_h;
  job.pixfmt = pixfmt;
  job.out = out;
  return static_cast<Pool*>(pool)->Submit(std::move(job));
}

int rnb_pool_wait(void* pool, long long ticket) {
  if (!pool || ticket <= 0) return kErrArg;
  return static_cast<Pool*>(pool)->Wait(ticket);
}

// 1 = done (result still pending retrieval via wait), 0 = in flight.
int rnb_pool_peek(void* pool, long long ticket) {
  if (!pool || ticket <= 0) return kErrArg;
  return static_cast<Pool*>(pool)->Peek(ticket) ? 1 : 0;
}

}  // extern "C"
