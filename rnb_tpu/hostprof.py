"""Opt-in host hot-path micro-profiler (``RNB_HOST_PROFILE=1``).

The benchmark's MFU ceiling question is a host question: on a 1-core
bench host every Python executor thread, the decode pool and the
transfer path share one core, so "which host component eats the core"
decides whether more device throughput is even reachable. This module
gives the hot paths named wall-time sections with negligible cost when
disabled (one module-level bool test) and ~100 ns per section when
enabled, aggregated per (section, thread role).

Wall-time sections measure where threads SPEND TIME (including waits:
decode-pool wait, device wait); the companion evidence for "the host
core is saturated" is process CPU time over the measured window
(``rusage_window`` in rnb_tpu.benchmark — always on, reported as
``host_cpu_frac``). The two together separate "host busy" from "host
waiting on device/decode".

The reference had no analog — its per-process stages made the host
cost visible in nvidia-smi/top; a single-process threaded runtime
needs explicit accounting.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Tuple

#: evaluated at import; tests flip it directly
ENABLED = bool(os.environ.get("RNB_HOST_PROFILE"))

_lock = threading.Lock()
_acc: Dict[str, List[float]] = {}  # name -> [total_s, calls]


def add(name: str, dt: float) -> None:
    with _lock:
        entry = _acc.get(name)
        if entry is None:
            _acc[name] = [dt, 1]
        else:
            entry[0] += dt
            entry[1] += 1


class _NullSection:
    """Shared no-op context manager: the disabled path costs one
    function call and no allocation."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullSection()


@contextmanager
def _timed(name: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        add(name, time.perf_counter() - t0)


def section(name: str):
    if not ENABLED:
        return _NULL
    return _timed(name)


def reset() -> None:
    with _lock:
        _acc.clear()


def snapshot() -> Dict[str, Tuple[float, int]]:
    with _lock:
        return {k: (v[0], v[1]) for k, v in _acc.items()}


def totals(prefix: str) -> Tuple[float, int]:
    """Summed ``(seconds, calls)`` over sections whose name starts
    with ``prefix`` — e.g. ``totals("loader.emit")`` for the whole
    emission-assembly family, or ``totals("transfer.")`` for the
    transfer-worker thread. The staging acceptance comparison
    (executor-thread ``loader.device_put`` + emit alloc/copy share,
    RESULTS.md round 5) is a prefix sum like this."""
    with _lock:
        total_s, calls = 0.0, 0
        for name, (secs, n) in _acc.items():
            if name.startswith(prefix):
                total_s += secs
                calls += n
        return total_s, calls


def report_lines(wall_s: float) -> List[str]:
    """Human table: per-section total seconds, share of the window,
    call count and per-call mean, sorted by total."""
    snap = snapshot()
    lines = ["%-28s %9s %6s %10s %10s"
             % ("section", "total_s", "pct", "calls", "mean_us")]
    for name, (total, calls) in sorted(snap.items(),
                                       key=lambda kv: -kv[1][0]):
        lines.append("%-28s %9.3f %5.1f%% %10d %10.1f"
                     % (name, total,
                        100.0 * total / wall_s if wall_s else 0.0,
                        calls, 1e6 * total / calls if calls else 0.0))
    return lines
