"""Opt-in host hot-path micro-profiler (``RNB_HOST_PROFILE=1``).

The benchmark's MFU ceiling question is a host question: on a 1-core
bench host every Python executor thread, the decode pool and the
transfer path share one core, so "which host component eats the core"
decides whether more device throughput is even reachable. This module
gives the hot paths named wall-time sections with negligible cost when
disabled (one module-level bool test) and ~100 ns per section when
enabled, aggregated per (section, thread role).

Wall-time sections measure where threads SPEND TIME (including waits:
decode-pool wait, device wait); the companion evidence for "the host
core is saturated" is process CPU time over the measured window
(``rusage_window`` in rnb_tpu.benchmark — always on, reported as
``host_cpu_frac``). The two together separate "host busy" from "host
waiting on device/decode".

The reference had no analog — its per-process stages made the host
cost visible in nvidia-smi/top; a single-process threaded runtime
needs explicit accounting.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Tuple

#: evaluated at import; tests flip it directly
ENABLED = bool(os.environ.get("RNB_HOST_PROFILE"))

_lock = threading.Lock()
#: (name, thread_role) -> [total_s, calls]. The role is the recording
#: thread's name — stable per worker ("runner-s0-g0-i0", "client",
#: "rnb-transfer", "rnb-decode_3"), so one section shared by several
#: thread roles (loader.cache_insert from the executor AND the
#: transfer worker) splits per role instead of folding together.
_acc: Dict[Tuple[str, str], List[float]] = {}


def add(name: str, dt: float, role: str = None) -> None:
    if role is None:
        role = threading.current_thread().name
    key = (name, role)
    with _lock:
        entry = _acc.get(key)
        if entry is None:
            _acc[key] = [dt, 1]
        else:
            entry[0] += dt
            entry[1] += 1


class _NullSection:
    """Shared no-op context manager: the disabled path costs one
    function call and no allocation."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullSection()


@contextmanager
def _timed(name: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        add(name, time.perf_counter() - t0)


def section(name: str):
    if not ENABLED:
        return _NULL
    return _timed(name)


def reset() -> None:
    with _lock:
        _acc.clear()


def snapshot() -> Dict[str, Tuple[float, int]]:
    """Role-less view (the historical schema): name -> (total_s,
    calls) summed across every thread role that hit the section."""
    out: Dict[str, List[float]] = {}
    with _lock:
        for (name, _role), (secs, n) in _acc.items():
            entry = out.setdefault(name, [0.0, 0])
            entry[0] += secs
            entry[1] += n
    return {k: (v[0], v[1]) for k, v in out.items()}


def snapshot_by_role() -> Dict[Tuple[str, str], Tuple[float, int]]:
    """Full-resolution view: (name, thread_role) -> (total_s, calls)."""
    with _lock:
        return {k: (v[0], v[1]) for k, v in _acc.items()}


def totals(prefix: str, role: str = None) -> Tuple[float, int]:
    """Summed ``(seconds, calls)`` over sections whose name starts
    with ``prefix`` — e.g. ``totals("loader.emit")`` for the whole
    emission-assembly family, or ``totals("transfer.")`` for the
    transfer-worker thread. ``role`` restricts the sum to one thread
    role (exact thread name), answering "how much of this section ran
    on THAT thread" — the question the role-less sum cannot. The
    staging acceptance comparison (executor-thread ``loader.device_put``
    + emit alloc/copy share, RESULTS.md round 5) is a prefix sum like
    this."""
    with _lock:
        total_s, calls = 0.0, 0
        for (name, r), (secs, n) in _acc.items():
            if name.startswith(prefix) and (role is None or r == role):
                total_s += secs
                calls += n
        return total_s, calls


def report_lines(wall_s: float) -> List[str]:
    """Human table: per-section total seconds, share of the window,
    call count and per-call mean, sorted by total — the role-less
    default view. Sections hit from more than one thread role get a
    per-role breakdown block appended (indented ``name @role`` rows),
    so a shared section (cache_insert from the executor AND the
    transfer worker) attributes its time to the threads that spent
    it."""
    snap = snapshot()
    by_role = snapshot_by_role()
    lines = ["%-28s %9s %6s %10s %10s"
             % ("section", "total_s", "pct", "calls", "mean_us")]
    for name, (total, calls) in sorted(snap.items(),
                                       key=lambda kv: -kv[1][0]):
        lines.append("%-28s %9.3f %5.1f%% %10d %10.1f"
                     % (name, total,
                        100.0 * total / wall_s if wall_s else 0.0,
                        calls, 1e6 * total / calls if calls else 0.0))
    multi = {}
    for (name, role), (secs, n) in by_role.items():
        multi.setdefault(name, []).append((role, secs, n))
    multi = {name: rows for name, rows in multi.items()
             if len(rows) > 1}
    if multi:
        lines.append("%-28s %9s %6s %10s %10s"
                     % ("  by thread role", "total_s", "pct", "calls",
                        "mean_us"))
        for name in sorted(multi, key=lambda n: -snap[n][0]):
            for role, secs, n in sorted(multi[name],
                                        key=lambda row: -row[1]):
                lines.append("  %-26s %9.3f %5.1f%% %10d %10.1f"
                             % ("%s @%s" % (name, role), secs,
                                100.0 * secs / wall_s if wall_s else 0.0,
                                n, 1e6 * secs / n if n else 0.0))
    return lines
