"""The operator plane: a live introspection & control HTTP server.

Every observability surface PRs 6-14 built is post-hoc: artifacts land
in ``logs/<job>/`` at flush or teardown, so nothing can observe,
scrape, or steer a run *while it is serving* — exactly the seam both
open scaling items need (ROADMAP item 2's cross-host scrape/push loop,
item 5's elastic actuation). This module is that seam: a threaded
stdlib-HTTP server (one ``ThreadingHTTPServer`` on loopback, root
config key ``operator: {enabled, port, allow_actions, sample_hz}``)
serving the *existing* registries — nothing is re-measured:

* ``GET /healthz`` — machine-readable lane-health board states
  (:class:`rnb_tpu.health.LaneHealthBoard` snapshots) + the
  termination flag;
* ``GET /metrics`` — live Prometheus text exposition rendered from the
  live :class:`rnb_tpu.metrics.MetricsRegistry` (the scrape side of
  ROADMAP item 2; byte-rule-identical to the teardown
  ``metrics.prom``);
* ``GET /statusz`` — one human HTML page: pipeline topology, queue
  depths, lane states, SLO burn, memory owners, compute gauges;
* ``GET /whatif`` — the PR 14 calibrated counterfactual answered live
  from the latest metrics snapshot (query vocabulary mirrors
  :meth:`rnb_tpu.whatif.WhatIfModel.query`);
* ``GET /stacks`` — an all-thread stack dump;
* ``POST /flight`` / ``POST /capture`` — force a flight-recorder dump
  / arm a devobs capture window. Both are gated by
  ``operator.allow_actions`` (default **false**: introspection is
  always safe to expose, actuation is opt-in — a 403 is counted in
  the ``denied`` ledger, honesty over convenience).

The bound address is written to ``logs/<job>/operator.json`` at start
(``port: 0`` binds an ephemeral port — the tests' and demo's default),
and the request ledger (scrapes / actions / denied / errors) lands in
the ``Operator:`` log-meta line + ``operator_*`` BenchmarkResult
fields, cross-checked against the artifact by ``parse_utils --check``.
With the ``operator`` key absent nothing binds and every log stays
byte-identical to the pre-operator schema.
"""

from __future__ import annotations

import html
import json
import os
import sys
import threading
import time
import traceback
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

#: the loopback-only bind host: the operator plane is a per-process
#: control endpoint, not a public service — a cross-host ingest tier
#: fronts it with its own transport (ROADMAP item 2)
BIND_HOST = "127.0.0.1"

#: endpoint inventory written into operator.json (the machine-readable
#: "what can I ask this process" contract)
ENDPOINTS = ("/healthz", "/metrics", "/statusz", "/whatif", "/stacks",
             "/flight", "/capture")


class OperatorSettings:
    """Validated per-job knobs (root config key ``operator``)."""

    __slots__ = ("enabled", "port", "allow_actions", "sample_hz")

    def __init__(self, enabled: bool = True, port: int = 0,
                 allow_actions: bool = False,
                 sample_hz: Optional[float] = None):
        from rnb_tpu.stacksampler import DEFAULT_SAMPLE_HZ
        self.enabled = bool(enabled)
        self.port = int(port)
        self.allow_actions = bool(allow_actions)
        self.sample_hz = (DEFAULT_SAMPLE_HZ if sample_hz is None
                          else float(sample_hz))

    @staticmethod
    def from_config(raw: Optional[dict]) -> Optional["OperatorSettings"]:
        """Settings from the validated config dict, or None when the
        key is absent or ``enabled`` is false (operator plane fully
        off: no server, no sampler, byte-stable logs)."""
        if raw is None:
            return None
        settings = OperatorSettings(
            enabled=raw.get("enabled", True),
            port=raw.get("port", 0),
            allow_actions=raw.get("allow_actions", False),
            sample_hz=raw.get("sample_hz"))
        return settings if settings.enabled else None


def _dump_all_stacks() -> str:
    """Text dump of every live thread's stack (the ``/stacks``
    payload) — name, daemon flag, and the full frame chain."""
    names = {t.ident: t for t in threading.enumerate()
             if t.ident is not None}
    parts: List[str] = []
    for ident, frame in sorted(sys._current_frames().items()):
        t = names.get(ident)
        label = t.name if t is not None else "ident-%d" % ident
        daemon = " daemon" if t is not None and t.daemon else ""
        parts.append("== thread %r (ident %d%s)" % (label, ident,
                                                    daemon))
        parts.append("".join(traceback.format_stack(frame)).rstrip())
        parts.append("")
    return "\n".join(parts) + "\n"


def parse_whatif_query(query: str) -> Dict[str, object]:
    """``/whatif`` query string -> the WhatIfModel.query spec.

    Vocabulary (mirroring rnb_tpu.whatif exactly):
    ``replicas_step<i>=<n|+k|-k>``, ``service_scale_step<i>=<f>`` and
    ``shard_degree_step<i>=<k>`` (one per step),
    ``arrival_scale=<f>``, ``pool_rows=<n>``. Unknown keys raise
    ValueError so a typo'd probe fails loudly (400), never as a
    silently-ignored knob."""
    spec: Dict[str, object] = {}
    replicas: Dict[str, object] = {}
    service_scale: Dict[str, float] = {}
    shard_degree: Dict[str, int] = {}
    for key, values in urllib.parse.parse_qs(
            query, keep_blank_values=True).items():
        value = values[-1]
        if value != value.strip():
            # query-string decoding turns an unencoded '+' into a
            # space — silently reading '+1' as the absolute count 1
            # would answer a scale-DOWN counterfactual for a scale-up
            # question; fail loudly with the fix instead
            raise ValueError(
                "value %r for %r carries whitespace — URL-encode a "
                "relative '+N' delta as %%2BN" % (value, key))
        if key.startswith("replicas_step") \
                and key[len("replicas_step"):].isdigit():
            step_key = key[len("replicas_"):]
            if value.startswith(("+", "-")):
                replicas[step_key] = value
            else:
                replicas[step_key] = int(value)
        elif key.startswith("service_scale_step") \
                and key[len("service_scale_step"):].isdigit():
            service_scale[key[len("service_scale_"):]] = float(value)
        elif key.startswith("shard_degree_step") \
                and key[len("shard_degree_step"):].isdigit():
            degree = int(value)
            if degree < 1:
                raise ValueError(
                    "shard degree must be >= 1, got %d" % degree)
            shard_degree[key[len("shard_degree_"):]] = degree
        elif key == "arrival_scale":
            spec[key] = float(value)
        elif key == "pool_rows":
            spec[key] = int(value)
        else:
            raise ValueError(
                "unknown whatif parameter %r (known: "
                "replicas_step<i>, service_scale_step<i>, "
                "shard_degree_step<i>, arrival_scale, pool_rows)"
                % key)
    if replicas:
        spec["replicas"] = replicas
    if service_scale:
        spec["service_scale"] = service_scale
    if shard_degree:
        spec["shard_degree"] = shard_degree
    return spec


class OperatorServer:
    """Threaded loopback HTTP server over the job's live registries.

    Every provider is an object the launcher already built (metrics
    registry, health boards, devobs plane, the raw config) or a cheap
    probe callable — the server *reads*, it never measures. One
    request ledger (scrapes / actions / denied / errors) under one
    lock backs the ``Operator:`` line.
    """

    GUARDED_BY = {
        "scrapes": "_lock",
        "actions": "_lock",
        "denied": "_lock",
        "errors": "_lock",
    }

    UNGUARDED_OK = {
        "_httpd": "controller-thread lifecycle (start/stop)",
        "_thread": "controller-thread lifecycle (start/stop)",
        "port": "written once by start() before the serve thread "
                "launches; later reads see an immutable publish",
    }

    def __init__(self, settings: OperatorSettings,
                 job_dir: Optional[str] = None, job_id: str = "",
                 metrics_registry=None,
                 boards: Optional[Dict[int, object]] = None,
                 devobs_plane=None,
                 config_raw: Optional[dict] = None,
                 topology: Optional[dict] = None,
                 queue_probes: Tuple = (),
                 termination=None,
                 window: Optional[dict] = None,
                 sampler=None):
        self.settings = settings
        self.job_dir = job_dir
        self.job_id = job_id
        self.metrics_registry = metrics_registry
        self.boards = dict(boards or {})
        self.devobs_plane = devobs_plane
        self.config_raw = config_raw or {}
        self.topology = topology or {}
        #: [(name, qsize_fn, capacity)] — the same probes the metrics
        #: plane samples, passed explicitly so /statusz shows depths
        #: even on metrics-off runs
        self.queue_probes = list(queue_probes)
        self.termination = termination
        #: mutable {"t0": epoch_s | None} the launcher stamps at the
        #: start barrier — the measured-window clock /whatif and
        #: /statusz report against
        self.window = window if window is not None else {"t0": None}
        self.sampler = sampler
        self._t_started = time.time()
        self._lock = threading.Lock()
        self.scrapes = 0
        self.actions = 0
        self.denied = 0
        self.errors = 0
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((BIND_HOST,
                                           self.settings.port), handler)
        # non-daemon handler threads: server_close() (stop below) then
        # JOINS any in-flight request, so the ledger is final when
        # summary() is read — a handler cannot bump a counter after
        # the Operator: line is written. The per-request socket
        # timeout on the Handler bounds how long that join can take.
        self._httpd.daemon_threads = False
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="operator-server", daemon=True)
        self._thread.start()
        if self.job_dir is not None:
            self._write_address()

    def _write_address(self) -> None:
        record = {
            "host": BIND_HOST,
            "port": self.port,
            "url": "http://%s:%d" % (BIND_HOST, self.port),
            "pid": os.getpid(),
            "job_id": self.job_id,
            "allow_actions": self.settings.allow_actions,
            "sample_hz": self.settings.sample_hz,
            "endpoints": list(ENDPOINTS),
        }
        path = os.path.join(self.job_dir, "operator.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f, sort_keys=True, indent=2)
        os.replace(tmp, path)

    def stop(self, timeout: float = 5.0) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def summary(self) -> Dict[str, int]:
        """The ``Operator:`` log-meta line payload (and the
        ``operator_*`` BenchmarkResult fields)."""
        with self._lock:
            return {"scrapes": self.scrapes, "actions": self.actions,
                    "denied": self.denied, "errors": self.errors}

    # -- ledger -------------------------------------------------------

    def _count(self, field: str) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + 1)

    # -- payload builders (read-only over the live registries) --------

    def wall_s(self) -> float:
        t0 = self.window.get("t0")
        if t0 is None:
            return 0.0
        return max(0.0, time.time() - t0)

    def healthz_payload(self) -> Dict[str, object]:
        lanes: Dict[str, str] = {}
        for board in self.boards.values():
            snap = board.snapshot()
            for q, detail in dict(snap.get("lane_detail", {})).items():
                lanes[str(q)] = str(detail.get("state"))
        degraded = sorted(q for q, state in lanes.items()
                          if state not in ("healthy", "suspect"))
        # TerminationFlag.UNSET is -1 (still serving); 0 is the clean
        # target-reached drain; positive codes are error terminations
        flag = (int(self.termination.value)
                if self.termination is not None else -1)
        if degraded:
            status = "degraded"
        elif flag < 0:
            status = "ok"
        elif flag == 0:
            status = "draining"
        else:
            status = "terminating"
        return {
            "status": status,
            "job_id": self.job_id,
            "serving": flag < 0,
            "termination_flag": flag,
            "boards": len(self.boards),
            "lanes": lanes,
            "degraded_lanes": degraded,
            "uptime_s": round(time.time() - self._t_started, 3),
            "window_s": round(self.wall_s(), 3),
        }

    def _whatif_model(self):
        registry = self.metrics_registry
        if registry is None:
            return None
        snapshot = registry.final_snapshot()
        if snapshot is None:
            return None
        from rnb_tpu import whatif as whatif_mod
        return whatif_mod.calibrate_from_snapshot(
            snapshot,
            whatif_mod.steps_info_from_config(self.config_raw),
            wall_s=max(1e-6, self.wall_s()),
            arrival_hz=whatif_mod.arrival_hz_from_snapshot(snapshot))

    def whatif_payload(self, query: str) -> Tuple[int, Dict[str, object]]:
        try:
            spec = parse_whatif_query(query)
        except (ValueError, TypeError) as e:
            return 400, {"error": str(e)}
        model = self._whatif_model()
        if model is None:
            return 503, {"error": "whatif needs the live metrics plane "
                                  "(root 'metrics' key) and at least "
                                  "one streamed snapshot"}
        out = dict(model.query(spec or None))
        out["calibrated"] = bool(model.calibrated)
        out["stages"] = len(model.stages)
        out["spec"] = spec
        return 200, out

    def statusz_html(self) -> str:
        """The one human page, every section read from an existing
        registry and individually fault-isolated (a dying provider
        renders as its error string, never a 500)."""
        sections: List[str] = []

        def section(title: str, build: Callable[[], str]) -> None:
            try:
                body = build()
            except Exception as e:  # noqa: BLE001 - shown, not hidden
                body = "<i>unavailable: %s</i>" % html.escape(str(e))
            sections.append("<h2>%s</h2>\n%s" % (html.escape(title),
                                                 body))

        def topology() -> str:
            steps = self.topology.get("steps", [])
            if not steps:
                return "<i>no topology</i>"
            rows = "".join(
                "<tr><td>step%d</td><td>%s</td><td>%d</td><td>%d</td>"
                "<td>%s</td></tr>"
                % (s["step"], html.escape(str(s["model"])),
                   s["groups"], s["instances"],
                   html.escape(str(s["replica_lanes"] or "-")))
                for s in steps)
            return ("<table border=1 cellpadding=4><tr><th>step</th>"
                    "<th>model</th><th>groups</th><th>instances</th>"
                    "<th>replica lanes</th></tr>%s</table>" % rows)

        def queues() -> str:
            if not self.queue_probes:
                return "<i>no probes</i>"
            rows = []
            for name, fn, capacity in self.queue_probes:
                try:
                    depth = fn()
                except Exception:
                    depth = "?"
                rows.append("<tr><td>%s</td><td>%s</td><td>%s</td></tr>"
                            % (html.escape(str(name)), depth,
                               capacity if capacity else "-"))
            return ("<table border=1 cellpadding=4><tr><th>queue</th>"
                    "<th>depth</th><th>capacity</th></tr>%s</table>"
                    % "".join(rows))

        def lanes() -> str:
            payload = self.healthz_payload()
            if not payload["lanes"]:
                return ("<i>no replica lanes (health plane off or no "
                        "replicated step)</i>")
            rows = "".join(
                "<tr><td>lane %s</td><td>%s</td></tr>"
                % (html.escape(q), html.escape(state))
                for q, state in sorted(payload["lanes"].items()))
            return ("<table border=1 cellpadding=4><tr><th>lane</th>"
                    "<th>state</th></tr>%s</table>" % rows)

        def slo() -> str:
            registry = self.metrics_registry
            if registry is None:
                return "<i>metrics plane off</i>"
            snapshot = registry.final_snapshot()
            if snapshot is None:
                return "<i>no snapshot yet</i>"
            gauges = dict(snapshot.get("gauges", {}))
            counters = dict(snapshot.get("counters", {}))
            return ("goodput %.3f/s, burn %.3f; tracked %d / within "
                    "%d / missed %d (snapshot seq %s)"
                    % (gauges.get("slo.goodput_vps", 0.0),
                       gauges.get("slo.burn_rate", 0.0),
                       counters.get("slo.tracked", 0),
                       counters.get("slo.within", 0),
                       counters.get("slo.missed", 0),
                       snapshot.get("seq")))

        def memory() -> str:
            plane = self.devobs_plane
            if plane is None:
                return "<i>devobs plane off</i>"
            # peek, never sample: a GET must not update peaks or fire
            # the watermark trigger (that would be ungated actuation)
            record = plane.ledger.peek()
            if record is None:
                return "<i>no ledger sample yet</i>"
            rows = "".join(
                "<tr><td>%s</td><td>%d</td></tr>"
                % (html.escape(owner), nbytes)
                for owner, nbytes
                in sorted(dict(record["owners"]).items()))
            return ("total %d bytes (peak %d)<br>"
                    "<table border=1 cellpadding=4><tr><th>owner</th>"
                    "<th>bytes</th></tr>%s</table>"
                    % (record["total"], plane.ledger.peak_total, rows))

        def compute() -> str:
            plane = self.devobs_plane
            if plane is None:
                return "<i>devobs plane off</i>"
            rows = []
            for meter in list(plane.meters.values()):
                snap = meter.snapshot()
                rows.append(
                    "<tr><td>step%d</td><td>%d</td><td>%d</td>"
                    "<td>%.4f</td></tr>"
                    % (meter.step_idx, snap["dispatches"],
                       snap["rows"], meter.achieved_tflops()))
            if not rows:
                return "<i>no compute meters</i>"
            return ("<table border=1 cellpadding=4><tr><th>stage</th>"
                    "<th>dispatches</th><th>rows</th>"
                    "<th>tflops(busy)</th></tr>%s</table>"
                    % "".join(rows))

        def sampler() -> str:
            if self.sampler is None:
                return "<i>stack sampler off (operator.sample_hz 0)</i>"
            summary = self.sampler.summary()
            return ("%d tick(s) at %g Hz over %d role(s), %d distinct "
                    "stack(s), %d sample(s)"
                    % (summary["samples"], self.sampler.sample_hz,
                       summary["threads"], summary["folded"],
                       summary["total"]))

        section("Pipeline topology", topology)
        section("Queue depths", queues)
        section("Replica lanes", lanes)
        section("SLO", slo)
        section("Memory owners", memory)
        section("Compute", compute)
        section("Stack sampler", sampler)
        ledger = self.summary()
        return ("<!DOCTYPE html><html><head><title>rnb-tpu statusz"
                "</title></head><body><h1>rnb-tpu %s</h1>"
                "<p>measured window %.3f s; operator ledger: "
                "%d scrape(s), %d action(s), %d denied, %d error(s); "
                "actions %s</p>\n%s</body></html>"
                % (html.escape(self.job_id), self.wall_s(),
                   ledger["scrapes"], ledger["actions"],
                   ledger["denied"], ledger["errors"],
                   "enabled" if self.settings.allow_actions
                   else "disabled",
                   "\n".join(sections)))

    # -- actions ------------------------------------------------------

    def action_flight(self) -> Tuple[int, Dict[str, object]]:
        registry = self.metrics_registry
        if registry is None or registry.bridge is None \
                or registry.bridge.ring is None:
            return 503, {"error": "no flight recorder (metrics plane "
                                  "or flight_recorder disabled)"}
        from rnb_tpu.metrics import TRIGGER_FORCED
        registry.request_dump(TRIGGER_FORCED, {"via": "operator"})
        return 200, {"armed": "flight",
                     "note": "dump serviced on the next flusher tick"}

    def action_capture(self) -> Tuple[int, Dict[str, object]]:
        plane = self.devobs_plane
        if plane is None:
            return 503, {"error": "no devobs plane (root 'devobs' key "
                                  "absent)"}
        plane.request_capture("operator")
        return 200, {"armed": "capture"}


def _make_handler(server: OperatorServer):
    """The BaseHTTPRequestHandler bound to one OperatorServer (the
    stdlib handler API is class-based; the closure carries the server
    reference without touching the socketserver plumbing)."""

    class Handler(BaseHTTPRequestHandler):
        # per-request threads (ThreadingHTTPServer): keep-alive off so
        # a dangling client can never pin a handler thread at
        # shutdown, and a socket timeout so the non-daemon handler
        # join in OperatorServer.stop() is bounded even against a
        # stalled peer
        protocol_version = "HTTP/1.0"
        timeout = 10.0

        def log_message(self, fmt, *args):  # noqa: N802 (stdlib API)
            pass  # operator traffic must not spam the bench stdout

        def _send(self, code: int, content_type: str,
                  body: str) -> None:
            data = body.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _send_json(self, code: int, payload: Dict) -> None:
            self._send(code, "application/json",
                       json.dumps(payload, sort_keys=True) + "\n")

        def do_GET(self) -> None:  # noqa: N802 (stdlib API)
            parsed = urllib.parse.urlsplit(self.path)
            route = parsed.path.rstrip("/") or "/"
            try:
                self._route_get(route, parsed)
            except BrokenPipeError:
                return  # client went away mid-write: not our error
            except Exception as e:  # noqa: BLE001 - counted + shown
                server._count("errors")
                try:
                    self._send_json(500, {"error": "%s: %s"
                                          % (type(e).__name__, e)})
                except BrokenPipeError:
                    pass

        def _route_get(self, route: str, parsed) -> None:
            if route == "/healthz":
                self._send_json(200, server.healthz_payload())
            elif route == "/metrics":
                registry = server.metrics_registry
                if registry is None:
                    server._count("errors")
                    self._send(503, "text/plain",
                               "metrics plane disabled (no root "
                               "'metrics' key)\n")
                    return
                self._send(200, "text/plain; version=0.0.4",
                           registry.render_exposition())
            elif route in ("/statusz", "/"):
                self._send(200, "text/html", server.statusz_html())
            elif route == "/whatif":
                code, payload = server.whatif_payload(parsed.query)
                if code != 200:
                    server._count("errors")
                    self._send_json(code, payload)
                    return
                self._send_json(200, payload)
            elif route == "/stacks":
                self._send(200, "text/plain", _dump_all_stacks())
            else:
                server._count("errors")
                self._send_json(404, {"error": "unknown endpoint",
                                      "endpoints": list(ENDPOINTS)})
                return
            server._count("scrapes")

        def do_POST(self) -> None:  # noqa: N802 (stdlib API)
            route = urllib.parse.urlsplit(self.path).path.rstrip("/")
            if route not in ("/flight", "/capture"):
                server._count("errors")
                self._send_json(404, {"error": "unknown action",
                                      "actions": ["/flight",
                                                  "/capture"]})
                return
            if not server.settings.allow_actions:
                # the gating honesty policy: a denied action is a
                # COUNTED outcome (the Operator: line carries it), so
                # a misconfigured actuator is visible, not silent
                server._count("denied")
                self._send_json(403, {
                    "error": "actions disabled — set "
                             "operator.allow_actions true to permit "
                             "POST /flight and /capture"})
                return
            try:
                if route == "/flight":
                    code, payload = server.action_flight()
                else:
                    code, payload = server.action_capture()
            except BrokenPipeError:
                return
            if code == 200:
                server._count("actions")
            else:
                server._count("errors")
            self._send_json(code, payload)

    return Handler
