"""Calibrated queueing what-if engine: counterfactuals before builds.

The placement planner (rnb_tpu.placement) answers "how many replicas
per step" with a first-order occupancy model; the ROADMAP item-4/5
planners need more: "what would throughput and queue delay become if I
added a lane / halved a stage's service / took 1.5x the arrivals /
resized the pool" — *before* anyone builds or reruns anything. This
module calibrates a per-stage open queueing model from signals the
runtime already streams — per-stage service histograms bridged into
``metrics.jsonl`` (``exec{i}.model_call``/``device_sync``), replica
lane counts and the declared fault-plan injection from the job-dir
config copy, the arrival EWMA / completion counters — and answers
counterfactual queries against it.

Model (honesty policy documented in README "Explanation plane"):

* Per stage ``i``: ``lanes_i`` replica lanes; ``dispatches_i`` batched
  dispatches carrying ``requests / dispatches_i`` requests each;
  per-dispatch service split into a **lane-parallel** part ``p_i``
  (the config-declared fault-plan latency injection — the emulated
  device-bound service of the scale-out arms; on hardware, device
  time) and a **host-serial** part ``h_i`` (the measured remainder:
  real compute the 1-core harness serializes across every lane).
* **Throughput** (:meth:`WhatIfModel.predict_throughput`) comes from a
  deterministic event simulation: dispatches flow stage to stage,
  each claims its stage's earliest-free lane for ``p_i`` then the
  shared host resource for ``h_i``. Finite-run effects (startup ramp,
  drain tail) fall out of the simulation instead of being ignored.
* **Queue delay** (:meth:`WhatIfModel.predict_wait_ms`) uses the
  Pollaczek-Khinchine mean-wait formula per stage at the calibrated
  (or scaled) arrival rate — exact for M/G/1, the standard ``rho/L``
  approximation for multi-lane stages; a query that saturates a stage
  (``rho >= 1``) reports ``saturated`` instead of extrapolating a
  finite wait that does not exist.
* Extrapolation limits: the model is calibrated from ONE run's
  operating point; service times are treated load-independent, the
  host is one serial resource, and pool-size queries scale the
  requests-per-dispatch ratio linearly. Predictions are *checked*
  (``make explain`` validates the replica counterfactual against the
  shipped scale-out arms' measured ratio), never trusted.

Calibration sources are artifacts, so it works offline on any job dir
(:func:`calibrate_job`) and in-run at teardown (the ``Whatif:``
log-meta line, gated on the root ``whatif`` config key — absent =>
byte-stable logs). ``whatif`` requires ``metrics``: the service
histograms ARE the calibration data.
"""

from __future__ import annotations

import json
import math
import os
import re
from typing import Dict, Iterable, List, Mapping, Optional, Tuple


class WhatifSettings:
    """Validated per-job knobs (root config key ``whatif``)."""

    __slots__ = ("enabled",)

    def __init__(self, enabled: bool = True):
        self.enabled = bool(enabled)

    @staticmethod
    def from_config(raw: Optional[dict]) -> Optional["WhatifSettings"]:
        if raw is None:
            return None
        settings = WhatifSettings(enabled=raw.get("enabled", True))
        return settings if settings.enabled else None


class StageCalib:
    """One pipeline stage's calibrated queueing parameters."""

    __slots__ = ("step", "lanes", "dispatches", "service_ms",
                 "service_m2_ms2", "injected_ms", "rows_cap",
                 "collective_ms", "shard_degree")

    def __init__(self, step: int, lanes: int, dispatches: int,
                 service_ms: float, service_m2_ms2: float = 0.0,
                 injected_ms: float = 0.0,
                 rows_cap: Optional[int] = None,
                 collective_ms: float = 0.0, shard_degree: int = 1):
        self.step = int(step)
        self.lanes = max(1, int(lanes))
        self.dispatches = max(0, int(dispatches))
        #: mean per-dispatch service (model_call + device_sync), ms
        self.service_ms = float(service_ms)
        #: second moment of the per-dispatch service (ms^2) — the
        #: P-K wait formula's variance input; 0 = treat deterministic
        self.service_m2_ms2 = float(service_m2_ms2)
        #: config-declared lane-parallel injection per dispatch
        #: (expected fault-plan latency: probability x ms)
        self.injected_ms = float(injected_ms)
        #: row capacity per dispatch (ragged pool_rows), for pool
        #: queries; None = not a pooled stage
        self.rows_cap = rows_cap
        #: mean per-dispatch collective tax (ms) — the measured
        #: ``exec{i}.collective`` merge wall. It is NOT added to
        #: service_ms (the merge span nests inside model_call, so the
        #: service histograms already count it); it is the measured
        #: slice shard-degree queries rescale.
        self.collective_ms = float(collective_ms)
        #: the degree the run was calibrated at (config-declared;
        #: 1 = unsharded)
        self.shard_degree = max(1, int(shard_degree))

    @property
    def host_ms(self) -> float:
        """The host-serial service component: measured minus the
        declared lane-parallel injection, floored at 0."""
        return max(0.0, self.service_ms - self.injected_ms)


class WhatIfModel:
    """A calibrated pipeline + the counterfactual query surface."""

    def __init__(self, stages: List[StageCalib], requests: int,
                 wall_s: float, arrival_hz: Optional[float] = None):
        self.stages = sorted(stages, key=lambda s: s.step)
        self.requests = max(0, int(requests))
        self.wall_s = float(wall_s)
        #: calibrated offered arrival rate (requests/s), None for a
        #: saturated bulk run (arrivals never limited the run)
        self.arrival_hz = arrival_hz

    @property
    def calibrated(self) -> bool:
        return bool(self.stages) and self.requests > 0 \
            and all(s.dispatches > 0 for s in self.stages)

    # -- overrides ----------------------------------------------------

    def _resolved(self, overrides: Optional[Mapping] = None
                  ) -> List[Tuple[StageCalib, int, float, int]]:
        """[(stage, lanes, per-dispatch service ms, dispatches)] with
        a query's overrides applied."""
        overrides = dict(overrides or {})
        replicas = {_step_idx(k): v for k, v
                    in dict(overrides.get("replicas", {})).items()}
        scales = {_step_idx(k): float(v) for k, v
                  in dict(overrides.get("service_scale", {})).items()}
        shard = {_step_idx(k): max(1, int(v)) for k, v
                 in dict(overrides.get("shard_degree", {})).items()}
        pool_rows = overrides.get("pool_rows")
        out = []
        for stage in self.stages:
            lanes = stage.lanes
            if stage.step in replicas:
                spec = replicas[stage.step]
                if isinstance(spec, str) and spec.startswith(("+", "-")):
                    lanes = max(1, lanes + int(spec))
                else:
                    lanes = max(1, int(spec))
            service_base = stage.service_ms
            if stage.step in shard:
                # shard-degree counterfactual: rescale ONLY the
                # measured collective slice by the ring-hop factor
                # ratio g(k)/g(d0), g(k) = (k-1)/k — the compute slice
                # is degree-invariant (weight-gathered sharding divides
                # parameter residency, not FLOPs). Calibrated at
                # degree 1 there is no measured collective slice
                # (collective_ms == 0), so the model honestly predicts
                # no tax rather than inventing one it never measured —
                # validate degree-1 -> k predictions against an
                # executed arm, never trust them.
                from rnb_tpu.placement import ring_hop_factor
                g0 = ring_hop_factor(stage.shard_degree)
                if g0 > 0.0 and stage.collective_ms > 0.0:
                    gk = ring_hop_factor(shard[stage.step])
                    service_base = (stage.service_ms
                                    - stage.collective_ms
                                    + stage.collective_ms * (gk / g0))
            service = service_base * scales.get(stage.step, 1.0)
            dispatches = stage.dispatches
            if pool_rows and stage.rows_cap:
                # first-order: requests-per-dispatch scales with the
                # pool capacity, so dispatch count scales inversely
                factor = float(pool_rows) / float(stage.rows_cap)
                dispatches = max(1, int(math.ceil(
                    stage.dispatches / factor)))
            out.append((stage, lanes, service, dispatches))
        return out

    def _arrivals(self, overrides: Optional[Mapping] = None
                  ) -> Optional[List[float]]:
        """Per-request arrival epochs (seconds), or None for bulk
        (everything offered at t=0)."""
        overrides = dict(overrides or {})
        hz = self.arrival_hz
        if hz is None:
            return None
        hz *= float(overrides.get("arrival_scale", 1.0))
        if hz <= 0.0:
            return None
        return [i / hz for i in range(self.requests)]

    # -- throughput: deterministic event simulation -------------------

    def predict_throughput(self, overrides: Optional[Mapping] = None
                           ) -> Tuple[float, int]:
        """(predicted requests/s, bottleneck step) for the calibrated
        workload size under ``overrides``. The bottleneck is the stage
        with the highest lane-busy fraction over the simulated wall."""
        if not self.calibrated:
            return (0.0, -1)
        arrivals = self._arrivals(overrides)
        ready = (list(arrivals) if arrivals is not None
                 else [0.0] * self.requests)
        host_free = 0.0
        busy_s: Dict[int, float] = {}
        lanes_of: Dict[int, int] = {}
        for stage, lanes, service_ms, dispatches in \
                self._resolved(overrides):
            lanes_of[stage.step] = lanes
            p_s = min(stage.injected_ms, service_ms) / 1000.0
            h_s = max(0.0, service_ms / 1000.0 - p_s)
            lane_free = [0.0] * lanes
            done: List[float] = []
            n = self.requests
            for j in range(dispatches):
                lo = (j * n) // dispatches
                hi = ((j + 1) * n) // dispatches
                if hi <= lo:
                    continue
                dispatch_ready = max(ready[lo:hi])
                lane = min(range(lanes), key=lambda i: lane_free[i])
                start = max(dispatch_ready, lane_free[lane])
                par_done = start + p_s
                host_start = max(par_done, host_free)
                finish = host_start + h_s
                host_free = finish
                lane_free[lane] = finish
                busy_s[stage.step] = busy_s.get(stage.step, 0.0) \
                    + (p_s + h_s)
                done.extend([finish] * (hi - lo))
            ready = done if len(done) == self.requests else ready
        start_s = arrivals[0] if arrivals else 0.0
        wall = max(ready) - start_s if ready else 0.0
        if wall <= 0.0:
            return (0.0, -1)
        bottleneck = max(
            busy_s,
            key=lambda s: (busy_s[s] / lanes_of.get(s, 1), -s))
        return (self.requests / wall, bottleneck)

    # -- queue delay: Pollaczek-Khinchine per stage -------------------

    def predict_wait_ms(self, step: int,
                        overrides: Optional[Mapping] = None
                        ) -> Optional[Dict[str, float]]:
        """Predicted mean queue wait at ``step`` under ``overrides``:
        ``{"rho": utilization, "wait_ms": mean queue delay}`` — or
        ``{"rho": .., "wait_ms": inf}`` when the query saturates the
        stage (the honest answer; no finite wait exists), or None when
        no arrival rate is calibrated (bulk runs have no open-queue
        operating point to perturb)."""
        overrides = dict(overrides or {})
        hz = self.arrival_hz
        if hz is None or not self.calibrated:
            return None
        hz *= float(overrides.get("arrival_scale", 1.0))
        for stage, lanes, service_ms, dispatches in \
                self._resolved(overrides):
            if stage.step != step:
                continue
            if dispatches <= 0 or service_ms <= 0.0:
                return {"rho": 0.0, "wait_ms": 0.0}
            per_dispatch = self.requests / dispatches
            lam = hz / per_dispatch  # dispatch arrivals per second
            mu = 1000.0 / service_ms  # dispatches per lane-second
            rho = lam / (lanes * mu)
            if rho >= 1.0:
                return {"rho": rho, "wait_ms": float("inf")}
            scale = service_ms / stage.service_ms \
                if stage.service_ms > 0.0 else 1.0
            m2 = (stage.service_m2_ms2 * scale * scale
                  if stage.service_m2_ms2 > 0.0 else service_ms ** 2)
            # P-K mean wait, with the multi-lane rho/L approximation:
            # each lane sees lam/lanes of the dispatch stream
            wait_ms = (lam / lanes) / 1000.0 * m2 / (2.0 * (1.0 - rho))
            return {"rho": rho, "wait_ms": wait_ms}
        return None

    def query(self, spec: Optional[Mapping] = None) -> Dict[str, object]:
        """One counterfactual: baseline vs predicted throughput (and
        per-stage waits when an arrival rate is calibrated)."""
        base_vps, base_bottleneck = self.predict_throughput()
        pred_vps, pred_bottleneck = self.predict_throughput(spec)
        out: Dict[str, object] = {
            "base_vps": round(base_vps, 4),
            "pred_vps": round(pred_vps, 4),
            "vps_ratio": round(pred_vps / base_vps, 4)
            if base_vps > 0 else 0.0,
            "base_bottleneck_step": base_bottleneck,
            "pred_bottleneck_step": pred_bottleneck,
        }
        if self.arrival_hz is not None:
            waits = {}
            for stage in self.stages:
                before = self.predict_wait_ms(stage.step)
                after = self.predict_wait_ms(stage.step, spec)
                if before is None or after is None:
                    continue
                waits["step%d" % stage.step] = {
                    "base_wait_ms": round(before["wait_ms"], 3)
                    if math.isfinite(before["wait_ms"]) else "saturated",
                    "pred_wait_ms": round(after["wait_ms"], 3)
                    if math.isfinite(after["wait_ms"]) else "saturated",
                }
            out["waits"] = waits
        return out


def _step_idx(key) -> int:
    """'step1' / '1' / 1 -> 1."""
    if isinstance(key, int):
        return key
    text = str(key)
    return int(text[4:]) if text.startswith("step") else int(text)


# -- calibration -------------------------------------------------------

def _hist_moments(hist: Mapping[str, object],
                  bounds: List[float]) -> Tuple[float, float]:
    """(mean ms, second moment ms^2) of one fixed-log2 metrics
    histogram: the mean is exact (count/sum are carried); the second
    moment approximates each bucket at its geometric midpoint (the
    last, unbounded bucket at 2x its lower bound)."""
    count = int(hist.get("count", 0))
    if count <= 0:
        return (0.0, 0.0)
    mean = float(hist.get("sum_ms", 0.0)) / count
    m2 = 0.0
    lower = 0.0
    for bound, n in zip(bounds, list(hist.get("buckets", []))):
        if not n:
            lower = bound
            continue
        if math.isinf(bound):
            mid = lower * 2.0 if lower > 0.0 else mean
        elif lower <= 0.0:
            mid = bound / 2.0
        else:
            mid = math.sqrt(lower * bound)
        m2 += int(n) * mid * mid
        lower = bound
    return (mean, m2 / count)


def steps_info_from_config(raw: Mapping[str, object]
                           ) -> Dict[int, Dict[str, object]]:
    """{step: {lanes, injected_ms, rows_cap}} from a (job-dir copy of
    a) pipeline config dict: lane counts from the replica-expanded
    device lists, the lane-parallel injection from the declared fault
    plan (expected latency: probability x ms), row capacity from the
    ragged pool."""
    info: Dict[int, Dict[str, object]] = {}
    ragged = raw.get("ragged") if isinstance(raw, dict) else None
    pool_rows = None
    if isinstance(ragged, dict) and ragged.get("enabled", True):
        pool_rows = ragged.get("pool_rows")
    for step_idx, step in enumerate(raw.get("pipeline", [])):
        if not isinstance(step, dict):
            continue
        # 'gpus' is the schema-accepted alias for 'devices'
        # (rnb_tpu.config): count whichever key the group declares,
        # matching the parsed config's instance count exactly
        lanes = sum(len(g.get("devices") or g.get("gpus") or [])
                    for g in step.get("queue_groups", [])
                    if isinstance(g, dict)) or 1
        shard = step.get("shard")
        shard_degree = 1
        if isinstance(shard, dict):
            try:
                shard_degree = max(1, int(shard.get("degree", 1)))
            except (TypeError, ValueError):
                shard_degree = 1
            # a shard ring is one executable over degree devices, not
            # degree executors — the as-written device list counts
            # replicas x degree entries, but only replicas lanes exist
            lanes = max(1, lanes // shard_degree)
        info[step_idx] = {"lanes": lanes, "injected_ms": 0.0,
                          "rows_cap": pool_rows,
                          "shard_degree": shard_degree}
    plan = raw.get("fault_plan") if isinstance(raw, dict) else None
    faults = dict(plan or {}).get("faults", [])
    for fault in faults or []:
        if not isinstance(fault, dict) or fault.get("kind") != "latency":
            continue
        step_idx = fault.get("step")
        if step_idx in info:
            info[step_idx]["injected_ms"] += (
                float(fault.get("probability", 1.0))
                * float(fault.get("ms", 0.0)))
    return info


_SPAN_RE = re.compile(r"^exec(\d+)\.(model_call|device_sync)$")
#: the shard merge span — parsed SEPARATELY from the service spans:
#: it nests inside model_call, so adding it to sum_ms would count the
#: collective tax twice
_COLL_RE = re.compile(r"^exec(\d+)\.collective$")


def calibrate_from_snapshot(snapshot: Mapping[str, object],
                            steps_info: Mapping[int, Mapping[str, object]],
                            wall_s: float,
                            requests: Optional[int] = None,
                            arrival_hz: Optional[float] = None
                            ) -> WhatIfModel:
    """A model from one metrics snapshot (the final metrics.jsonl
    record — the same dict in-run and offline, so the ``Whatif:`` line
    is reproducible from the artifacts alone) plus the config-derived
    per-step facts. ``requests`` defaults to the snapshot's
    ``slo.tracked`` completion counter; ``arrival_hz`` defaults to
    saturated/bulk (None) — pass the client-arrival or autotune EWMA
    rate for open-queue wait predictions."""
    from rnb_tpu.metrics import hist_upper_bounds
    bounds = hist_upper_bounds()
    hists = dict(snapshot.get("histograms", {}))
    counters = dict(snapshot.get("counters", {}))
    if requests is None:
        requests = int(counters.get("slo.tracked", 0))
    per_step: Dict[int, Dict[str, object]] = {}
    coll_sum_ms: Dict[int, float] = {}
    for name, hist in hists.items():
        cm = _COLL_RE.match(str(name))
        if cm is not None:
            coll_sum_ms[int(cm.group(1))] = \
                coll_sum_ms.get(int(cm.group(1)), 0.0) \
                + float(dict(hist).get("sum_ms", 0.0))
            continue
        m = _SPAN_RE.match(str(name))
        if m is None:
            continue
        step = int(m.group(1))
        entry = per_step.setdefault(
            step, {"dispatches": 0, "sum_ms": 0.0, "m2_ms2": 0.0})
        hist = dict(hist)
        count = int(hist.get("count", 0))
        mean, m2 = _hist_moments(hist, bounds)
        if m.group(2) == "model_call":
            entry["dispatches"] = count
            # the service variance lives in the model_call span; the
            # sync span adds its mean (its variance is second-order)
            entry["m2_ms2"] = m2
        entry["sum_ms"] += float(hist.get("sum_ms", 0.0))
    stages: List[StageCalib] = []
    for step, entry in sorted(per_step.items()):
        dispatches = int(entry["dispatches"])
        if dispatches <= 0:
            continue
        service_ms = entry["sum_ms"] / dispatches
        info = dict(steps_info.get(step, {}))
        # the m2 approximation can undershoot the exact mean (coarse
        # log2 buckets); floor it at the deterministic-service moment
        m2 = max(float(entry["m2_ms2"]), service_ms ** 2)
        stages.append(StageCalib(
            step=step, lanes=int(info.get("lanes", 1) or 1),
            dispatches=dispatches, service_ms=service_ms,
            service_m2_ms2=m2,
            injected_ms=float(info.get("injected_ms", 0.0)),
            rows_cap=info.get("rows_cap"),
            collective_ms=coll_sum_ms.get(step, 0.0) / dispatches,
            shard_degree=int(info.get("shard_degree", 1) or 1)))
    return WhatIfModel(stages, requests=requests, wall_s=wall_s,
                       arrival_hz=arrival_hz)


def arrival_hz_from_snapshot(snapshot: Mapping[str, object]
                             ) -> Optional[float]:
    """The one arrival-rate rule shared by the in-run ``Whatif:``
    line and :func:`calibrate_job`, so the two calibrations can never
    diverge: the autotune controller's arrival EWMA gauge when it
    exists, else the client's windowed arrival rate (which reads 0 on
    a bulk run whose enqueue burst left the window — correctly
    yielding the saturated/bulk model)."""
    gauges = dict(snapshot.get("gauges", {}))
    rates = dict(snapshot.get("rates", {}))
    if gauges.get("autotune.arrival_hz"):
        return float(gauges["autotune.arrival_hz"])
    if rates.get("client.arrivals"):
        return float(rates["client.arrivals"]) or None
    return None


def job_config(job_dir: str) -> Optional[Dict[str, object]]:
    """The pipeline-config copy benchmark.py drops into a job dir
    (first ``*.json`` carrying a ``pipeline`` key), or None. Shared
    with ``parse_utils``'s offline critpath recompute so the two
    consumers can never disagree on which file is the config."""
    for name in sorted(os.listdir(job_dir)):
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(job_dir, name)) as f:
                raw = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(raw, dict) and "pipeline" in raw:
            return raw
    return None


def _job_wall(job_dir: str) -> float:
    try:
        with open(os.path.join(job_dir, "log-meta.txt")) as f:
            for line in f:
                parts = line.split()
                if len(parts) == 2:
                    try:
                        t0, t1 = float(parts[0]), float(parts[1])
                    except ValueError:
                        continue
                    return t1 - t0
    except OSError:
        pass
    return 0.0


def calibrate_job(job_dir: str) -> Optional[WhatIfModel]:
    """Calibrate from one job directory's artifacts alone: the final
    metrics.jsonl snapshot, the config copy, and the log-meta wall
    window — the offline twin of the in-run ``Whatif:`` line (the two
    must agree; ``parse_utils --check`` holds them to +-1 milli-vps).
    None when the job streamed no metrics (nothing to calibrate
    from)."""
    path = os.path.join(job_dir, "metrics.jsonl")
    if not os.path.isfile(path):
        return None
    last = None
    with open(path) as f:
        for line in f:
            if line.strip():
                last = line
    if last is None:
        return None
    snapshot = json.loads(last)
    raw = job_config(job_dir) or {}
    return calibrate_from_snapshot(
        snapshot, steps_info_from_config(raw), wall_s=_job_wall(job_dir),
        arrival_hz=arrival_hz_from_snapshot(snapshot))


def summary_counters(model: Optional[WhatIfModel]) -> Dict[str, int]:
    """The ``Whatif:`` log-meta line's integer payload (and the
    ``whatif_*`` BenchmarkResult fields) for one calibrated model —
    zeros/-1 when calibration found nothing to model."""
    if model is None or not model.calibrated:
        return {"stages": len(model.stages) if model else 0,
                "calibrated": 0, "pred_vps_milli": 0,
                "bottleneck_step": -1}
    vps, bottleneck = model.predict_throughput()
    return {"stages": len(model.stages), "calibrated": 1,
            "pred_vps_milli": int(round(vps * 1000.0)),
            "bottleneck_step": int(bottleneck)}
