"""Self-healing replica serving: lane health, deadlines, hedging.

PR 9 made the pipeline horizontally scaled (``replicas: N`` lanes,
least-loaded routing, device-resident handoff) but left it brittle: a
single stalled or dead replica lane silently strands its queued work,
and every already-doomed request still burns decode, transfer and TPU
time all the way to the end of the pipe. This module is the
self-healing layer on top of the PR 9 lanes, in three pieces:

* **Lane health + circuit breaking** (:class:`LaneHealthBoard`, root
  config key ``health``): per-lane state ``healthy -> suspect -> open
  -> half_open`` driven by signals the lanes already export — the
  oldest undrained item's age per lane (the InflightDepths window),
  per-lane dead-letter counts, and an explicit liveness beat the
  executor loop publishes each iteration. The upstream
  :class:`rnb_tpu.selector.ReplicaSelector` consults the board and
  stops routing to open lanes; a half-open lane recovers through a
  single probe dispatch. A *permanently* dead lane (the chaos
  ``replica_crash``/``replica_stall`` fault kinds,
  :class:`rnb_tpu.faults.LaneDeathError`) is **evicted**: its
  executor dead-letters the in-service dispatch, then drains its
  queued-but-undispatched work and re-enqueues it onto healthy
  siblings — every moved card grows a ``redispatched`` content stamp
  and the lane's in-flight counters are reconciled, so every request
  still terminates exactly once.
* **Deadline propagation + expiry shedding** (:class:`DeadlineSettings`
  / :class:`DeadlineStats`, root config key ``deadline``): the client
  stamps every request with an absolute wall-clock deadline
  (``enqueue + budget_ms``; the budget seeds from ``autotune.slo_ms``
  when not set explicitly). Every stage boundary — loader hold,
  Batcher admission, executor queue-take, pre-ring-write — checks it
  and sheds expired requests through the PR 1 shed machinery (shed
  reason ``deadline_expired``, counted per site) instead of computing
  doomed work, so under overload the pipeline degrades to
  fresh-request goodput rather than uniformly-late completions.
* **Hedged re-dispatch** (:class:`HedgeGovernor`, step key
  ``hedge_ms`` on a replicated step): a dispatch outstanding on a lane
  beyond the threshold (static milliseconds, or ``"p95x"`` derived
  from the governor's own settle-latency EWMA) is re-issued to the
  best healthy sibling; the first resolution — completion *or*
  contained failure — wins and the loser's result is discarded by
  request id with no double count anywhere (hedge compute is counted
  as ``hedges_wasted_ms`` overhead, never as throughput).

Everything is gated: without the ``health``/``deadline`` root keys and
``hedge_ms`` step key, no board/stats/governor is built, no
``Health:``/``Deadline:``/``Hedge:`` log-meta line is written, and
logs stay byte-stable with the pre-PR schema. All board/stats methods
take an explicit ``now`` (``time.monotonic()`` seconds) from the
caller so unit tests drive the state machine deterministically.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from rnb_tpu import lockwitness, metrics, trace

# -- lane states -------------------------------------------------------

HEALTHY = "healthy"
SUSPECT = "suspect"
OPEN = "open"
HALF_OPEN = "half_open"
EVICTED = "evicted"

#: dead-letters on one lane since its last state transition that trip
#: the circuit one hop (healthy -> suspect, suspect -> open): a lane
#: failing FAST stays low-distress (it beats and settles promptly), so
#: failure count is its own signal next to in-flight age and beat
#: staleness — without it the least-loaded router would keep feeding
#: an always-empty always-failing lane forever
FAILURE_TRIP_THRESHOLD = 3

#: the legal state machine — parse_utils --check replays every lane's
#: transition log against exactly these edges (eviction is legal from
#: any live state: a crash needs no circuit warning first)
LEGAL_TRANSITIONS = {
    (HEALTHY, SUSPECT), (SUSPECT, HEALTHY), (SUSPECT, OPEN),
    (OPEN, HALF_OPEN), (HALF_OPEN, HEALTHY), (HALF_OPEN, OPEN),
    (HEALTHY, EVICTED), (SUSPECT, EVICTED), (OPEN, EVICTED),
    (HALF_OPEN, EVICTED),
}


class HealthSettings:
    """Validated, defaulted view of the ``health`` root config key."""

    __slots__ = ("suspect_after_ms", "open_after_ms",
                 "probe_interval_ms")

    def __init__(self, suspect_after_ms: float = 500.0,
                 open_after_ms: float = 2000.0,
                 probe_interval_ms: float = 1000.0):
        if not suspect_after_ms > 0:
            raise ValueError("health suspect_after_ms must be > 0")
        if open_after_ms < suspect_after_ms:
            raise ValueError("health open_after_ms (%g) must be >= "
                             "suspect_after_ms (%g)"
                             % (open_after_ms, suspect_after_ms))
        if not probe_interval_ms > 0:
            raise ValueError("health probe_interval_ms must be > 0")
        self.suspect_after_ms = float(suspect_after_ms)
        self.open_after_ms = float(open_after_ms)
        self.probe_interval_ms = float(probe_interval_ms)

    @staticmethod
    def from_config(raw: Optional[dict]) -> Optional["HealthSettings"]:
        """Settings from the (schema-validated) config dict, or None
        when the key is absent or ``enabled`` is false — absent means
        no boards, no Health: line, byte-stable logs."""
        if raw is None or not raw.get("enabled", True):
            return None
        return HealthSettings(
            suspect_after_ms=raw.get("suspect_after_ms", 500.0),
            open_after_ms=raw.get("open_after_ms", 2000.0),
            probe_interval_ms=raw.get("probe_interval_ms", 1000.0))


class _Lane:
    """Mutable per-lane record (board-lock protected)."""

    __slots__ = ("state", "since", "last_beat", "inflight", "failures",
                 "path", "probe_outstanding", "probe_t", "redispatched",
                 "routes_after_open", "drained", "instances")

    def __init__(self, now: float):
        self.state = HEALTHY
        self.since = now
        #: end-of-stream reached on this lane (its executor saw the
        #: exit marker, or an evicted lane's drain pump finished)
        self.drained = False
        #: live executor instances serving this lane's queue
        #: (register_instance/instance_died) — the LAST one to die
        #: runs the drain pump; while any lives, the lane still serves
        self.instances = 0
        self.last_beat: Optional[float] = None  # None = not yet live
        #: monotonic enqueue instants of in-flight dispatches, oldest
        #: first — the age signal the circuit trips on
        self.inflight: "deque[float]" = deque()
        self.failures = 0
        #: transition log: state names in visit order, healthy first
        self.path: List[str] = [HEALTHY]
        self.probe_outstanding = False
        self.probe_t = 0.0
        self.redispatched = 0
        self.routes_after_open = 0


class LaneHealthBoard:
    """Shared per-replica-step health state: producers route on it,
    replica executors feed it.

    Thread-safe under one lock (same discipline as
    :class:`rnb_tpu.handoff.InflightDepths`, which it parallels — the
    depths carry the load signal, this board carries the health
    verdict). Every transition is appended to the lane's path log and
    emitted as a ``health.lane_state`` trace instant, so the state
    machine's whole history is a checkable artifact, not a claim.
    """

    #: minimum gap between full state-machine evaluations — beats fire
    #: per executor loop iteration, and an O(lanes) scan under the
    #: shared lock on every one would make the board a hot-loop
    #: serialization point for a machine whose thresholds are
    #: hundreds of milliseconds
    EVAL_INTERVAL_S = 0.02

    #: declared concurrency contract (rnb-lint RNB-C001/C003)
    GUARDED_BY = {
        "_lanes": "_lock",
        "_last_eval": "_lock",
        "num_transitions": "_lock",
        "num_opens": "_lock",
        "num_evictions": "_lock",
        "num_probes": "_lock",
    }

    def __init__(self, queue_indices, settings: HealthSettings):
        self.settings = settings
        self._lock = lockwitness.lock("LaneHealthBoard._lock")
        now = time.monotonic()
        self._last_eval = float("-inf")
        self._lanes: "OrderedDict[int, _Lane]" = OrderedDict(
            (int(q), _Lane(now)) for q in queue_indices)
        # -- counters (snapshot/log-meta schema) ----------------------
        self.num_transitions = 0
        self.num_opens = 0
        self.num_evictions = 0
        self.num_probes = 0

    # -- signal feeds (executor + producer sides) ---------------------

    def beat(self, queue_idx: int, now: Optional[float] = None) -> None:
        """Executor loop-top liveness beat for its lane — and a
        state-machine tick: a wedged sibling's circuit must open even
        after the producer routed its last item (routing is the only
        other evaluation driver), so every live executor's beat also
        advances the clock-driven transitions."""
        with self._lock:
            lane = self._lanes.get(queue_idx)
            if lane is not None:
                now = time.monotonic() if now is None else now
                lane.last_beat = now
                self._evaluate_locked(now)

    def note_enqueue(self, queue_idx: int,
                     now: Optional[float] = None) -> None:
        """Producer routed one dispatch onto the lane: opens its
        in-flight age window (paired with :meth:`note_settle`)."""
        with self._lock:
            lane = self._lanes.get(queue_idx)
            if lane is not None:
                lane.inflight.append(
                    time.monotonic() if now is None else now)

    def note_settle(self, queue_idx: int, n: int = 1) -> None:
        """The lane's executor finished processing ``n`` dispatches
        (or redispatch moved them off the lane): close the oldest
        in-flight windows and let a successful half-open probe heal
        the lane."""
        with self._lock:
            lane = self._lanes.get(queue_idx)
            if lane is None:
                return
            for _ in range(min(n, len(lane.inflight))):
                lane.inflight.popleft()
            if lane.state == HALF_OPEN and lane.probe_outstanding:
                lane.probe_outstanding = False
                self._transition_locked(queue_idx, lane, HEALTHY,
                                 "probe-settled")

    def note_failure(self, queue_idx: int) -> None:
        """A dispatch on this lane was dead-lettered (the PR 1 fault
        stats' per-lane face)."""
        with self._lock:
            lane = self._lanes.get(queue_idx)
            if lane is not None:
                lane.failures += 1

    def evict(self, queue_idx: int, reason: str) -> None:
        """Permanent lane death (replica_crash/replica_stall): the
        lane leaves the routable set forever."""
        with self._lock:
            lane = self._lanes.get(queue_idx)
            if lane is not None and lane.state != EVICTED:
                self._transition_locked(queue_idx, lane, EVICTED, reason)
                self.num_evictions += 1

    def note_redispatch(self, from_queue_idx: int, n: int = 1) -> None:
        """``n`` queued items drained off an evicted lane and
        re-enqueued onto siblings."""
        with self._lock:
            lane = self._lanes.get(from_queue_idx)
            if lane is not None:
                lane.redispatched += n

    def register_instance(self, queue_idx: int) -> None:
        """One executor instance serves this lane's queue (called at
        thread start, before the start barrier). A lane may carry
        several instances (a multi-device sub-mesh per replica); lane
        death is only lane-wide once the LAST one died."""
        with self._lock:
            lane = self._lanes.get(queue_idx)
            if lane is not None:
                lane.instances += 1

    def instance_died(self, queue_idx: int) -> int:
        """One of the lane's executor instances died; returns how many
        remain. The caller runs the eviction drain only at 0 — while
        any instance survives, the lane's queue still has a consumer
        and draining it would steal live work, not rescue stranded
        work."""
        with self._lock:
            lane = self._lanes.get(queue_idx)
            if lane is None:
                return 0
            lane.instances = max(0, lane.instances - 1)
            return lane.instances

    def note_drained(self, queue_idx: int) -> None:
        """This lane's stream is over: its executor consumed the
        end-of-stream marker (or, for an evicted lane, its drain pump
        finished moving the queue's remainder to siblings)."""
        with self._lock:
            lane = self._lanes.get(queue_idx)
            if lane is not None:
                lane.drained = True

    def all_drained(self) -> bool:
        """Every lane of the step has reached end-of-stream.

        The end-of-stream *linger* protocol (rnb_tpu.runner): a
        healthy lane seeing its exit marker must not exit while a
        sibling could still redispatch stranded work onto its queue —
        it keeps polling until every lane is drained. Without this, a
        lane evicted AFTER its siblings finished would re-enqueue its
        queued items into queues nobody reads, stranding exactly the
        requests the drain exists to rescue."""
        with self._lock:
            return all(lane.drained for lane in self._lanes.values())

    # -- the state machine --------------------------------------------

    def _transition_locked(self, queue_idx: int, lane: _Lane, to: str,
                    why: str, now: Optional[float] = None) -> None:
        # lock held by caller; `now` keeps the transition clock in the
        # caller's timeline (unit tests drive it explicitly)
        frm = lane.state
        lane.state = to
        lane.since = time.monotonic() if now is None else now
        lane.failures = 0
        lane.path.append(to)
        self.num_transitions += 1
        if to == OPEN:
            self.num_opens += 1
        if trace.ACTIVE is not None:
            trace.instant("health.lane_state", args={
                "lane": queue_idx, "from": frm, "to": to, "why": why})
        if to == OPEN:
            # the flight recorder's circuit-open trigger
            # (rnb_tpu.metrics): arm a black-box dump of the ring
            # around this exact incident — the recorder's flusher
            # does the IO, never this (board-lock-holding) thread
            metrics.trigger(metrics.TRIGGER_CIRCUIT_OPEN,
                            {"lane": queue_idx, "why": why})

    def _evaluate_locked(self, now: float) -> None:
        if now - self._last_eval < self.EVAL_INTERVAL_S:
            return  # rate-limited: transitions lag by <= 20 ms
        self._last_eval = now
        s = self.settings
        for queue_idx, lane in self._lanes.items():
            if lane.state == EVICTED:
                continue
            # the distress signal: the oldest undrained dispatch's age
            # — and, once the lane has ever beaten, a stale beat while
            # work is outstanding (a wedged executor stops beating but
            # its queue keeps aging; an idle lane with nothing queued
            # is silent, not sick)
            age_ms = ((now - lane.inflight[0]) * 1000.0
                      if lane.inflight else 0.0)
            beat_ms = 0.0
            if lane.inflight and lane.last_beat is not None:
                beat_ms = (now - lane.last_beat) * 1000.0
            distress = max(age_ms, beat_ms)
            # the failure-rate signal: dead-letters since the last
            # transition (reset each hop, so escalation needs FRESH
            # failures at every rung)
            failing = lane.failures >= FAILURE_TRIP_THRESHOLD
            if lane.state == HEALTHY:
                if distress > s.suspect_after_ms or failing:
                    self._transition_locked(
                        queue_idx, lane, SUSPECT,
                        "failures %d" % lane.failures if failing
                        else "distress %.0fms" % distress, now)
            elif lane.state == SUSPECT:
                if distress > s.open_after_ms or failing:
                    self._transition_locked(
                        queue_idx, lane, OPEN,
                        "failures %d" % lane.failures if failing
                        else "distress %.0fms" % distress, now)
                elif distress <= s.suspect_after_ms \
                        and lane.failures == 0 \
                        and (now - lane.since) * 1000.0 \
                        >= s.suspect_after_ms:
                    # recovery needs a CLEAN record since the
                    # transition (failures reset each hop, so healing
                    # demands zero NEW dead-letters) plus a dwell of
                    # suspect_after_ms — a fast-failing lane is
                    # low-distress the instant it transitions, and
                    # dwell-free healing would flap
                    # healthy<->suspect forever
                    self._transition_locked(queue_idx, lane, HEALTHY,
                                     "recovered", now)
            elif lane.state == OPEN:
                if (now - lane.since) * 1000.0 >= s.probe_interval_ms:
                    self._transition_locked(queue_idx, lane, HALF_OPEN,
                                     "probe-due", now)
            elif lane.state == HALF_OPEN:
                if lane.probe_outstanding and \
                        (now - lane.probe_t) * 1000.0 > s.open_after_ms:
                    lane.probe_outstanding = False
                    self._transition_locked(queue_idx, lane, OPEN,
                                     "probe-aged-out", now)

    def state(self, queue_idx: int) -> Optional[str]:
        with self._lock:
            lane = self._lanes.get(queue_idx)
            return lane.state if lane is not None else None

    def route_filter(self, queue_indices,
                     now: Optional[float] = None
                     ) -> Tuple[List[int], Optional[int]]:
        """The producer-side routing consult: evaluate transitions,
        then return ``(routable_lanes, probe_lane)``.

        ``routable_lanes`` is the least-loaded candidate set (healthy
        + suspect lanes, in the caller's order; suspect still serves —
        only an *open* circuit stops traffic). ``probe_lane`` is a
        half-open lane due for its single recovery probe (the caller
        MUST route this dispatch there and nowhere else when set).
        Both empty means no routable lane exists — the caller falls
        back to routing over everything (deterministic beats dropping
        on the floor) and marks those routes ``forced``, which exempts
        them from the ``routes_after_open`` invariant.
        """
        now = time.monotonic() if now is None else now
        with self._lock:
            self._evaluate_locked(now)
            allowed = [q for q in queue_indices
                       if (lane := self._lanes.get(q)) is not None
                       and lane.state in (HEALTHY, SUSPECT)]
            probe = None
            for q in queue_indices:
                lane = self._lanes.get(q)
                if lane is not None and lane.state == HALF_OPEN \
                        and not lane.probe_outstanding:
                    lane.probe_outstanding = True
                    lane.probe_t = now
                    self.num_probes += 1
                    probe = q
                    break
            return allowed, probe

    def consult_and_route(self, queue_idx: int,
                          now: Optional[float] = None) -> bool:
        """Atomic single-lane routing decision: evaluate transitions
        and, in the same locked step, either claim the route (True) or
        refuse it (False, caller goes elsewhere).

        The split ``route_filter`` + ``note_route`` consult leaves a
        window where another thread's evaluation flips the lane OPEN
        between the caller's check and its note — which would count a
        ``routes_after_open`` violation against a dispatch that was
        decided while the lane was still routable. A caller with a
        single candidate lane and a fallback path (the netedge
        dispatcher) uses this instead: the decision and the
        accounting share one lock acquisition, so a route claimed
        here is by construction never a containment violation.
        Healthy/suspect route; a half-open lane grants exactly one
        probe (the claimer must dispatch it); open/evicted refuse.
        """
        now = time.monotonic() if now is None else now
        with self._lock:
            self._evaluate_locked(now)
            lane = self._lanes.get(queue_idx)
            if lane is None:
                return False
            if lane.state in (HEALTHY, SUSPECT):
                return True
            if lane.state == HALF_OPEN and not lane.probe_outstanding:
                lane.probe_outstanding = True
                lane.probe_t = now
                self.num_probes += 1
                return True
            return False

    def note_route(self, queue_idx: int, forced: bool = False) -> None:
        """One dispatch routed to the lane. A route landing on an
        open/evicted lane while routable siblings existed is the
        containment violation ``--check`` holds to zero; ``forced``
        marks the no-routable-sibling fallback, which is exempt."""
        with self._lock:
            lane = self._lanes.get(queue_idx)
            if lane is None:
                return
            if lane.state in (OPEN, EVICTED) and not forced:
                # (probe routes land while the lane is HALF_OPEN, so
                # they never count here)
                lane.routes_after_open += 1

    # -- reporting ----------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        """Job-end counters + per-lane detail for the ``Health:`` /
        ``Health lanes:`` log-meta lines (read after the pipeline
        drained, like every other sink snapshot)."""
        with self._lock:
            detail = {
                str(q): {
                    "state": lane.state,
                    "path": list(lane.path),
                    "redispatched_from": lane.redispatched,
                    "routes_after_open": lane.routes_after_open,
                }
                for q, lane in self._lanes.items()}
            return {
                "lanes": len(self._lanes),
                "transitions": self.num_transitions,
                "opens": self.num_opens,
                "evictions": self.num_evictions,
                "probes": self.num_probes,
                "redispatches": sum(lane.redispatched
                                    for lane in self._lanes.values()),
                "routes_after_open": sum(
                    lane.routes_after_open
                    for lane in self._lanes.values()),
                "lane_detail": detail,
            }


def aggregate_board_snapshots(snapshots: List[Dict[str, object]]
                              ) -> Dict[str, object]:
    """Sum per-step board snapshots into the job-wide view (lane
    queue indices are globally unique, so the detail dicts merge
    without collision)."""
    out: Dict[str, object] = {"lanes": 0, "transitions": 0, "opens": 0,
                              "evictions": 0, "probes": 0,
                              "redispatches": 0, "routes_after_open": 0}
    detail: Dict[str, dict] = {}
    for snap in snapshots:
        for key in ("lanes", "transitions", "opens", "evictions",
                    "probes", "redispatches", "routes_after_open"):
            out[key] += int(snap.get(key, 0))
        detail.update(dict(snap.get("lane_detail", {})))
    out["lane_detail"] = detail
    return out


def legal_path(path) -> bool:
    """Is a lane's transition log a legal automaton walk? (The
    ``--check`` invariant: starts healthy, every hop a declared
    edge.)"""
    path = list(path)
    if not path or path[0] != HEALTHY:
        return False
    return all((a, b) in LEGAL_TRANSITIONS
               for a, b in zip(path, path[1:]))


# -- deadline propagation ---------------------------------------------

class DeadlineSettings:
    """Validated view of the ``deadline`` root config key.

    ``budget_ms`` defaults to ``autotune.slo_ms`` when the autotune
    key is present (the one latency contract the config already
    declares), else 1000 ms.
    """

    __slots__ = ("budget_ms",)

    DEFAULT_BUDGET_MS = 1000.0

    def __init__(self, budget_ms: float):
        if not budget_ms > 0:
            raise ValueError("deadline budget_ms must be > 0")
        self.budget_ms = float(budget_ms)

    @staticmethod
    def from_config(raw: Optional[dict],
                    autotune_raw: Optional[dict] = None
                    ) -> Optional["DeadlineSettings"]:
        if raw is None or not raw.get("enabled", True):
            return None
        budget = raw.get("budget_ms")
        if budget is None and autotune_raw:
            budget = autotune_raw.get("slo_ms")
        if budget is None:
            budget = DeadlineSettings.DEFAULT_BUDGET_MS
        return DeadlineSettings(budget_ms=budget)


class DeadlineStats:
    """Job-wide expiry-shed accounting, per check site.

    Deliberately a SECOND ledger next to ``FaultStats.shed_sites``
    (every deadline shed records in both): ``parse_utils --check``
    cross-foots the two, so a check site that shed without counting —
    or counted without shedding — is a detectable inconsistency, not
    silent drift.
    """

    GUARDED_BY = {"expired": "_lock", "sites": "_lock"}

    def __init__(self):
        self._lock = lockwitness.lock("DeadlineStats._lock")
        self.expired = 0
        self.sites: Dict[str, int] = {}

    def record(self, site: str, n: int = 1) -> None:
        with self._lock:
            self.expired += n
            self.sites[site] = self.sites.get(site, 0) + n

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {"expired": self.expired, "sites": dict(self.sites)}


#: the shed-site suffix every deadline expiry site carries — the
#: ``--check`` cross-foot selects FaultStats shed sites by it
DEADLINE_SITE_SUFFIX = ":deadline_expired"


def deadline_site(where: str) -> str:
    """The one site-naming rule for deadline sheds (``where`` names
    the boundary, e.g. ``step1_take``)."""
    return where + DEADLINE_SITE_SUFFIX


def cards_of(time_card) -> list:
    """The individual TimeCards behind one pipeline item (mirrors
    rnb_tpu.runner._cards_of without importing the executor)."""
    cards = getattr(time_card, "time_cards", None)
    return list(cards) if cards is not None else [time_card]


def expired(time_card, now: Optional[float] = None) -> bool:
    """Has EVERY constituent request of this item blown its absolute
    deadline? (A fused batch is one indivisible dispatch — it sheds
    only when no member can still meet its contract; wall clock,
    matching the client's enqueue stamps.)

    Cards without a ``deadline_s`` stamp never expire, so the check
    is inert on deadline-off runs and on exit markers.
    """
    now = time.time() if now is None else now
    saw = False
    for tc in cards_of(time_card):
        d = getattr(tc, "deadline_s", None)
        if d is None:
            return False
        saw = True
        if d >= now:
            return False
    return saw


# -- hedged re-dispatch -----------------------------------------------

#: claim() verdicts
WINNER = "winner"
LOSER = "loser"
UNTRACKED = "untracked"


class DirectPayload:
    """A hedge copy's tensor payload, carried INSIDE the queue item in
    place of a ring :class:`rnb_tpu.control.Signal`.

    The original dispatch still owns its ring slot (read + release on
    its own lane); re-enqueueing the same Signal twice would let the
    first consumer release the slot under the second one. A hedge
    instead snapshots the committed (immutable) arrays by reference at
    fire time and ships them directly — same zero-copy discipline as
    the device-resident handoff adopt rule.
    """

    __slots__ = ("payload",)

    def __init__(self, payload):
        self.payload = payload


def clone_cards(time_card):
    """A stamp-complete copy of one item's card (or TimeCardList) for
    a hedge dispatch: same id(s) and timings, so the winner's summary
    row is schema-identical whichever copy wins — but a distinct
    object, so the two lanes' stamps never race on one card. The
    clone carries ``hedge_copy`` (a declared content stamp) so the
    claim site knows which copy resolved first."""
    from rnb_tpu.telemetry import CONTENT_STAMPS, TimeCard, TimeCardList

    def _one(tc):
        child = TimeCard(tc.id)
        child.timings = OrderedDict(tc.timings)
        child.devices = list(tc.devices)
        for attr in CONTENT_STAMPS:
            if hasattr(tc, attr):
                setattr(child, attr, getattr(tc, attr))
        child.hedge_copy = True
        return child

    cards = getattr(time_card, "time_cards", None)
    if cards is not None:
        return TimeCardList([_one(tc) for tc in cards])
    return _one(time_card)


class _Outstanding:
    __slots__ = ("key", "lane", "t0", "payload", "non_tensors", "card",
                 "hedged")

    def __init__(self, key, lane, t0, payload, non_tensors, card):
        self.key = key
        self.lane = lane
        self.t0 = t0
        self.payload = payload
        self.non_tensors = non_tensors
        self.card = card
        self.hedged = False


class HedgeGovernor:
    """Tail-latency hedging for one replica-expanded edge.

    The producer tracks every dispatch it routes onto a lane; when one
    is outstanding past the threshold, :meth:`poll` hands back a hedge
    copy to re-issue on the best healthy sibling. Each hedged request
    id resolves exactly once through :meth:`claim` — consulted at the
    replica step's completion, dead-letter and shed sites — so
    "first completion wins" is an accounting invariant, not a race:
    ``hedges_won + hedges_lost == hedges_fired`` always, and the
    loser's burned service time lands in ``hedges_wasted_ms``
    (overhead, never throughput — the honesty policy).

    Threshold modes: a static ``hedge_ms`` number, or ``"p95x"`` — a
    p95 estimate (EWMA mean + 2 sigma from an EWMA of squares) of the
    edge's own enqueue->settle latency, floored at
    :data:`P95X_MIN_SAMPLES` observations so cold starts never hedge.
    """

    P95X_MIN_SAMPLES = 5
    P95X_MIN_MS = 1.0

    #: declared concurrency contract (rnb-lint RNB-C001/C003); mode /
    #: static_ms / ewma_alpha are immutable after __init__ and so
    #: outside the contract by convention
    GUARDED_BY = {
        "_outstanding": "_lock",
        "_unresolved": "_lock",
        "_resolved": "_lock",
        "_lat_mean_ms": "_lock",
        "_lat_sq_ms": "_lock",
        "_samples": "_lock",
        "fired": "_lock",
        "won": "_lock",
        "lost": "_lock",
        "wasted_ms": "_lock",
    }

    def __init__(self, hedge_ms, ewma_alpha: float = 0.2):
        self.mode = "p95x" if hedge_ms == "p95x" else "static"
        self.static_ms = (float(hedge_ms) if self.mode == "static"
                          else 0.0)
        self.ewma_alpha = float(ewma_alpha)
        self._lock = lockwitness.lock("HedgeGovernor._lock")
        self._outstanding: "OrderedDict[tuple, _Outstanding]" = \
            OrderedDict()
        #: hedged keys awaiting their FIRST resolution (either copy)
        self._unresolved: set = set()
        #: hedged keys whose winner already resolved — the other
        #: copy's resolution is the loser; removed on that second
        #: claim (exactly two copies exist per fired hedge)
        self._resolved: set = set()
        self._lat_mean_ms: Optional[float] = None
        self._lat_sq_ms: Optional[float] = None
        self._samples = 0
        # -- counters (snapshot/log-meta schema) ----------------------
        self.fired = 0
        self.won = 0
        self.lost = 0
        self.wasted_ms = 0.0

    @staticmethod
    def key_of(time_card) -> tuple:
        """The dispatch identity: the sorted tuple of constituent
        request ids (stable across the original and its clone)."""
        return tuple(tc.id for tc in cards_of(time_card))

    # -- producer side ------------------------------------------------

    def threshold_ms(self) -> Optional[float]:
        if self.mode == "static":
            return self.static_ms
        with self._lock:
            if self._samples < self.P95X_MIN_SAMPLES:
                return None
            mean = self._lat_mean_ms or 0.0
            var = max(0.0, (self._lat_sq_ms or 0.0) - mean * mean)
            # mean + 2 sigma approximates p95 for the typical settle
            # distribution, with a 1.5x-mean floor so a low-variance
            # stream never hedges its own median dispatch
            return max(self.P95X_MIN_MS, 1.5 * mean,
                       mean + 2.0 * var ** 0.5)

    def track(self, time_card, lane: int, payload, non_tensors,
              now: Optional[float] = None) -> None:
        """One dispatch routed onto ``lane``: snapshot what a hedge
        would need. Called by the producer BEFORE the enqueue so the
        clone can never race the consumer's stamps."""
        key = self.key_of(time_card)
        clone = clone_cards(time_card)
        now = time.monotonic() if now is None else now
        with self._lock:
            self._outstanding[key] = _Outstanding(
                key, lane, now, payload, non_tensors, clone)

    def _settle_locked(self, key: tuple, now: float) -> None:
        # lock held: close the outstanding window + feed the p95x
        # estimator. A key already settled (the other copy resolved
        # first, or a redundant call) is a no-op.
        entry = self._outstanding.pop(key, None)
        if entry is None:
            return
        lat_ms = (now - entry.t0) * 1000.0
        a = self.ewma_alpha
        self._lat_mean_ms = (lat_ms if self._lat_mean_ms is None
                             else a * lat_ms
                             + (1 - a) * self._lat_mean_ms)
        sq = lat_ms * lat_ms
        self._lat_sq_ms = (sq if self._lat_sq_ms is None
                           else a * sq + (1 - a) * self._lat_sq_ms)
        self._samples += 1

    def settle(self, time_card, now: Optional[float] = None) -> None:
        """Close one dispatch's outstanding window without resolving
        a claim (abort-path bookkeeping; :meth:`claim` settles
        implicitly on every normal resolution path)."""
        with self._lock:
            self._settle_locked(self.key_of(time_card),
                                time.monotonic() if now is None
                                else now)

    def num_outstanding(self) -> int:
        """Tracked dispatches not yet settled — the producer lingers
        on this at end-of-stream (rnb_tpu.runner): exit markers may
        only follow once nothing is left that could still need a
        hedge (a hedge fired after the markers would arrive behind
        them and strand)."""
        with self._lock:
            return len(self._outstanding)

    def poll(self, now: Optional[float] = None) -> List[_Outstanding]:
        """Dispatches outstanding past the threshold and not yet
        hedged — the producer re-issues each on a healthy sibling and
        then commits with :meth:`begin_fire` before enqueueing."""
        threshold = self.threshold_ms()
        if threshold is None:
            return []
        now = time.monotonic() if now is None else now
        due: List[_Outstanding] = []
        with self._lock:
            for entry in self._outstanding.values():
                if entry.hedged:
                    continue
                if (now - entry.t0) * 1000.0 > threshold:
                    due.append(entry)
        return due

    def begin_fire(self, entry: _Outstanding) -> bool:
        """Atomically commit to hedging ``entry`` BEFORE the copy is
        enqueued: False when the dispatch already resolved (its
        consumer's claim settled it between the poll and this call —
        firing then would let the late copy claim WINNER and publish
        the request a second time) or another producer got here
        first. On True the caller MUST enqueue the copy or roll back
        with :meth:`cancel_fire`."""
        with self._lock:
            if entry.hedged or entry.key not in self._outstanding:
                return False
            entry.hedged = True
            self.fired += 1
            self._unresolved.add(entry.key)
            return True

    def cancel_fire(self, entry: _Outstanding) -> None:
        """Roll back :meth:`begin_fire` (the sibling queue was full):
        the dispatch goes back to un-hedged so a later tick retries."""
        with self._lock:
            if entry.hedged and entry.key in self._unresolved:
                entry.hedged = False
                self.fired -= 1
                self._unresolved.discard(entry.key)

    # -- consumer side ------------------------------------------------

    def claim(self, time_card, now: Optional[float] = None) -> str:
        """Resolve one copy of a dispatch: WINNER for the first
        resolution of a hedged key (count it normally), LOSER for the
        second (discard — the rid already terminated), UNTRACKED for
        dispatches no hedge was ever fired for. Always settles the
        key's outstanding window in the same critical section, so a
        dispatch that resolved can never be hedged afterwards
        (:meth:`begin_fire` re-checks under the same lock)."""
        key = self.key_of(time_card)
        is_hedge = any(getattr(tc, "hedge_copy", False)
                       for tc in cards_of(time_card))
        with self._lock:
            self._settle_locked(key, time.monotonic() if now is None
                                else now)
            if key in self._unresolved:
                self._unresolved.discard(key)
                self._resolved.add(key)
                if is_hedge:
                    self.won += 1
                else:
                    self.lost += 1
                return WINNER
            if key in self._resolved:
                self._resolved.discard(key)
                return LOSER
            return UNTRACKED

    def discard(self, time_card) -> None:
        """The losing copy's accounting: the service span it burned at
        the hedged step — the DEEPEST ``inference{i}_start``'s step,
        which is the losing dispatch itself (earlier steps' spans are
        shared pre-fork history both copies paid exactly once, so
        falling back to them would inflate the counter). A loser that
        never finished that span (contained failure mid-service, shed
        before dispatch) counts 0 — undercounting unfinished waste
        beats charging shared work to the hedge."""
        waste = 0.0
        for tc in cards_of(time_card):
            starts: Dict[int, float] = {}
            finishes: Dict[int, float] = {}
            for key, t in tc.timings.items():
                for suffix, into in (("_start", starts),
                                     ("_finish", finishes)):
                    if key.startswith("inference") \
                            and key.endswith(suffix):
                        digits = key[len("inference"):-len(suffix)]
                        if digits.isdigit():
                            into[int(digits)] = t
            if starts:
                step = max(starts)
                t1 = finishes.get(step)
                if t1 is not None:
                    waste = max(waste, (t1 - starts[step]) * 1000.0)
        with self._lock:
            self.wasted_ms += waste

    # -- reporting ----------------------------------------------------

    def live_counters(self) -> Dict[str, int]:
        """Read-only counter view for the live-metrics poll
        (rnb_tpu.metrics) — unlike :meth:`snapshot` it does NOT
        resolve unresolved hedges, so it can be read every flusher
        tick without perturbing the claim ledger. The final metric
        snapshot is taken AFTER :meth:`snapshot` ran at teardown, so
        it foots with the Hedge: log-meta line exactly."""
        with self._lock:
            return {"fired": self.fired, "won": self.won,
                    "lost": self.lost}

    def snapshot(self) -> Dict[str, object]:
        """Final counters; hedges still unresolved at teardown (the
        run was cut off mid-flight) resolve as lost with zero waste so
        ``won + lost == fired`` holds on every path."""
        with self._lock:
            unresolved = len(self._unresolved)
            self._unresolved.clear()
            self.lost += unresolved
            return {"fired": self.fired, "won": self.won,
                    "lost": self.lost,
                    "wasted_ms": int(round(self.wasted_ms))}


def aggregate_hedge_snapshots(snapshots: List[Dict[str, object]]
                              ) -> Dict[str, object]:
    out = {"fired": 0, "won": 0, "lost": 0, "wasted_ms": 0}
    for snap in snapshots:
        for key in out:
            out[key] += int(snap.get(key, 0))
    return out
