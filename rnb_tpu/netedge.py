"""The network edge: a crash-tolerant cross-host ingest transport.

ROADMAP item 2 calls for splitting ingest (decode + staging) from
inference behind a real transport. This module is that edge: the main
process keeps the client, the local step-0 fallback path and every
downstream inference stage, while a *peer* process (``python -m
rnb_tpu.netedge --serve``) runs a second copy of the step-0 stage and
serves requests over the length-prefixed, checksummed TCP frame
protocol in :mod:`rnb_tpu.ops.wire`.

The robustness contract — every signal the PR 10 health machinery
consumes exists on the wire:

* liveness beats are heartbeat frames (``BEAT`` every ``beat_ms``),
* the peer's in-flight depth rides the header of EVERY frame,
* ``deadline_s`` rides the REQ header so expiry shedding fires on
  both sides of the edge without decoding the payload,
* the sender reconnects with capped exponential backoff + jitter and
  keeps a bounded sequence-numbered resend window,
* both sides keep dedup ledgers so a resend after an ack-loss can
  never double-dispatch (the peer re-serves its cached response; the
  main side drops response frames for already-settled sequences),
* the receiver side is bound to a :class:`~rnb_tpu.health.LaneHealthBoard`
  (one lane, :data:`NET_LANE`), so a dead or wedged peer trips
  healthy -> suspect -> open, surviving requests drain to the local
  fallback path, and every request still terminates exactly once.

Exactly-once honesty policy: a window entry is removed ONLY on a
terminal event — its DATA injected downstream, its DISPOSE processed,
a receive-boundary deadline shed, a corrupt-frame dead-letter, or a
local reroute. Acks merely suppress resends. ``frames_sent`` counts
unique sequences, so ``frames_sent == frames_acked + resent_pending``
holds at teardown by construction, and ``--check`` cross-foots the
whole ledger (rnb_tpu/scripts/parse_utils.py).

Clocks: ``deadline_s`` stamps are wall-clock (``time.time()``), which
is comparable across processes on one host (the loopback cell) and
across NTP-disciplined hosts; the health board's staleness math stays
monotonic and purely local to the main process.
"""

from __future__ import annotations

import argparse
import os
import queue
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from rnb_tpu import lockwitness
from rnb_tpu.control import dispose_requests
from rnb_tpu.faults import (NetCorruptFrameError, NetPartialFrameError,
                            NetRefusedError, NetResetError,
                            NetTimeoutError, PermanentError,
                            TransientError, fault_reason)
from rnb_tpu.health import (DirectPayload, deadline_site, expired)
from rnb_tpu.ops import wire

#: the edge's lane index on its dedicated LaneHealthBoard — there is
#: exactly one remote peer, so one lane (index 0 keeps lane_detail
#: keys disjoint from per-step replica boards only because netedge
#: excludes replicas entirely; see config.py guards)
NET_LANE = 0

#: reconnect backoff: exponential from ``backoff_ms``, capped here
BACKOFF_CAP_MS = 2000.0
#: uniform jitter fraction added on top of each capped base delay
JITTER_FRAC = 0.25

#: dispatcher wait-loop tick — every blocking wait in this module
#: polls at this period so the health board keeps evaluating (and the
#: circuit can open) even while the peer is wedged and nothing else
#: is making progress
_TICK_S = 0.05

#: peer: exit when connected once, then idle with no connection this long
_PEER_IDLE_S = 60.0
#: peer: dedup ledger size (seq -> cached response); far beyond any
#: legal resend_window so a resend always finds its cached response
_PEER_LEDGER_MAX = 4096


def parse_addr(addr: str) -> Tuple[str, int]:
    """``"host:port"`` -> ``(host, port)`` (IPv4/hostname only)."""
    host, sep, port = str(addr).rpartition(":")
    if not sep or not host:
        raise ValueError("netedge address %r is not host:port" % (addr,))
    return host, int(port)


def backoff_schedule_ms(backoff_ms: float, max_retries: int,
                        seed: int) -> List[float]:
    """The deterministic per-cycle reconnect delay schedule.

    Attempt ``i`` sleeps ``min(backoff_ms * 2**i, BACKOFF_CAP_MS)``
    plus uniform jitter up to ``JITTER_FRAC`` of that base — seeded,
    so a chaos run's dial storm is replayable byte-for-byte. The
    attempt counter resets after every successful connect; the same
    schedule is reused per cycle (re-drawing jitter per cycle would
    make reconnect timing depend on how many cycles ran before).
    """
    rng = np.random.default_rng(int(seed) if seed else 0)
    schedule = []
    for attempt in range(int(max_retries)):
        base = min(float(backoff_ms) * (2.0 ** attempt), BACKOFF_CAP_MS)
        schedule.append(base + float(rng.uniform(0.0, base * JITTER_FRAC)))
    return schedule


class NetEdgeSettings:
    """Validated, defaulted view of the root ``netedge`` config key."""

    __slots__ = ("listen", "connect", "beat_ms", "io_timeout_ms",
                 "max_retries", "backoff_ms", "resend_window", "spawn")

    def __init__(self, listen: Optional[str] = None,
                 connect: Optional[str] = None,
                 beat_ms: float = 200.0,
                 io_timeout_ms: float = 2000.0,
                 max_retries: int = 5,
                 backoff_ms: float = 50.0,
                 resend_window: int = 8,
                 spawn: bool = False):
        if not beat_ms > 0:
            raise ValueError("netedge beat_ms must be > 0")
        if not io_timeout_ms > beat_ms:
            raise ValueError(
                "netedge io_timeout_ms (%g) must be > beat_ms (%g): "
                "a receive timeout shorter than the heartbeat period "
                "would classify a healthy peer as silent"
                % (io_timeout_ms, beat_ms))
        if int(max_retries) < 1:
            raise ValueError("netedge max_retries must be >= 1")
        if backoff_ms < 0:
            raise ValueError("netedge backoff_ms must be >= 0")
        if int(resend_window) < 1:
            raise ValueError("netedge resend_window must be >= 1")
        if connect is None and not spawn:
            raise ValueError(
                "netedge needs 'connect' (host:port of a running "
                "peer) or 'spawn: true' (launch the peer locally)")
        if connect is not None:
            parse_addr(connect)
        if listen is not None:
            parse_addr(listen)
        self.listen = listen
        self.connect = connect
        self.beat_ms = float(beat_ms)
        self.io_timeout_ms = float(io_timeout_ms)
        self.max_retries = int(max_retries)
        self.backoff_ms = float(backoff_ms)
        self.resend_window = int(resend_window)
        self.spawn = bool(spawn)

    @staticmethod
    def from_config(raw: Optional[dict]) -> Optional["NetEdgeSettings"]:
        """Settings from the (schema-validated) config dict, or None
        when the key is absent or ``enabled`` is false — absent means
        no edge, no Net: lines, byte-stable logs (the PR 6/11/15
        inertness pattern)."""
        if raw is None or not raw.get("enabled", True):
            return None
        return NetEdgeSettings(
            listen=raw.get("listen"),
            connect=raw.get("connect"),
            beat_ms=raw.get("beat_ms", 200.0),
            io_timeout_ms=raw.get("io_timeout_ms", 2000.0),
            max_retries=raw.get("max_retries", 5),
            backoff_ms=raw.get("backoff_ms", 50.0),
            resend_window=raw.get("resend_window", 8),
            spawn=raw.get("spawn", False))


class NetStats:
    """Thread-safe edge counters — the ``Net:`` / ``Net errors:``
    log-meta lines, the ``net.*`` metrics poll, and the BenchmarkResult
    ``net_*`` fields all read one :meth:`snapshot`."""

    COUNTERS = ("frames_sent", "frames_acked", "resent_pending",
                "resends", "beats", "reconnects", "remote", "local",
                "dedup_drops", "dup_arrivals", "wire_bytes",
                "frame_bytes", "window_stranded",
                "open_before_timeout", "err_total", "err_refused",
                "err_reset", "err_timeout", "err_partial_frame",
                "err_corrupt")

    _ERR_FIELD = {"net_refused": "err_refused",
                  "net_reset": "err_reset",
                  "net_timeout": "err_timeout",
                  "net_partial_frame": "err_partial_frame",
                  "net_corrupt": "err_corrupt"}

    #: declared concurrency contract (rnb-lint RNB-C001/C003)
    GUARDED_BY = {
        "_c": "_lock",
        "peer_depth": "_lock",
        "_t_first_open": "_lock",
        "_t_first_timeout": "_lock",
    }

    def __init__(self):
        self._lock = lockwitness.lock("NetStats._lock")
        self._c: Dict[str, int] = {k: 0 for k in self.COUNTERS}
        self.peer_depth = 0.0
        self._t_first_open: Optional[float] = None
        self._t_first_timeout: Optional[float] = None

    def inc(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._c[key] += n

    def err(self, reason: str, n: int = 1) -> None:
        """Count one classified net error (``fault_reason`` string)."""
        with self._lock:
            self._c["err_total"] += n
            self._c[self._ERR_FIELD[reason]] += n
            if reason == "net_timeout" and self._t_first_timeout is None:
                self._t_first_timeout = time.monotonic()

    def gauge_depth(self, depth: float) -> None:
        with self._lock:
            self.peer_depth = float(depth)

    def note_open(self) -> None:
        """The dispatcher observed the lane circuit OPEN (or worse)."""
        with self._lock:
            if self._t_first_open is None:
                self._t_first_open = time.monotonic()

    def finalize(self, stranded: int) -> None:
        """Teardown bookkeeping: the resend-window remainder and the
        did-the-circuit-beat-the-io-timeout verdict (the netchaos
        gate's headline assertion)."""
        with self._lock:
            self._c["window_stranded"] = int(stranded)
            self._c["resent_pending"] = (self._c["frames_sent"]
                                         - self._c["frames_acked"])
            self._c["open_before_timeout"] = int(
                self._t_first_open is not None
                and (self._t_first_timeout is None
                     or self._t_first_open < self._t_first_timeout))

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            snap: Dict[str, object] = dict(self._c)
            snap["peer_depth"] = self.peer_depth
            return snap


class _WindowEntry:
    """One in-flight remote request (window-lock protected)."""

    __slots__ = ("seq", "path", "card", "frame", "acked")

    def __init__(self, seq: int, path, card, frame: bytes):
        self.seq = seq
        self.path = path
        self.card = card
        self.frame = frame   # cached wire bytes, ready to resend
        self.acked = False


class NetEdgeClient:
    """Main-process side of the edge: a dispatcher thread
    (``netedge-tx``) routing filename-queue items remote-or-local, and
    a receiver thread (``netedge-rx``) turning response frames back
    into step-0 output-queue items. Neither joins the pipeline
    barriers — the edge is a transport, not a stage."""

    #: declared concurrency contract (rnb-lint RNB-C001/C003): three
    #: locks, three planes — socket handoff, resend window, receiver
    #: pad re-count
    GUARDED_BY = {
        "_sock": "_send_lock",
        "_window": "_wlock",
        "_seq_next": "_wlock",
        "_finalizing": "_wlock",
        "_pad": "_pad_lock",
    }
    UNGUARDED_OK = {
        "_dial_count": "tx thread is the sole dialer",
        "_ever_connected": "tx-thread confined (dial path only)",
        "_fired": "tx-thread confined (dial path only)",
        "_eos_sent": "tx-thread confined (EOS drain runs on tx)",
        "_evicted": "written only by the tx dial path; other "
                    "threads' bare bool reads are monotone "
                    "(evicted never un-evicts)",
    }

    def __init__(self, settings: NetEdgeSettings, *, board, stats,
                 fault_plan, fault_stats, deadline_stats, counter,
                 num_videos, termination, filename_queue, local_queue,
                 inject_queue, num_markers, seed: int = 0):
        self.settings = settings
        self.board = board
        self.stats = stats
        self.fault_plan = fault_plan
        self.fault_stats = fault_stats
        self.deadline_stats = deadline_stats
        self.counter = counter
        self.num_videos = num_videos
        self.termination = termination
        self.filename_queue = filename_queue
        self.local_queue = local_queue
        self.inject_queue = inject_queue
        self.num_markers = int(num_markers)
        self._io_s = settings.io_timeout_ms / 1000.0
        self._schedule = backoff_schedule_ms(
            settings.backoff_ms, settings.max_retries, seed)
        self._addr = parse_addr(settings.connect)
        # -- connection (tx thread is the sole dialer) ----------------
        self._sock: Optional[socket.socket] = None
        self._send_lock = lockwitness.lock("NetEdgeClient._send_lock")
        self._connected = threading.Event()
        self._ever_connected = False
        self._dial_count = 0
        self._fired: set = set()   # (fault_idx, id) net-fault ledger
        self._evicted = False
        #: EOS shipped — the peer closing its end after that is the
        #: protocol's clean goodbye, not a net_reset to count
        self._eos_sent = False
        # -- resend window --------------------------------------------
        self._wlock = lockwitness.lock("NetEdgeClient._wlock")
        self._window: "OrderedDict[int, _WindowEntry]" = OrderedDict()
        self._seq_next = 1
        self._resend_due = threading.Event()
        #: entries popped by the receiver but not yet fully settled —
        #: the EOS drain must not release end-of-stream markers while
        #: an injection is mid-flight (pop happens first for dedup)
        self._finalizing = 0
        # -- receiver-side pad accounting: remote cards carry the
        # loader's pad_rows stamps but the peer's PadCounter dies with
        # the peer, so the receiver re-counts shipped emissions here
        # and the launcher appends it to the job's pad sink
        self._pad_lock = lockwitness.lock("NetEdgeClient._pad_lock")
        self._pad = {"pad_rows": 0, "total_rows": 0, "emissions": 0}
        self._stop = threading.Event()
        self._tx = threading.Thread(target=self._tx_loop,
                                    name="netedge-tx", daemon=True)
        self._rx = threading.Thread(target=self._rx_loop,
                                    name="netedge-rx", daemon=True)

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        self._rx.start()
        self._tx.start()

    def stop(self, timeout_s: float = 30.0) -> None:
        """Join both threads (the tx thread ends itself after the EOS
        drain protocol) and finalize the teardown counters."""
        self._tx.join(timeout=timeout_s)
        self._stop.set()
        self._close_sock()
        self._rx.join(timeout=5.0)
        with self._wlock:
            stranded = len(self._window)
        self.stats.finalize(stranded)

    def pad_snapshot(self) -> Dict[str, int]:
        with self._pad_lock:
            return dict(self._pad)

    # -- connection management (tx thread only) -----------------------

    def _close_sock(self) -> None:
        with self._send_lock:
            sock, self._sock = self._sock, None
            self._connected.clear()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _mark_dead(self, sock) -> None:
        """Receiver saw the connection die; the dispatcher redials."""
        with self._send_lock:
            if self._sock is sock:
                self._sock = None
                self._connected.clear()
        try:
            sock.close()
        except OSError:
            pass

    def _dial_once(self) -> socket.socket:
        """One dial attempt — consults the fault plan's ``net_refused``
        draws first (dial counter as the request id, PR 1 contract)."""
        self._dial_count += 1
        if self.fault_plan is not None:
            hit = self.fault_plan.net_fault("net_refused",
                                            self._dial_count)
            if hit is not None:
                key = (hit[0], self._dial_count)
                if key not in self._fired:
                    self._fired.add(key)
                    raise NetRefusedError(
                        "injected dial refusal (fault %d, dial %d)"
                        % (hit[0], self._dial_count))
        try:
            sock = socket.create_connection(self._addr,
                                            timeout=self._io_s)
        except Exception as exc:  # noqa: BLE001 - classified below
            net = wire.classify_io_error(exc)
            if net is None and isinstance(exc, OSError):
                # dialing a dead host surfaces as assorted OSErrors
                # (EHOSTUNREACH, ENETUNREACH...) — all "refused" for
                # the edge's purposes: nobody answered
                net = NetRefusedError(str(exc))
            if net is not None:
                raise net from exc
            raise
        sock.settimeout(self._io_s)
        return sock

    def _ensure_connected(self) -> bool:
        """Live connection or bust: dial with the seeded backoff
        schedule; an exhausted cycle (``max_retries`` failed dials)
        evicts the lane and reroutes the whole window locally."""
        if self._connected.is_set():
            return True
        if self._evicted:
            return False
        last_reason = "net_refused"
        for attempt in range(self.settings.max_retries):
            if self._stop.is_set() or self._aborted():
                return False
            try:
                sock = self._dial_once()
            except (NetRefusedError, NetResetError,
                    NetTimeoutError) as exc:
                last_reason = fault_reason(exc)
                self.stats.err(last_reason)
                if attempt < len(self._schedule):
                    self._sleep_ticking(
                        self._schedule[attempt] / 1000.0)
                continue
            with self._send_lock:
                self._sock = sock
                self._connected.set()
            if self._ever_connected:
                self.stats.inc("reconnects")
            self._ever_connected = True
            self._resend_all()
            return True
        self._evict("netedge peer unreachable (%s after %d dials)"
                    % (last_reason, self.settings.max_retries))
        return False

    def _evict(self, reason: str) -> None:
        self._evicted = True
        self.board.evict(NET_LANE, reason)
        self.stats.note_open()   # evicted is as open as it gets
        self._close_sock()
        self._reroute_window()

    # -- resend window ------------------------------------------------

    def _resend_all(self) -> None:
        """After a reconnect: resend every non-terminal entry in
        sequence order. The peer's dedup ledger re-acks and re-serves
        processed ones; the rest are genuinely lost and re-enter."""
        with self._wlock:
            frames = [e.frame for e in self._window.values()]
        for frame in frames:
            if not self._send_raw(frame):
                return
            self.stats.inc("resends")

    def _maybe_resend(self) -> None:
        """Receive-timeout recovery: the receiver heard nothing for a
        full io_timeout, so nudge the oldest unacked entry (an ack
        lost to a reset would otherwise strand it until reconnect)."""
        if not self._resend_due.is_set():
            return
        self._resend_due.clear()
        if not self._connected.is_set():
            return
        with self._wlock:
            frame = next((e.frame for e in self._window.values()
                          if not e.acked), None)
        if frame is not None and self._send_raw(frame):
            self.stats.inc("resends")

    def _reroute_window(self) -> None:
        """Move every non-terminal window entry onto the local fallback
        path — each atomically popped, so a response frame racing in
        for it hits the dedup ledger instead of double-dispatching."""
        while True:
            with self._wlock:
                if not self._window:
                    return
                _, entry = self._window.popitem(last=False)
            card = entry.card
            card.redispatched = getattr(card, "redispatched", 0) + 1
            self.board.note_redispatch(NET_LANE)
            self.board.note_settle(NET_LANE)
            self.stats.inc("local")
            self._put_local((None, entry.path, card))

    # -- dispatcher (netedge-tx) --------------------------------------

    def _aborted(self) -> bool:
        """Abnormal termination only — target-reached keeps the edge
        draining so already-produced requests still terminate."""
        return (self.termination.terminated
                and int(self.termination.value) != 0)

    def _tick(self) -> None:
        """The idle-path health tick: evaluate the board's clock-driven
        transitions (an empty consult sets no probes) and track the
        first OPEN sighting. board.beat() would be WRONG here — it
        refreshes last_beat and would mask exactly the staleness this
        tick exists to let the board see."""
        self.board.route_filter(())
        state = self.board.state(NET_LANE)
        if state in ("open", "evicted"):
            self.stats.note_open()

    def _sleep_ticking(self, seconds: float) -> None:
        deadline = time.monotonic() + seconds
        while not self._stop.is_set() and not self._aborted():
            self._tick()
            left = deadline - time.monotonic()
            if left <= 0:
                return
            time.sleep(min(_TICK_S, left))

    def _put_local(self, item) -> None:
        while not self._stop.is_set():
            try:
                self.local_queue.put(item, timeout=_TICK_S)
                return
            except queue.Full:
                if self._aborted():
                    return
                self._tick()

    def _route_remote(self) -> bool:
        """Cheap pre-filter for the next dispatch: evaluate the board
        and rule out an open/evicted lane early. Deliberately
        CLAIM-FREE — ``consult_and_route`` inside ``_send_request`` is
        the one routing arbiter (it claims half-open probes and
        accounts the route atomically); claiming the probe here via
        ``route_filter((NET_LANE,))`` would make the arbiter refuse
        it and strand the lane half-open until the probe ages out.
        Never forced: the local fallback always exists, so
        ``routes_after_open`` stays an invariant, not an apology."""
        if self._evicted:
            return False
        self.board.route_filter(())   # pure evaluation tick
        state = self.board.state(NET_LANE)
        if state in ("open", "evicted"):
            self.stats.note_open()
            return False
        return state in ("healthy", "suspect", "half_open")

    def _send_raw(self, frame: bytes) -> bool:
        with self._send_lock:
            sock = self._sock
            if sock is None:
                return False
            try:
                wire.send_frame(sock, frame)
            except (NetResetError, NetPartialFrameError,
                    NetTimeoutError) as exc:
                if not self._eos_sent:
                    self.stats.err(fault_reason(exc))
                self._sock = None
                self._connected.clear()
                try:
                    sock.close()
                except OSError:
                    pass
                return False
        self.stats.inc("wire_bytes", len(frame))
        return True

    def _send_request(self, path, card) -> bool:
        """Own this dispatch remotely: window slot, sequence number,
        REQ frame. True = the window owns it now (even if the send
        itself failed — reconnect will resend it); False = route it
        locally instead."""
        if not self._ensure_connected():
            return False
        # block for a window slot, re-consulting the route so a
        # wedged peer (full window, circuit opening) releases new
        # arrivals to the local path instead of convoying behind it
        while True:
            with self._wlock:
                if len(self._window) < self.settings.resend_window:
                    # the routing claim and the slot are taken under
                    # one window lock hold: consult_and_route decides
                    # and accounts atomically on the board (a route
                    # claimed here can never be a routes_after_open
                    # violation), and the entry enters the window only
                    # on a claimed route — never before, so a
                    # concurrent reconnect's _resend_all cannot ship
                    # an entry whose route was about to be refused
                    if not self.board.consult_and_route(NET_LANE):
                        return False
                    seq = self._seq_next
                    self._seq_next += 1
                    frame = wire.encode_frame(
                        wire.REQ, wire.encode_req(path, card), seq=seq,
                        deadline=float(getattr(card, "deadline_s", 0.0)
                                       or 0.0),
                        depth=len(self._window))
                    self._window[seq] = _WindowEntry(seq, path, card,
                                                     frame)
                    break
            if self._stop.is_set() or self._aborted():
                return False
            self._tick()
            self._maybe_resend()
            if not self._connected.is_set() \
                    and not self._ensure_connected():
                return False
            state = self.board.state(NET_LANE)
            if state not in ("healthy", "suspect", "half_open"):
                return False
            time.sleep(_TICK_S)
        self.board.note_enqueue(NET_LANE)
        self.stats.inc("frames_sent")
        self.stats.inc("remote")
        self._send_raw(frame)   # failure is fine: reconnect resends
        return True

    def _tx_loop(self) -> None:
        markers = 0
        while not self._stop.is_set():
            if self._aborted():
                return
            try:
                item = self.filename_queue.get(timeout=_TICK_S)
            except queue.Empty:
                self._tick()
                self._maybe_resend()
                if not self._connected.is_set() and not self._evicted \
                        and self._window_nonempty():
                    self._ensure_connected()
                continue
            if item is None:
                markers += 1
                if markers >= self.num_markers:
                    break
                continue
            _, path, card = item
            if self._route_remote() and self._send_request(path, card):
                continue
            self.stats.inc("local")
            self._put_local((None, path, card))
        self._drain_window()
        # markers ONLY after the drain: every remote injection into
        # the step-0 output queues precedes end-of-stream downstream,
        # and every leftover reroute precedes the markers locally
        for _ in range(markers):
            self._put_local(None)
        self._send_eos()

    def _window_nonempty(self) -> bool:
        with self._wlock:
            return bool(self._window) or self._finalizing > 0

    def _drain_window(self) -> None:
        """Wait (bounded) for in-flight responses, then reroute the
        leftovers locally — nothing strands."""
        budget = (self._io_s * (self.settings.max_retries + 2)
                  + sum(self._schedule) / 1000.0 + 1.0)
        deadline = time.monotonic() + budget
        while self._window_nonempty() and not self._evicted \
                and not self._aborted() \
                and time.monotonic() < deadline:
            self._tick()
            self._maybe_resend()
            if not self._connected.is_set():
                self._ensure_connected()
            time.sleep(_TICK_S)
        self._reroute_window()

    def _send_eos(self) -> None:
        self._eos_sent = True
        if self._connected.is_set():
            self._send_raw(wire.encode_frame(wire.EOS))

    # -- receiver (netedge-rx) ----------------------------------------

    def _rx_loop(self) -> None:
        while not self._stop.is_set():
            # the tx thread swaps _sock on every reconnect — take the
            # same lock that guards the swap, or this loop can read a
            # half-published reference mid-redial
            with self._send_lock:
                sock = self._sock
            if sock is None:
                if self._evicted:
                    return
                self._connected.wait(_TICK_S)
                continue
            try:
                (ftype, _flags, depth, seq, _deadline,
                 payload) = wire.read_frame(sock)
            except NetTimeoutError:
                self.stats.err("net_timeout")
                self._resend_due.set()
                continue
            except NetCorruptFrameError as exc:
                self.stats.err("net_corrupt")
                self._dead_letter(getattr(exc, "seq", 0))
                continue
            except (NetResetError, NetPartialFrameError) as exc:
                if not self._stop.is_set() and not self._eos_sent:
                    self.stats.err(fault_reason(exc))
                self._mark_dead(sock)
                continue
            except OSError:
                self._mark_dead(sock)
                continue
            self.stats.inc("wire_bytes",
                           wire.HEADER_SIZE + len(payload))
            self.board.beat(NET_LANE)
            self.stats.gauge_depth(depth)
            if ftype == wire.BEAT:
                self.stats.inc("beats")
            elif ftype == wire.ACK:
                self._on_ack(seq)
            elif ftype == wire.DATA:
                self._on_data(seq, payload)
            elif ftype == wire.DISPOSE:
                self._on_dispose(seq, payload)

    def _on_ack(self, seq: int) -> None:
        with self._wlock:
            entry = self._window.get(seq)
            if entry is not None and not entry.acked:
                entry.acked = True
                self.stats.inc("frames_acked")

    def _pop_entry(self, seq: int) -> Optional[_WindowEntry]:
        """Terminal-event pop, or the dedup verdict: a response for a
        sequence no longer in the window already terminated — a
        resend's twin, dropped here and never dispatched twice."""
        with self._wlock:
            entry = self._window.pop(seq, None)
            if entry is not None:
                self._finalizing += 1
        if entry is None:
            # classification site: this arrival is a duplicate
            self.stats.inc("dup_arrivals")
        return entry

    def _finalized(self) -> None:
        with self._wlock:
            self._finalizing -= 1

    def _on_data(self, seq: int, payload: bytes) -> None:
        entry = self._pop_entry(seq)
        if entry is None:
            # drop-action site (--check: dedup_drops == dup_arrivals)
            self.stats.inc("dedup_drops")
            return
        batch, non_tensors, card, row_bytes = wire.decode_data(payload)
        self.stats.inc("frame_bytes", row_bytes)
        with self._pad_lock:
            self._pad["pad_rows"] += batch.max_rows - batch.valid
            self._pad["total_rows"] += batch.max_rows
            self._pad["emissions"] += 1
        if self.deadline_stats is not None and expired(card):
            site = deadline_site("netedge")
            card.mark_shed(site)
            self.fault_stats.record_shed(site)
            self.deadline_stats.record(site)
            dispose_requests(self.counter, self.num_videos,
                             self.termination)
        else:
            self._inject((DirectPayload((batch,)), non_tensors, card))
        self.board.note_settle(NET_LANE)
        self._finalized()

    def _on_dispose(self, seq: int, payload: bytes) -> None:
        entry = self._pop_entry(seq)
        if entry is None:
            self.stats.inc("dedup_drops")
            return
        outcome, reason, card = wire.decode_dispose(payload)
        if outcome == "failed":
            self.fault_stats.record_failure([card.id], 0, reason)
            self.board.note_failure(NET_LANE)
        else:
            self.fault_stats.record_shed(reason)
            if self.deadline_stats is not None \
                    and reason.endswith(":deadline_expired"):
                self.deadline_stats.record(reason)
        dispose_requests(self.counter, self.num_videos,
                         self.termination)
        self.board.note_settle(NET_LANE)
        self._finalized()

    def _dead_letter(self, seq: int) -> None:
        """A corrupt frame consumed in full: framing survived, the
        request it carried did not (permanent per the taxonomy)."""
        with self._wlock:
            entry = self._window.pop(seq, None)
            if entry is not None:
                self._finalizing += 1
        if entry is None:
            return
        card = entry.card
        card.mark_failed("net_corrupt")
        self.fault_stats.record_failure([card.id], 0, "net_corrupt")
        self.board.note_failure(NET_LANE)
        self.board.note_settle(NET_LANE)
        dispose_requests(self.counter, self.num_videos,
                         self.termination)
        self._finalized()

    def _inject(self, item) -> None:
        while not self._stop.is_set():
            try:
                self.inject_queue.put(item, timeout=_TICK_S)
                return
            except queue.Full:
                if self.termination.terminated:
                    return


# -- the peer process -------------------------------------------------

class _PeerConnGone(Exception):
    """Internal: this connection is over; back to accept()."""


class NetEdgePeer:
    """The ingest peer: step 0 of the same config, served over the
    wire. One connection at a time (the edge has one sender); a beat
    thread keeps liveness flowing while the model runs."""

    GUARDED_BY = {"_conn": "_send_lock"}
    UNGUARDED_OK = {
        "_ledger": "serve-thread confined",
        "_fired": "serve-thread confined",
        "_depth": "written by the serve thread; the beat thread's "
                  "bare int read is a depth gauge (staleness shows "
                  "up as one conservative beat)",
        "_wedge_until": "written by the serve thread; the beat "
                        "thread reads a float gate (worst case one "
                        "extra beat before wedging)",
        "model": "published by build_model before the listener binds "
                 "and the beat thread starts",
    }

    def __init__(self, config, listen: str, seed: int = 0):
        from rnb_tpu.faults import FaultPlan
        self.config = config
        self.listen_addr = parse_addr(listen)
        self.step = config.steps[0]
        self.settings = (NetEdgeSettings.from_config(config.netedge)
                         or NetEdgeSettings(connect="127.0.0.1:1"))
        self._io_s = self.settings.io_timeout_ms / 1000.0
        self.plan = FaultPlan.resolve(config.fault_plan)
        self.device = self.step.groups[0].devices[0]
        self._fired: set = set()
        self._ledger: "OrderedDict[int, tuple]" = OrderedDict()
        self._depth = 0
        self._wedge_until = 0.0
        self._send_lock = lockwitness.lock("NetEdgePeer._send_lock")
        self._conn: Optional[socket.socket] = None
        self._beat_stop = threading.Event()
        self.model = None

    def build_model(self) -> None:
        """Construct (and warm up) the stage BEFORE binding the
        listener, so the advertised port means 'ready to serve'."""
        from rnb_tpu.utils.class_utils import load_class
        model_class = load_class(self.step.model)
        self.model = model_class(self.device,
                                 **self.step.kwargs_for_group(0))

    # -- framing helpers ----------------------------------------------

    def _send(self, frame: bytes) -> None:
        with self._send_lock:
            conn = self._conn
            if conn is None:
                raise _PeerConnGone()
            try:
                wire.send_frame(conn, frame)
            except (NetResetError, NetPartialFrameError,
                    NetTimeoutError) as exc:
                raise _PeerConnGone() from exc

    def _beat_loop(self) -> None:
        period = self.settings.beat_ms / 1000.0
        while not self._beat_stop.wait(period):
            if time.monotonic() < self._wedge_until:
                continue   # a wedged peer is SILENT — that is the point
            try:
                self._send(wire.encode_frame(wire.BEAT,
                                             depth=self._depth))
            except _PeerConnGone:
                return

    # -- request serving ----------------------------------------------

    def _net_hit(self, kind: str, rid: int):
        """One-shot fault draw: re-matches on resends (the plan is
        stateless) but fires once per (fault, request)."""
        if self.plan is None:
            return None
        hit = self.plan.net_fault(kind, rid)
        if hit is None:
            return None
        key = (hit[0], rid)
        if key in self._fired:
            return None
        self._fired.add(key)
        return hit[1]

    def _run_model(self, path, card):
        """The executor containment recipe, single-request edition:
        transient retries per the step budget, permanent degrade."""
        card.add_device(self.device.label)
        card.record("runner%d_start" % 0)
        attempt = 0
        while True:
            card.record("inference%d_start" % 0)
            try:
                tensors, non_tensors, out_card = self.model(
                    None, path, card)
                break
            except TransientError as exc:
                if attempt >= self.step.max_retries:
                    card.mark_failed(fault_reason(exc))
                    return None, fault_reason(exc)
                attempt += 1
                time.sleep(self.step.retry_backoff_ms / 1000.0)
            except PermanentError as exc:
                card.mark_failed(fault_reason(exc))
                return None, fault_reason(exc)
        out_card.record("inference%d_finish" % 0)
        if tensors is None or len(tensors) != 1:
            out_card.mark_failed("net_bad_emission")
            return None, "net_bad_emission"
        return (tensors[0], non_tensors, out_card), None

    def _serve_req(self, seq: int, deadline: float,
                   payload: bytes) -> None:
        if seq in self._ledger:
            # dedup ledger: a resend after ack-loss re-serves the
            # cached outcome — never a second model call
            ack, response = self._ledger[seq]
            self._send(ack)
            self._send(response)
            return
        path, card = wire.decode_req(payload)
        rid = int(card.id)
        hit = self._net_hit("net_reset", rid)
        if hit is not None:
            if hit.get("fatal"):
                os._exit(1)   # the chaos peer kill: no goodbye
            with self._send_lock:
                conn, self._conn = self._conn, None
            if conn is not None:
                conn.setsockopt(
                    socket.SOL_SOCKET, socket.SO_LINGER,
                    struct.pack("ii", 1, 0))   # RST, not FIN
                conn.close()
            raise _PeerConnGone()
        self._depth += 1
        ack = wire.encode_frame(wire.ACK, seq=seq, depth=self._depth)
        self._send(ack)
        hit = self._net_hit("net_timeout", rid)
        if hit is not None:
            # the wedge: acked, then silent — beats pause too, so the
            # main side's distress is inflight age + beat staleness
            stall_s = float(hit.get("ms", 0.0)) / 1000.0
            self._wedge_until = time.monotonic() + stall_s
            time.sleep(stall_s)
        if deadline > 0 and time.time() > deadline:
            site = deadline_site("netedge")
            card.mark_shed(site)
            response = wire.encode_frame(
                wire.DISPOSE, wire.encode_dispose("shed", site, card),
                seq=seq, depth=self._depth)
        else:
            served, reason = self._run_model(path, card)
            if served is None:
                response = wire.encode_frame(
                    wire.DISPOSE,
                    wire.encode_dispose("failed", reason, card),
                    seq=seq, depth=self._depth)
            else:
                batch, non_tensors, out_card = served
                response = wire.encode_frame(
                    wire.DATA,
                    wire.encode_data(batch, non_tensors, out_card),
                    seq=seq, depth=self._depth)
        self._depth -= 1
        self._ledger[seq] = (ack, response)
        while len(self._ledger) > _PEER_LEDGER_MAX:
            self._ledger.popitem(last=False)
        if self._net_hit("net_corrupt", rid) is not None:
            # flip one payload byte AFTER the crc was computed
            corrupt = bytearray(response)
            corrupt[-1] ^= 0xff
            self._send(bytes(corrupt))
            return
        if self._net_hit("net_partial_frame", rid) is not None:
            half = response[:max(1, len(response) // 2)]
            self._send(half)
            with self._send_lock:
                conn, self._conn = self._conn, None
            if conn is not None:
                conn.close()
            raise _PeerConnGone()
        self._send(response)

    # -- accept loop --------------------------------------------------

    def serve_forever(self, port_file: Optional[str] = None) -> int:
        lsock = socket.create_server(self.listen_addr)
        lsock.settimeout(1.0)
        port = lsock.getsockname()[1]
        if port_file:
            tmp = port_file + ".tmp"
            with open(tmp, "w") as f:
                f.write("%d\n" % port)
            os.replace(tmp, port_file)   # atomic: readers never see ""
        served_any = False
        idle_since = time.monotonic()
        try:
            while True:
                try:
                    conn, _ = lsock.accept()
                except socket.timeout:
                    if served_any and (time.monotonic() - idle_since
                                       > _PEER_IDLE_S):
                        return 3   # orphaned: main died without EOS
                    continue
                served_any = True
                conn.settimeout(self._io_s)
                if self._serve_conn(conn):
                    return 0       # EOS: clean end of stream
                idle_since = time.monotonic()
        finally:
            lsock.close()

    def _serve_conn(self, conn) -> bool:
        """One connection until EOS (-> True) or it dies (-> False)."""
        # published under the send lock: a previous connection's beat
        # thread may still be draining through _send — it must observe
        # either the old (dead) socket or the new one, never a torn
        # handoff
        with self._send_lock:
            self._conn = conn
        self._beat_stop.clear()
        beat = threading.Thread(target=self._beat_loop,
                                name="netedge-beat", daemon=True)
        beat.start()
        try:
            while True:
                try:
                    (ftype, _flags, _depth, seq, deadline,
                     payload) = wire.read_frame(conn)
                except NetTimeoutError:
                    continue   # idle sender; beats still flowing
                except (NetResetError, NetPartialFrameError,
                        NetCorruptFrameError):
                    return False
                if ftype == wire.EOS:
                    return True
                if ftype == wire.REQ:
                    try:
                        self._serve_req(seq, deadline, payload)
                    except _PeerConnGone:
                        return False
        finally:
            self._beat_stop.set()
            beat.join(timeout=2.0)
            with self._send_lock:
                conn, self._conn = self._conn, None
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass


def spawn_peer(config_path: str, settings: NetEdgeSettings,
               seed: int = 0, timeout_s: float = 60.0):
    """Launch the ingest peer as a real second process (same config
    file the main process runs) and wait for its bound port. Returns
    ``(proc, "host:port")``; the caller owns termination. The child
    inherits the environment (XLA_FLAGS, RNB_FAULT_PLAN) so both
    sides resolve the same fault plan."""
    listen = settings.listen or "127.0.0.1:0"
    host, _ = parse_addr(listen)
    tmpdir = tempfile.mkdtemp(prefix="rnb-netedge-")
    port_file = os.path.join(tmpdir, "port")
    cmd = [sys.executable, "-m", "rnb_tpu.netedge", "--serve",
           "--config", config_path, "--listen", listen,
           "--port-file", port_file, "--seed", str(int(seed))]
    proc = subprocess.Popen(cmd, env=dict(os.environ))
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                "netedge peer exited rc=%d before binding its port"
                % proc.returncode)
        if os.path.exists(port_file):
            with open(port_file) as f:
                port = int(f.read().strip())
            return proc, "%s:%d" % (host, port)
        time.sleep(0.05)
    proc.terminate()
    raise RuntimeError("netedge peer did not bind within %.0fs"
                       % timeout_s)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m rnb_tpu.netedge",
        description="RnB-TPU ingest peer: serve step 0 of a pipeline "
                    "config over the netedge wire protocol.")
    parser.add_argument("--serve", action="store_true", required=True,
                        help="run the ingest peer (the only mode)")
    parser.add_argument("--config", required=True,
                        help="pipeline config JSON (same file the "
                             "main process runs)")
    parser.add_argument("--listen", default="127.0.0.1:0",
                        help="host:port to bind (port 0 = ephemeral)")
    parser.add_argument("--port-file", default=None,
                        help="write the bound port here once serving")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    from rnb_tpu.config import load_config
    config = load_config(args.config)
    peer = NetEdgePeer(config, args.listen, seed=args.seed)
    peer.build_model()
    return peer.serve_forever(port_file=args.port_file)


if __name__ == "__main__":
    sys.exit(main())
