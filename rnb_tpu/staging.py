"""Zero-copy fused decode staging + pipelined host->device transfer.

Round 5 measured the single bench-host core at 98% saturation with the
two dominant terms being raw byte movement: ``device_put`` staging
(49.3% of the window) and decode-output assembly + decode wait (22.1%)
— see RESULTS.md round 5 and the motivation in ``rnb_tpu/cache.py``.
The clip cache removes those terms for popularity-skewed *hits*; this
module removes them for the miss/uniform hot path itself:

* **StagingPool** — per-(loader, bucket-shape) sets of pre-allocated
  C-contiguous host slots with an explicit lifecycle
  (``free -> decoding -> transferring -> free``). The fusing loader
  plans row placement at submit time, so the native
  ``DecodePool.submit_into`` decodes each request **directly into its
  disjoint row-slice of a slot** — the fused batch is assembled by the
  decoder itself and the per-emission ``np.empty`` + per-row memcpy
  (``loader.emit_alloc`` / ``loader.emit_copy``) vanish on the native
  path. A slot is recycled only after every planned decode retired its
  reference AND every transfer from it is confirmed complete; slot
  exhaustion backpressures the submitter (counted ``acquire_waits``,
  never silently dropped).

* **TransferWorker** — a dedicated per-stage thread that issues
  ``device_put`` for fused batch N while batch N+1 decodes into the
  next slot (double/triple buffering via the ``staging_slots`` config
  knob; opt-in per step via ``transfer_async``). The executor thread
  hands a finished assembly off and immediately returns to
  submitting/harvesting; completed transfers surface back through the
  stage's ``take_ready()`` hook, which the executor drains ahead of
  new input (rnb_tpu.runner publish handoff).

Alias safety (the subtle part): on some backends — notably the CPU
backend tier-1 runs on — ``jax.device_put`` of a host array may
*alias* the host buffer instead of copying (alignment-dependent).
Recycling an aliased slot would corrupt a live in-flight batch, so
transfer confirmation probes the produced array's buffer pointer
against the slot's memory range; an aliased slot gets a **fresh
backing buffer** before reuse (counted ``reallocs``) and the old
buffer's ownership rides with the device array. On real TPUs the
transfer is a genuine host->HBM copy, the probe never fires, and slots
recycle with zero allocation.

Padding bytes stay zeroed exactly as on the seed copy path, so staged
and copied emissions are byte-identical end to end (golden-logit
parity, ``tests/test_staging.py``).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from rnb_tpu import lockwitness, trace
from rnb_tpu.utils.lazy_jax import jax_numpy as _jax_numpy

#: slot lifecycle states (kept as strings for cheap introspection)
FREE, DECODING, TRANSFERRING = "free", "decoding", "transferring"


def _aliases(device_array, buf: np.ndarray) -> bool:
    """Does ``device_array``'s backing buffer live inside ``buf``'s
    memory range? Conservative: an unprobeable array is treated as
    aliased (the slot gets a fresh buffer — one allocation, never a
    corruption)."""
    try:
        ptr = int(device_array.unsafe_buffer_pointer())
    except Exception:
        return True
    base = int(buf.ctypes.data)
    return base <= ptr < base + int(buf.nbytes)


class StagingSlot:
    """One pre-allocated C-contiguous host buffer plus its lifecycle
    accounting. ``refs`` counts planned decodes whose rows are still
    live in the buffer; ``transfers`` counts handed-off-but-unconfirmed
    device transfers; ``pending_confirm`` holds device arrays whose
    transfer completion is confirmed lazily at the next acquire (the
    double-buffering gate). ``dtype`` follows the owning loader's wire
    dtype (uint8 pixel/plane rows; int16 packed dct coefficient
    rows)."""

    __slots__ = ("buf", "shape", "dtype", "state", "refs", "transfers",
                 "pending_confirm", "tainted")

    def __init__(self, shape: Tuple[int, ...], dtype=np.uint8):
        self.dtype = np.dtype(dtype)
        self.buf = np.empty(shape, dtype=self.dtype)
        self.shape = tuple(shape)
        self.state = FREE
        self.refs = 0
        self.transfers = 0
        self.pending_confirm: List[Any] = []
        #: a confirmed transfer aliased this buffer: replace it before
        #: the slot is handed out again
        self.tainted = False

    @property
    def nbytes(self) -> int:
        return int(self.buf.nbytes)


class StagingPool:
    """Bounded per-shape pools of staging slots with counted
    backpressure.

    All mutators take the pool lock; ``acquire`` blocks (counted) when
    every slot of the requested shape is busy — exhaustion
    backpressures the submitter, it never drops work. A worker-thread
    failure recorded via :meth:`fail` re-raises out of ``acquire`` and
    :meth:`raise_if_failed` so a dead transfer pipeline can never
    silently hang the executor.
    """

    #: declared concurrency contract (rnb-lint RNB-C001/C003); the
    #: ``_available`` Condition is built ON ``_lock``, so holding
    #: either is the same critical section
    GUARDED_BY = {
        "_slots": "_lock",
        "_error": "_lock",
        "num_acquires": "_lock",
        "num_acquire_waits": "_lock",
        "num_staged_batches": "_lock",
        "num_copied_batches": "_lock",
        "num_bypassed_batches": "_lock",
        "num_reallocs": "_lock",
    }

    def __init__(self, shapes: Sequence[Tuple[int, ...]],
                 slots_per_shape: int, dtype=np.uint8):
        if slots_per_shape < 1:
            raise ValueError("slots_per_shape must be >= 1, got %r"
                             % (slots_per_shape,))
        self.dtype = np.dtype(dtype)
        self._lock = lockwitness.lock("StagingPool._lock")
        self._available = threading.Condition(self._lock)
        self._slots: Dict[Tuple[int, ...], List[StagingSlot]] = {}
        for shape in shapes:
            shape = tuple(int(d) for d in shape)
            if shape not in self._slots:
                self._slots[shape] = [StagingSlot(shape, self.dtype)
                                      for _ in range(slots_per_shape)]
        self.slots_per_shape = int(slots_per_shape)
        self._error: Optional[BaseException] = None
        # exact counters, surfaced end-to-end (BenchmarkResult /
        # log-meta `Staging:` line / parse_utils)
        self.num_acquires = 0
        self.num_acquire_waits = 0
        self.num_staged_batches = 0
        self.num_copied_batches = 0
        self.num_bypassed_batches = 0
        self.num_reallocs = 0

    # -- lifecycle ----------------------------------------------------

    def _claim_pending_locked(self, slot: StagingSlot) -> List[Any]:
        """Detach a just-claimed slot's lazily-pending transfers for
        confirmation OUTSIDE the lock: the slot's state is already
        DECODING, so no other acquirer can reach it, and the device
        sync the confirmation blocks on must never run under the pool
        lock (rnb-lint RNB-C005 — it would stall every producer and
        worker behind one device round-trip)."""
        lockwitness.require("StagingPool._lock")
        pending, slot.pending_confirm = slot.pending_confirm, []
        return pending

    def _confirm_claimed(self, slot: StagingSlot,
                         pending: List[Any]) -> None:
        """Retire the detached pending transfers of a slot this caller
        claimed: wait for the device copies, probe for host-buffer
        aliasing, and swap in a fresh buffer when a device array took
        ownership of this one. Runs WITHOUT the pool lock — the slot
        is owner-private (state DECODING) until the caller hands it
        on, so ``buf``/``tainted`` cannot race."""
        if pending:
            jax, _ = _jax_numpy()
            for arr in pending:
                jax.block_until_ready(arr)
                if _aliases(arr, slot.buf):
                    slot.tainted = True
        if slot.tainted:
            # the device array owns (aliases) the old buffer — replace
            # it rather than corrupt the live batch. One np.empty, no
            # copy: still cheaper than the seed alloc+memcpy path.
            slot.buf = np.empty(slot.shape, dtype=slot.dtype)
            slot.tainted = False
            with self._lock:
                self.num_reallocs += 1

    def _acquirable_locked(self, shape) -> Optional[StagingSlot]:
        for slot in self._slots[shape]:
            if slot.state == FREE and slot.refs == 0 \
                    and slot.transfers == 0:
                return slot
        return None

    def try_acquire(self, shape) -> Optional[StagingSlot]:
        """A free slot of ``shape`` (confirm-processed), or None."""
        shape = tuple(int(d) for d in shape)
        with self._lock:
            self.raise_if_failed_locked()
            if shape not in self._slots:
                # shapes are pre-registered at construction; an unseen
                # shape (e.g. a config change) gets its own sub-pool
                self._slots[shape] = [StagingSlot(shape, self.dtype)
                                      for _ in range(self.slots_per_shape)]
            slot = self._acquirable_locked(shape)
            if slot is None:
                return None
            slot.state = DECODING
            self.num_acquires += 1
            pending = self._claim_pending_locked(slot)
        self._confirm_claimed(slot, pending)
        return slot

    def acquire(self, shape) -> StagingSlot:
        """Blocking acquire: counted backpressure on exhaustion."""
        slot = self.try_acquire(shape)
        if slot is not None:
            return slot
        shape = tuple(int(d) for d in shape)
        with self._lock:
            self.num_acquire_waits += 1
        from rnb_tpu import hostprof
        with hostprof.section("staging.acquire_wait"), \
                trace.span("staging.acquire_wait"):
            while True:
                pending = None
                with self._available:
                    self.raise_if_failed_locked()
                    slot = self._acquirable_locked(shape)
                    if slot is None:
                        self._available.wait(timeout=0.05)
                        slot = self._acquirable_locked(shape)
                    if slot is not None:
                        slot.state = DECODING
                        self.num_acquires += 1
                        pending = self._claim_pending_locked(slot)
                if slot is not None:
                    self._confirm_claimed(slot, pending)
                    return slot

    def add_ref(self, slot: StagingSlot) -> None:
        """One more planned decode targets rows of this slot."""
        with self._lock:
            slot.refs += 1

    def retire_ref(self, slot: StagingSlot) -> None:
        """A planned decode is done with its rows (emitted, failed,
        discarded, or re-decoded elsewhere)."""
        with self._available:
            slot.refs -= 1
            assert slot.refs >= 0, "staging ref underflow"
            self._maybe_free_locked(slot)

    def begin_transfer(self, slot: StagingSlot) -> None:
        """The slot's bytes are being handed to a device transfer."""
        with self._lock:
            slot.state = TRANSFERRING
            slot.transfers += 1

    def finish_transfer(self, slot: StagingSlot, device_array=None
                        ) -> None:
        """A transfer was issued. With ``device_array`` given, its
        completion is confirmed lazily at the slot's next acquire (the
        executor never blocks); pass None when the caller already
        confirmed (:meth:`confirm_now`, the transfer worker)."""
        with self._available:
            if device_array is not None:
                slot.pending_confirm.append(device_array)
            slot.transfers -= 1
            assert slot.transfers >= 0, "staging transfer underflow"
            self._maybe_free_locked(slot)

    def confirm_now(self, slot: StagingSlot, device_array) -> None:
        """Synchronously confirm one transfer (off-executor callers:
        the TransferWorker). Blocks until the device copy is done,
        probes for aliasing, then releases the transfer hold."""
        jax, _ = _jax_numpy()
        jax.block_until_ready(device_array)
        with self._available:
            if _aliases(device_array, slot.buf):
                slot.tainted = True
            slot.transfers -= 1
            assert slot.transfers >= 0, "staging transfer underflow"
            self._maybe_free_locked(slot)

    def _maybe_free_locked(self, slot: StagingSlot) -> None:
        if slot.refs == 0 and slot.transfers == 0:
            slot.state = FREE
            self._available.notify_all()

    # -- accounting ---------------------------------------------------

    def note_staged(self) -> None:
        with self._lock:
            self.num_staged_batches += 1

    def note_copied(self) -> None:
        with self._lock:
            self.num_copied_batches += 1

    def note_bypassed(self) -> None:
        """An emission shipped with **zero** host->device bytes — every
        row was gathered on-device from the page allocator (full
        cache-hit or feature-page hit, rnb_tpu.pager). No slot was
        acquired and no transfer issued; counted separately so the
        staged/copied split still foots against transfer-carrying
        emissions only."""
        with self._lock:
            self.num_bypassed_batches += 1

    def fail(self, exc: BaseException) -> None:
        """Record a transfer-pipeline failure; every later acquire /
        raise_if_failed re-raises it (no silent hang)."""
        with self._available:
            if self._error is None:
                self._error = exc
            self._available.notify_all()

    def raise_if_failed_locked(self) -> None:
        if self._error is not None:
            raise self._error

    def raise_if_failed(self) -> None:
        with self._lock:
            self.raise_if_failed_locked()

    def available(self, shape=None) -> int:
        """Free-slot count (one shape, or all) — test/introspection."""
        with self._lock:
            pools = ([self._slots[tuple(int(d) for d in shape)]]
                     if shape is not None else self._slots.values())
            return sum(1 for slots in pools for s in slots
                       if s.state == FREE and s.refs == 0
                       and s.transfers == 0)

    def total_slots(self) -> int:
        with self._lock:
            return sum(len(s) for s in self._slots.values())

    def snapshot(self) -> Dict[str, int]:
        """Point-in-time counter copy for reports (additive across
        stage instances, like rnb_tpu.cache snapshots)."""
        with self._lock:
            return {
                "slots": sum(len(s) for s in self._slots.values()),
                "slot_bytes": sum(slot.nbytes
                                  for slots in self._slots.values()
                                  for slot in slots),
                "acquires": self.num_acquires,
                "acquire_waits": self.num_acquire_waits,
                "staged_batches": self.num_staged_batches,
                "copied_batches": self.num_copied_batches,
                "bypassed_batches": self.num_bypassed_batches,
                "reallocs": self.num_reallocs,
            }


def aggregate_snapshots(snapshots: List[Dict[str, int]]) -> Dict[str, int]:
    """Sum per-instance staging snapshots into one job-wide record
    (every counter is additive; slots/slot_bytes sum because each
    instance owns its own pool)."""
    total = {"slots": 0, "slot_bytes": 0, "acquires": 0,
             "acquire_waits": 0, "staged_batches": 0,
             "copied_batches": 0, "bypassed_batches": 0,
             "reallocs": 0}
    for snap in snapshots:
        for k in total:
            total[k] += int(snap.get(k, 0))
    return total


class TransferWorker:
    """A single dedicated thread running host->device transfer jobs.

    The executor thread enqueues a finished fused assembly and returns
    to submitting/harvesting immediately; the worker issues the
    ``device_put`` (batch N transferring while batch N+1 decodes into
    the next slot). Job errors are captured — not swallowed — and
    re-raised on the executor thread via :meth:`raise_if_failed`
    (wired through the stage's ``take_ready()``).
    """

    GUARDED_BY = {
        "_jobs": "_lock",
        "_outstanding": "_lock",
        "_error": "_lock",
        "_closed": "_lock",
    }

    def __init__(self, name: str = "rnb-transfer",
                 pool: Optional[StagingPool] = None):
        self._jobs: "deque[Optional[Callable[[], None]]]" = deque()
        self._lock = lockwitness.lock("TransferWorker._lock")
        self._wake = threading.Condition(self._lock)
        self._outstanding = 0
        self._error: Optional[BaseException] = None
        self._pool = pool
        self._closed = False
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def submit(self, job: Callable[[], None]) -> None:
        with self._wake:
            if self._closed:
                raise RuntimeError("TransferWorker is closed")
            self.raise_if_failed_locked()
            self._jobs.append(job)
            self._outstanding += 1
            self._wake.notify_all()

    def outstanding(self) -> int:
        with self._lock:
            return self._outstanding

    def raise_if_failed_locked(self) -> None:
        if self._error is not None:
            raise self._error

    def raise_if_failed(self) -> None:
        with self._lock:
            self.raise_if_failed_locked()

    def _run(self) -> None:
        while True:
            with self._wake:
                while not self._jobs and not self._closed:
                    self._wake.wait(timeout=0.1)
                if not self._jobs and self._closed:
                    return
                job = self._jobs.popleft()
            try:
                with trace.span("transfer.job"):
                    job()
            except BaseException as exc:  # noqa: BLE001 — surfaced
                with self._wake:
                    if self._error is None:
                        self._error = exc
                if self._pool is not None:
                    self._pool.fail(exc)
            finally:
                with self._wake:
                    self._outstanding -= 1
                    self._wake.notify_all()

    def close(self, timeout: float = 30.0) -> None:
        """Drain remaining jobs (transfers keep slot accounting
        balanced even on the abort path), then stop the thread."""
        with self._wake:
            self._closed = True
            self._wake.notify_all()
        self._thread.join(timeout=timeout)
