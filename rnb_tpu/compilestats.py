"""Compile/warmup accounting: count the shapes a stage's jit sees.

The pipeline's one structural promise about XLA is *bounded
compilation*: every jitted stage applier is warmed on its full shape
vocabulary before the measured window opens, and no new signature —
i.e. no compile — may appear mid-run. rnb-lint's RNB-G006 enforces
that statically from config declarations; this module verifies it
**dynamically**, per stage instance, against what the hot loop
actually dispatched — which is also how the ragged path's headline
claim ("exactly one compiled shape per stage") is asserted at runtime
rather than taken on faith.

Counting is deliberately signature-based, not XLA-event-based: the
persistent compilation cache (rnb_tpu.benchmark) turns repeat-run
compiles into cache hits, so backend compile events undercount on
warm caches — while the number of *distinct (shape, dtype) entry
signatures* a jitted applier is fed equals the number of executables
the run requires, cache or no cache. One tracker per stage instance;
the executor freezes it when the measured window opens
(rnb_tpu.runner), so any signature first seen after the freeze is a
mid-run recompile and is surfaced as ``steady_new`` in the
``Compiles:`` accounting (parse_utils --check fails on nonzero).

Warmup wall-time rides the same sink: the executor times each stage's
construction (weights + warmup compiles happen in ``__init__``) and
the launcher writes the per-step ``Warmup:`` log-meta line — under
ragged, collapsing the per-bucket warmup matrix to one compile is a
measurable launch-latency win, and this is where it is measured.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple


def signature_of(*arrays) -> tuple:
    """The jit-entry signature of a positional array argument list:
    per-argument (shape, dtype-name). Scalars and non-array leaves
    hash by type (a traced scalar never forks an executable)."""
    sig = []
    for a in arrays:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is None:
            sig.append((type(a).__name__,))
        else:
            sig.append((tuple(int(d) for d in shape), str(dtype)))
    return tuple(sig)


class SignatureTracker:
    """Distinct jit-entry signatures of one stage applier, split at
    the measured-window freeze. Locked: under ``transfer_async`` the
    fusing loader's preprocess dispatch (and so its observe) runs on
    the transfer-worker thread while cache hits dispatch on the
    executor thread — the lock costs nanoseconds per *emission* and
    keeps the counters exact."""

    __slots__ = ("_warmup", "_steady_new", "_steady_calls", "_frozen",
                 "_lock")

    GUARDED_BY = {
        "_warmup": "_lock",
        "_steady_new": "_lock",
        "_steady_calls": "_lock",
        "_frozen": "_lock",
    }

    def __init__(self):
        self._warmup: set = set()
        self._steady_new: set = set()
        self._steady_calls = 0
        self._frozen = False
        self._lock = threading.Lock()

    def observe(self, *arrays) -> None:
        """Note one dispatch's entry signature."""
        sig = signature_of(*arrays)
        with self._lock:
            if not self._frozen:
                self._warmup.add(sig)
                return
            self._steady_calls += 1
            if sig not in self._warmup:
                # a signature warmup never saw: this dispatch is (or
                # would be, modulo the persistent cache) a mid-run
                # compile
                self._steady_new.add(sig)

    def freeze(self) -> None:
        """The measured window opened: signatures from here on must
        already be warmed."""
        with self._lock:
            self._frozen = True

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "warmup": len(self._warmup),
                "steady_new": len(self._steady_new),
                "steady_calls": self._steady_calls,
            }


def aggregate_compile_records(records: List[Tuple[int, float, dict]]
                              ) -> Tuple[Dict[str, dict],
                                         Dict[str, float]]:
    """Per-instance ``(step_idx, warmup_s, sigs-or-None)`` records ->
    (``{step: {warmup, steady_new, steady_calls}}`` summed over the
    step's instances for tracker-owning stages,
    ``{step: warmup_seconds}`` summed over every instance)."""
    compiles: Dict[str, dict] = {}
    warmup: Dict[str, float] = {}
    for step_idx, warmup_s, sigs in records:
        key = "step%d" % int(step_idx)
        warmup[key] = round(warmup.get(key, 0.0) + float(warmup_s), 3)
        if sigs is None:
            continue
        agg = compiles.setdefault(
            key, {"warmup": 0, "steady_new": 0, "steady_calls": 0})
        for field in agg:
            agg[field] += int(sigs.get(field, 0))
    return compiles, warmup
