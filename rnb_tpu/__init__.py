"""rnb_tpu — a TPU-native streaming video-analytics inference framework.

A ground-up JAX/XLA re-design of the capabilities of snuspl/rnb (the
"Replicate & Batch" multi-GPU video inference benchmark): a client emits
video requests at Poisson intervals into a configurable multi-stage
pipeline (decode -> neural net stages -> aggregation) with replication,
partitioning, segmentation, dynamic batching and content-aware routing —
except that stages here map onto TPU-core sub-meshes inside a single
controller process, stage hand-off is device-to-device transfer between
shardings, and all model compute is jit-compiled XLA with static shapes.

Architecture differences vs the reference (see SURVEY.md):
  * one controller process + one Python thread per runner instance
    (JAX async dispatch provides concurrency; the reference used one OS
    process + private CUDA stream per GPU, reference runner.py:41-44)
  * immutable device arrays handed through channels (the reference used
    mutable shared CUDA tensors + CUDA IPC, reference control.py:19-46);
    ring-slot credits provide equivalent backpressure semantics
  * fixed max-shape batches + explicit valid-row counts everywhere, so
    XLA compiles each stage exactly once (the reference sliced tensors to
    the valid batch size, reference runner.py:109-114)
"""

__version__ = "0.1.0"

from rnb_tpu.telemetry import TimeCard, TimeCardList, TimeCardSummary
from rnb_tpu.stage import PaddedBatch, StageModel
from rnb_tpu.selector import QueueSelector, RoundRobinSelector
from rnb_tpu.video_path_provider import (VideoPathIterator,
                                         ZipfPathIterator)
from rnb_tpu.cache import ClipCache
from rnb_tpu.faults import (CorruptVideoError, FaultPlan, PermanentError,
                            TransientError, classify_error)
