"""Request-stream sources: iterables of video paths for the client.

A concrete iterator is named by string in the JSON config
(``video_path_iterator``) and instantiated inside the client thread.
Implementations should cycle indefinitely (e.g. ``itertools.cycle``) so
any requested video count can be served regardless of dataset size.

Reference parity: video_path_provider.py:1-14.
"""

from __future__ import annotations


class VideoPathIterator:
    """Base contract: iterate video paths (or synthetic video ids) forever."""

    def __init__(self):
        pass

    def __iter__(self):
        raise NotImplementedError
