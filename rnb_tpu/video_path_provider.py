"""Request-stream sources: iterables of video paths for the client.

A concrete iterator is named by string in the JSON config
(``video_path_iterator``) and instantiated inside the client thread.
Implementations should cycle indefinitely (e.g. ``itertools.cycle``) so
any requested video count can be served regardless of dataset size.

Reference parity: video_path_provider.py:1-14.
"""

from __future__ import annotations

import os

VIDEO_EXTENSIONS = (".y4m", ".mjpg", ".mjpeg")


def scan_video_tree(root: str, extensions=VIDEO_EXTENSIONS) -> list:
    """Sorted video paths from a root/label/video dataset tree (the
    reference's Kinetics layout, models/r2p1d/model.py:86-113). The
    one dataset-layout scan — the r2p1d iterator and
    scripts/decode_bench.py both delegate here; it lives in this
    jax-free module so tooling can scan datasets without importing
    the model stack."""
    videos = []
    for label in sorted(os.listdir(root)):
        label_dir = os.path.join(root, label)
        if os.path.isdir(label_dir):
            videos.extend(
                os.path.join(label_dir, v)
                for v in sorted(os.listdir(label_dir))
                if v.endswith(extensions))
    return videos


class VideoPathIterator:
    """Base contract: iterate video paths (or synthetic video ids) forever."""

    def __init__(self):
        pass

    def __iter__(self):
        raise NotImplementedError

    def dataset(self):
        """The finite video universe behind this iterator, or None when
        unknown. Popularity-skewed wrappers (:class:`ZipfPathIterator`)
        use it to assign ranks; iterators without a materialized list
        may return None and the wrapper falls back to drawing distinct
        items from the cycle."""
        return None


#: fallback universe size when a base iterator exposes no dataset():
#: bounded so materializing distinct items from an endless cycle halts
DEFAULT_UNIVERSE = 1024


def zipf_probabilities(universe: int, s: float):
    """Rank-frequency Zipf pmf over ranks 1..universe: p(r) ∝ r^-s.

    ``s=0`` degenerates to the uniform distribution; larger ``s``
    concentrates mass on the head. Pure numpy, importable by tooling
    without the model stack.
    """
    import numpy as np
    if universe < 1:
        raise ValueError("universe must be >= 1, got %r" % (universe,))
    if s < 0:
        raise ValueError("zipf skew s must be >= 0, got %r" % (s,))
    weights = np.arange(1, universe + 1, dtype=np.float64) ** -float(s)
    return weights / weights.sum()


class ZipfPathIterator(VideoPathIterator):
    """Popularity-skewed wrapper: draw paths from a base iterator's
    universe with Zipf(s) rank frequencies.

    Rank assignment is deterministic — rank r maps to the r-th video of
    the base iterator's (sorted-scan) dataset — and the draw stream is
    seeded, so the same (dataset, s, universe, seed) produces the
    identical request sequence: the reproducibility the cache benchmark
    cell needs for honest A/Bs. ``universe`` restricts popularity to
    the first N videos and clamps to the dataset size (a universe
    larger than the dataset cannot invent videos).

    Config: root key ``popularity: {"dist": "zipf", "s": 1.1,
    "universe": 64}`` (rnb_tpu.config) — the client wraps the
    configured ``video_path_iterator`` with this class.
    """

    def __init__(self, base, s: float = 1.0, universe=None, seed=None):
        super().__init__()
        videos = base.dataset() if hasattr(base, "dataset") else None
        if videos is None:
            # endless-cycle base: materialize the first `universe`
            # distinct items (the cycle revisits its population, so a
            # full lap yields every id)
            want = int(universe) if universe else DEFAULT_UNIVERSE
            seen, ordered = set(), []
            for video in base:
                if video in seen:
                    break
                seen.add(video)
                ordered.append(video)
                if len(ordered) >= want:
                    break
            videos = ordered
        if not videos:
            raise ValueError("ZipfPathIterator needs a non-empty video "
                             "universe")
        videos = list(videos)
        if universe is not None:
            universe = min(int(universe), len(videos))
            if universe < 1:
                raise ValueError("popularity universe must be >= 1")
            videos = videos[:universe]
        self._videos = videos
        self.s = float(s)
        self.seed = seed
        import numpy as np
        self._probabilities = zipf_probabilities(len(videos), self.s)
        self._cumulative = np.cumsum(self._probabilities)
        self._cumulative[-1] = 1.0  # guard float drift at the tail

    def dataset(self):
        return list(self._videos)

    def __iter__(self):
        import numpy as np
        rng = np.random.default_rng(self.seed)
        videos, cumulative = self._videos, self._cumulative
        while True:
            # inverse-CDF draw: O(log U) per request vs rng.choice's
            # O(U) — the client hot loop runs per arrival
            yield videos[int(np.searchsorted(cumulative, rng.random(),
                                             side="right"))]
