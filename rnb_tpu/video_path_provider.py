"""Request-stream sources: iterables of video paths for the client.

A concrete iterator is named by string in the JSON config
(``video_path_iterator``) and instantiated inside the client thread.
Implementations should cycle indefinitely (e.g. ``itertools.cycle``) so
any requested video count can be served regardless of dataset size.

Reference parity: video_path_provider.py:1-14.
"""

from __future__ import annotations

import os

VIDEO_EXTENSIONS = (".y4m", ".mjpg", ".mjpeg")


def scan_video_tree(root: str, extensions=VIDEO_EXTENSIONS) -> list:
    """Sorted video paths from a root/label/video dataset tree (the
    reference's Kinetics layout, models/r2p1d/model.py:86-113). The
    one dataset-layout scan — the r2p1d iterator and
    scripts/decode_bench.py both delegate here; it lives in this
    jax-free module so tooling can scan datasets without importing
    the model stack."""
    videos = []
    for label in sorted(os.listdir(root)):
        label_dir = os.path.join(root, label)
        if os.path.isdir(label_dir):
            videos.extend(
                os.path.join(label_dir, v)
                for v in sorted(os.listdir(label_dir))
                if v.endswith(extensions))
    return videos


class VideoPathIterator:
    """Base contract: iterate video paths (or synthetic video ids) forever."""

    def __init__(self):
        pass

    def __iter__(self):
        raise NotImplementedError
