"""Load generators: the request streams that drive a pipeline.

``poisson_client`` emits one video request per draw of an exponential
inter-arrival time (mean ``mean_interval_ms``) — the open-loop streaming
workload. ``bulk_client`` enqueues ``num_videos`` requests as fast as
possible — the max-throughput mode selected by ``-mi 0``. Both stamp a
fresh TimeCard (``enqueue_filename``) per request.

A full filename queue is handled per the config's overload policy:
``"abort"`` (default, reference parity) treats it as a fatal
configuration failure; ``"shed"`` drops the *new* request with a
counted ``shed`` outcome — disposed toward the run target through the
shared counter — and keeps streaming, so a load spike degrades
success-rate instead of killing the job.

Capability parity with the reference clients (client.py:11-106), as
threads in the controller process instead of a separate OS process.
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
from typing import Optional

import numpy as np

from rnb_tpu import metrics, trace
from rnb_tpu.control import NUM_EXIT_MARKERS, FaultStats, \
    InferenceCounter, TerminationFlag, TerminationState, \
    dispose_requests, send_exit_markers
from rnb_tpu.telemetry import TimeCard
from rnb_tpu.utils.class_utils import load_class

SHED_SITE = "filename_queue"


def _client(video_path_iterator_path: str, filename_queue: "queue.Queue",
            termination: TerminationState, sta_bar: threading.Barrier,
            fin_bar: threading.Barrier, *, mean_interval_ms: int,
            num_videos: Optional[int], seed: Optional[int],
            num_markers: int = NUM_EXIT_MARKERS,
            overload_policy: str = "abort",
            fault_stats: Optional[FaultStats] = None,
            counter: Optional[InferenceCounter] = None,
            target_num_videos: Optional[int] = None,
            popularity: Optional[dict] = None,
            deadline_budget_s: Optional[float] = None) -> None:
    try:
        source = load_class(video_path_iterator_path)()
        if popularity is not None:
            # popularity-skewed replay (config root key "popularity"):
            # wrap the configured iterator with the seeded Zipf sampler
            # so the request stream models head-heavy real traffic —
            # the workload shape the decoded-clip cache (rnb_tpu.cache)
            # is benchmarked under. Seeded with the job seed: same
            # seed => identical request sequence.
            from rnb_tpu.video_path_provider import ZipfPathIterator
            # derive a CHILD seed for the popularity draws: seeding the
            # video stream and the Poisson interarrival rng below with
            # the identical value would hand both generators the same
            # PCG64 state, deterministically coupling video rank with
            # the following gap length — a correlation the Poisson+Zipf
            # workload must not carry
            zipf_seed = (None if seed is None
                         else np.random.SeedSequence([seed, 1]))
            source = ZipfPathIterator(source,
                                      s=popularity.get("s", 1.0),
                                      universe=popularity.get("universe"),
                                      seed=zipf_seed)
        iterator = iter(source)
        rng = np.random.default_rng(seed)
    except Exception:
        traceback.print_exc()
        termination.raise_flag(TerminationFlag.INTERNAL_ERROR)
        iterator = None

    try:
        sta_bar.wait()
    except threading.BrokenBarrierError:
        pass

    try:
        if iterator is not None:
            video_count = 0
            while not termination.terminated:
                if num_videos is not None and video_count >= num_videos:
                    break
                video_path = next(iterator)
                time_card = TimeCard(video_count)
                time_card.record("enqueue_filename")
                if deadline_budget_s is not None:
                    # absolute per-request deadline (rnb_tpu.health,
                    # root 'deadline' config key): every stage
                    # boundary downstream sheds the request once this
                    # wall-clock instant passes, instead of computing
                    # doomed work
                    time_card.deadline_s = \
                        time_card.timings["enqueue_filename"] \
                        + deadline_budget_s
                # flow anchor for the request's cross-stage trace
                # chain + an event-driven arrival-rate counter track
                # (rnb_tpu.trace; one None test each when tracing off)
                trace.instant("client.enqueue", rid=video_count)
                trace.counter("client.enqueued", video_count + 1)
                # live-metrics arrival feed (rnb_tpu.metrics): the
                # windowed arrival rate the future cross-host ingest
                # tier schedules on; one None test each when off
                metrics.counter("client.requests")
                metrics.mark("client.arrivals")
                try:
                    filename_queue.put_nowait((None, video_path, time_card))
                except queue.Full:
                    if overload_policy == "shed":
                        # overload: drop the NEW request, count it, and
                        # keep the stream alive (it still consumes an
                        # id and counts toward the run target — the
                        # pipeline owes it no further work)
                        trace.instant("client.shed", rid=video_count)
                        metrics.counter("client.shed")
                        time_card.mark_shed(SHED_SITE)
                        if fault_stats is not None:
                            fault_stats.record_shed(SHED_SITE)
                        if counter is not None \
                                and target_num_videos is not None:
                            dispose_requests(counter, target_num_videos,
                                             termination)
                    else:
                        # counted telemetry (log-meta 'Queue
                        # overflows:' / BenchmarkResult
                        # .queue_overflows) instead of a stray
                        # stdout warning; the termination flag
                        # still records the abort
                        if fault_stats is not None:
                            fault_stats.record_overflow(SHED_SITE)
                        termination.raise_flag(
                            TerminationFlag.FILENAME_QUEUE_FULL)
                        break
                video_count += 1
                if mean_interval_ms > 0:
                    time.sleep(rng.exponential(mean_interval_ms / 1000.0))
    except Exception:
        traceback.print_exc()
        termination.raise_flag(TerminationFlag.INTERNAL_ERROR)
    finally:
        send_exit_markers(filename_queue, num_markers, termination)
        try:
            fin_bar.wait()
        except threading.BrokenBarrierError:
            pass


def poisson_client(video_path_iterator_path, filename_queue,
                   mean_interval_ms, termination, sta_bar, fin_bar,
                   seed: Optional[int] = None,
                   num_markers: int = NUM_EXIT_MARKERS,
                   **fault_kwargs) -> None:
    """Open-loop Poisson stream until the job terminates
    (reference client.py:11-59)."""
    _client(video_path_iterator_path, filename_queue, termination, sta_bar,
            fin_bar, mean_interval_ms=mean_interval_ms, num_videos=None,
            seed=seed, num_markers=num_markers, **fault_kwargs)


def bulk_client(video_path_iterator_path, filename_queue, num_videos,
                termination, sta_bar, fin_bar,
                seed: Optional[int] = None,
                num_markers: int = NUM_EXIT_MARKERS,
                **fault_kwargs) -> None:
    """Enqueue num_videos requests immediately — max-throughput mode
    (reference client.py:61-106)."""
    _client(video_path_iterator_path, filename_queue, termination, sta_bar,
            fin_bar, mean_interval_ms=0, num_videos=num_videos, seed=seed,
            num_markers=num_markers, **fault_kwargs)
