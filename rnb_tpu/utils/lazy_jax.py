"""Module-cached lazy (jax, jax.numpy) import.

Hot paths must not pay per-call interpreter import machinery
(sys.modules lookup + module-dict binding, ~1 us each) — the PR 2
Batcher hoist, generalized into the one helper every per-request code
path shares. Modules that cannot import jax at module top (import cost
for jax-free tooling, or circularity) call :func:`jax_numpy` once per
call site; the tuple is bound after the first call.

The static hot-path lint (rnb_tpu.analysis.hotpath, rule RNB-H002)
flags ``import`` statements inside per-request code; this helper is
the prescribed fix.
"""

from __future__ import annotations

_jax_mods = None


def jax_numpy():
    """-> the (jax, jax.numpy) module pair, imported once per process."""
    global _jax_mods
    if _jax_mods is None:
        import jax
        import jax.numpy as jnp
        _jax_mods = (jax, jnp)
    return _jax_mods
