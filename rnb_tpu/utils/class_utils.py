"""Dynamic class loading — the plugin mechanism behind string-named
models, selectors and path iterators in JSON configs.

Reference parity: utils/class_utils.py:1-8.
"""

from __future__ import annotations

import importlib


def load_class(full_class_path: str):
    """Load a class from a dotted path like ``pkg.module.ClassName``."""
    module_path, _, class_name = full_class_path.rpartition(".")
    if not module_path:
        raise ValueError("expected a dotted class path, got %r"
                         % full_class_path)
    module = importlib.import_module(module_path)
    try:
        return getattr(module, class_name)
    except AttributeError as e:
        raise ImportError("module %r has no class %r"
                          % (module_path, class_name)) from e
