"""Failure taxonomy and the deterministic fault-injection plan.

The containment layer (rnb_tpu.runner) sorts every exception escaping a
stage's model call into one of three classes:

* **transient** — worth retrying on the same request: I/O blips, an
  injected :class:`InjectedTransientError`, any plain ``OSError``. The
  executor retries up to the step's ``max_retries`` with
  ``retry_backoff_ms`` of sleep between attempts; an exhausted budget
  degrades the error to permanent.
* **permanent** — the request can never succeed: a corrupt or
  unsupported video (:class:`CorruptVideoError`), an injected
  :class:`InjectedPermanentError`. The request's TimeCard is stamped
  ``failed`` and routed to the controller's dead-letter record; the
  stream continues.
* **fatal** — everything else. Stage-init failures, ring-protocol
  violations and genuine bugs abort the job with ``INTERNAL_ERROR``
  exactly as before the containment layer existed; containment must
  never paper over a broken pipeline.

:class:`FaultPlan` is the chaos side of the same taxonomy: a seeded,
fully deterministic injection schedule (from the config's
``fault_plan`` key or the ``RNB_FAULT_PLAN`` env JSON) that raises
classified errors, adds latency, or stalls a stage at chosen request
ids or probabilities — so failure-path behavior is reproducible in
tests and benchmarks instead of depending on broken files showing up.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Any, Dict, List, Optional

#: classification outcomes (string constants, compared by identity)
TRANSIENT = "transient"
PERMANENT = "permanent"
FATAL = "fatal"

ENV_PLAN = "RNB_FAULT_PLAN"


class TransientError(Exception):
    """Base for errors worth retrying on the same request."""


class PermanentError(Exception):
    """Base for errors that can never succeed for this request."""


class CorruptVideoError(PermanentError, ValueError):
    """Malformed/truncated/unsupported video input.

    Subclasses ValueError so pre-containment callers (and tests) that
    caught the decoders' plain ValueError keep working.
    """


class TransientDecodeError(TransientError, ValueError):
    """Decode-layer I/O error (e.g. the native decoder's read failure)
    — the file may be fine on a retry. Subclasses ValueError for the
    same back-compat reason as :class:`CorruptVideoError`."""


class InjectedTransientError(TransientError):
    """Raised by a :class:`FaultPlan` 'transient' fault."""


class InjectedPermanentError(PermanentError):
    """Raised by a :class:`FaultPlan` 'permanent' fault."""


class NetRefusedError(TransientError):
    """The peer actively refused the dial (nothing listening yet, or
    the listener's backlog is gone). Transient: the peer may come up —
    the sender retries the dial under its capped backoff schedule."""
    fault_reason = "net_refused"


class NetResetError(TransientError):
    """The established connection died mid-stream (RST / broken pipe /
    EOF inside a frame boundary). Transient: the sender reconnects and
    resends every non-terminal window entry; the receiver's dedup
    ledger keeps the resend from double-dispatching."""
    fault_reason = "net_reset"


class NetTimeoutError(TransientError):
    """A configured socket timeout (``netedge.io_timeout_ms``) expired
    waiting on the peer — wedged, not dead. Transient: the sender
    resends the oldest unacked frame / reconnects; the health board
    has usually opened the circuit from beat staleness well before
    this fires (that ordering is asserted by ``make netchaos``)."""
    fault_reason = "net_timeout"


class NetPartialFrameError(TransientError):
    """The stream ended inside a length-prefixed frame (short read).
    Transient: framing is lost so the connection is torn down and
    re-dialed; unacked frames are resent on the fresh connection."""
    fault_reason = "net_partial_frame"


class NetCorruptFrameError(PermanentError):
    """A frame arrived complete but its CRC32 did not match. Permanent
    for the REQUEST it carried: retrying cannot un-corrupt recorded
    bytes, so the request is dead-lettered with reason ``net_corrupt``
    — but framing stayed in sync, so the connection survives."""
    fault_reason = "net_corrupt"


class LaneDeathError(Exception):
    """A replica lane's executor is dead (chaos 'replica_crash' /
    'replica_stall' fault kinds).

    NOT part of the transient/permanent taxonomy: the *lane* fails, not
    the request. The executor (rnb_tpu.runner) intercepts it before
    classification on replica lanes — dead-letters the in-service
    dispatch, evicts the lane on the health board
    (rnb_tpu.health.LaneHealthBoard), and re-enqueues the lane's
    queued-but-undispatched work onto healthy siblings. Escaping to
    :func:`classify_error` (a plan targeting a non-replica step with no
    lane to evict) it classifies FATAL, so a misconfigured chaos plan
    aborts loudly instead of silently containing a lane-scale failure
    as one dead-lettered request.
    """

    def __init__(self, message: str, fate: str):
        super().__init__(message)
        #: "crash" (immediate death) or "stall" (wedged, then dead)
        self.fate = fate


#: OSErrors that are deterministic verdicts on the input, not blips —
#: retrying an open() of a file that is not there cannot succeed, so
#: burning the retry budget on them would only delay the dead-letter
_PERMANENT_OS_ERRORS = (FileNotFoundError, IsADirectoryError,
                        NotADirectoryError, PermissionError)


def classify_error(exc: BaseException) -> str:
    """-> TRANSIENT | PERMANENT | FATAL for one caught exception.

    Only explicitly classified errors (and OSError, the canonical
    host-I/O blip — minus its deterministic subtypes like
    FileNotFoundError, which are permanent) are contained; anything
    unrecognized is FATAL so a genuine bug still aborts the job loudly.
    """
    if isinstance(exc, TransientError):
        return TRANSIENT
    if isinstance(exc, PermanentError):
        return PERMANENT
    if isinstance(exc, _PERMANENT_OS_ERRORS):
        return PERMANENT
    if isinstance(exc, OSError):
        return TRANSIENT
    return FATAL


def fault_reason(exc: BaseException) -> str:
    """Stable short reason string for dead-letter accounting."""
    reason = getattr(exc, "fault_reason", None)
    if reason:
        return str(reason)
    if isinstance(exc, LaneDeathError):
        return "replica-%s" % exc.fate
    if isinstance(exc, InjectedTransientError):
        return "injected-transient"
    if isinstance(exc, InjectedPermanentError):
        return "injected-permanent"
    if isinstance(exc, CorruptVideoError):
        return "corrupt-video"
    if isinstance(exc, TransientDecodeError):
        return "decode-io"
    if isinstance(exc, FileNotFoundError):
        return "file-not-found"
    if isinstance(exc, OSError):
        return "os-error"
    return type(exc).__name__.lower()


#: kinds that address the cross-host ingest EDGE (rnb_tpu.netedge)
#: instead of a pipeline step: net_refused fires at the sender's dial,
#: the other four fire on the peer while serving a matched request.
#: net_corrupt is the one permanent member (a recorded-bytes verdict);
#: the rest are transient per the PR 1 taxonomy.
NET_KINDS = ("net_refused", "net_reset", "net_timeout",
             "net_partial_frame", "net_corrupt")

VALID_KINDS = ("transient", "permanent", "latency", "stall",
               "replica_crash", "replica_stall") + NET_KINDS

#: kinds that kill a replica LANE rather than fail a request — they
#: carry an optional 'lane' (queue index) address and fire exactly once
#: per matching (step, lane) executor
LANE_KINDS = ("replica_crash", "replica_stall")

#: the one edge-addressed site key used in the deterministic draw for
#: NET_KINDS faults (there is exactly one edge, and it is not a step)
NET_SITE = -1


def validate_plan(spec: Any) -> Dict[str, Any]:
    """Validate a fault-plan dict; returns it. Raises ValueError with a
    config-grade message on any structural problem (rnb_tpu.config
    wraps this into a ConfigError at parse time)."""
    if not isinstance(spec, dict):
        raise ValueError("fault plan must be a JSON object, got %r"
                         % type(spec).__name__)
    seed = spec.get("seed", 0)
    if not isinstance(seed, int):
        raise ValueError("fault plan 'seed' must be an integer")
    faults = spec.get("faults")
    if not isinstance(faults, list):
        raise ValueError("fault plan needs a 'faults' list")
    for idx, f in enumerate(faults):
        where = "fault %d" % idx
        if not isinstance(f, dict):
            raise ValueError("%s must be an object" % where)
        kind = f.get("kind")
        if kind not in VALID_KINDS:
            raise ValueError("%s: 'kind' must be one of %s, got %r"
                             % (where, list(VALID_KINDS), kind))
        step = f.get("step")
        if step is not None and (not isinstance(step, int) or step < 0):
            raise ValueError("%s: 'step' must be a non-negative integer "
                             "(or omitted for every step)" % where)
        ids = f.get("request_ids")
        prob = f.get("probability")
        if (ids is None) == (prob is None):
            raise ValueError("%s needs exactly one of 'request_ids' or "
                             "'probability'" % where)
        if ids is not None and (
                not isinstance(ids, list)
                or not all(isinstance(i, int) for i in ids)):
            raise ValueError("%s: 'request_ids' must be a list of "
                             "integers" % where)
        if prob is not None and not (isinstance(prob, (int, float))
                                     and 0.0 <= prob <= 1.0):
            raise ValueError("%s: 'probability' must be in [0, 1]" % where)
        fatal = f.get("fatal")
        if fatal is not None:
            if kind != "net_reset":
                raise ValueError("%s: 'fatal' only applies to net_reset "
                                 "faults (it kills the peer process)"
                                 % where)
            if not isinstance(fatal, bool):
                raise ValueError("%s: 'fatal' must be a boolean" % where)
        if kind in NET_KINDS:
            if step is not None:
                raise ValueError("%s: net faults address the edge; "
                                 "'step' is not allowed" % where)
            if f.get("lane") is not None:
                raise ValueError("%s: net faults address the edge; "
                                 "'lane' is not allowed" % where)
            if "times" in f:
                raise ValueError("%s: 'times' only applies to "
                                 "transient/permanent faults" % where)
            if kind == "net_timeout":
                ms = f.get("ms")
                if not (isinstance(ms, (int, float)) and ms >= 0):
                    raise ValueError("%s: net_timeout faults need a "
                                     "non-negative 'ms' (peer wedge "
                                     "duration)" % where)
            elif "ms" in f:
                raise ValueError("%s: among net faults only net_timeout "
                                 "takes 'ms'" % where)
        elif kind in ("latency", "stall", "replica_stall"):
            ms = f.get("ms")
            if not (isinstance(ms, (int, float)) and ms >= 0):
                raise ValueError("%s: %r faults need a non-negative 'ms'"
                                 % (where, kind))
            if "times" in f:
                # would be silently ignored (delay kinds fire on
                # attempt 0 only; lane deaths are permanent by nature)
                # — reject like any other typo
                raise ValueError("%s: 'times' only applies to "
                                 "transient/permanent faults" % where)
        elif kind == "replica_crash":
            if "ms" in f:
                raise ValueError("%s: 'ms' only applies to latency/"
                                 "stall/replica_stall faults" % where)
            if "times" in f:
                raise ValueError("%s: 'times' only applies to "
                                 "transient/permanent faults" % where)
        else:
            if "ms" in f:
                raise ValueError("%s: 'ms' only applies to latency/"
                                 "stall/replica_stall faults" % where)
            times = f.get("times", 1)
            if not (isinstance(times, int) and times >= 1):
                raise ValueError("%s: 'times' must be a positive integer"
                                 % where)
        lane = f.get("lane")
        if lane is not None:
            # any kind may be lane-addressed: replica_crash/
            # replica_stall target the lane itself; a lane-addressed
            # 'latency'/'stall' is the SLOW-LANE class (one replica
            # degrades while its siblings stay fast — the shape
            # hedged re-dispatch exists for); error kinds emulate a
            # lane-local fault domain
            if not (isinstance(lane, int) and not isinstance(lane, bool)
                    and lane >= 0):
                raise ValueError("%s: 'lane' must be a non-negative "
                                 "queue index" % where)
        reason = f.get("reason")
        if reason is not None and not isinstance(reason, str):
            raise ValueError("%s: 'reason' must be a string" % where)
        unknown = set(f) - {"kind", "step", "request_ids", "probability",
                            "ms", "times", "reason", "lane", "fatal"}
        if unknown:
            raise ValueError("%s has unknown keys %s"
                             % (where, sorted(unknown)))
    unknown = set(spec) - {"seed", "faults"}
    if unknown:
        raise ValueError("fault plan has unknown keys %s"
                         % sorted(unknown))
    return spec


def _hash_draw(seed: int, fault_idx: int, step_idx: int,
               request_id: int) -> float:
    """Deterministic uniform [0, 1) draw keyed by the fault site —
    stateless, so concurrent stage threads cannot perturb each other's
    draws (a shared RNG would make plans depend on thread scheduling)."""
    key = ("%d:%d:%d:%d" % (seed, fault_idx, step_idx, request_id))
    return zlib.crc32(key.encode()) / 2.0 ** 32


class FaultPlan:
    """A validated, deterministic fault-injection schedule.

    The executor consults two hooks per request:

    * :meth:`stall_ms` before the inference span — 'stall' faults wedge
      the stage thread there, so the induced delay lands in downstream
      queue-wait accounting (the queue behind the stage backs up);
    * :meth:`fire` immediately before each model-call attempt —
      'latency' faults sleep inside the inference span, 'transient' /
      'permanent' faults raise their classified error. Error faults
      fire on the first ``times`` attempts of a request (default 1), so
      an injected transient succeeds on retry — the shape the retry
      budget exists for.

    Matching is by TimeCard id. Both hooks accept one id or the id list
    of a fused TimeCardList batch: a fault matching ANY constituent
    affects the whole fused dispatch (the blast radius a real fault at
    a batched stage has), so plans targeting downstream-of-batcher
    steps fire instead of silently never matching.
    """

    def __init__(self, spec: Dict[str, Any]):
        spec = validate_plan(spec)
        self.seed = int(spec.get("seed", 0))
        self.faults: List[Dict[str, Any]] = list(spec.get("faults", []))
        # pre-resolve id lists to sets for the hot-loop membership test
        self._id_sets = [set(f["request_ids"])
                         if f.get("request_ids") is not None else None
                         for f in self.faults]

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        """Plan from the RNB_FAULT_PLAN env JSON, or None if unset."""
        raw = os.environ.get(ENV_PLAN)
        if not raw:
            return None
        try:
            spec = json.loads(raw)
        except json.JSONDecodeError as e:
            raise ValueError("%s is not valid JSON: %s" % (ENV_PLAN, e)) \
                from e
        return cls(spec)

    def check_steps(self, num_steps: int) -> None:
        """Reject fault 'step' indices outside the pipeline — a typo'd
        step would otherwise silently never fire while --check reports
        the plan active (the chaos run would then read as 'containment
        verified' without a single fault injected)."""
        for idx, f in enumerate(self.faults):
            step = f.get("step")
            if step is not None and step >= num_steps:
                raise ValueError(
                    "fault %d targets step %d but the pipeline has %d "
                    "step(s) (0..%d) — the fault would never fire"
                    % (idx, step, num_steps, num_steps - 1))

    @classmethod
    def resolve(cls, config_plan: Optional[Dict[str, Any]]
                ) -> Optional["FaultPlan"]:
        """The ONE precedence rule for plan resolution, shared by the
        launcher and --check so they can never disagree: the
        RNB_FAULT_PLAN env JSON overrides the config's ``fault_plan``
        key; None when neither is set."""
        plan = cls.from_env()
        if plan is None and config_plan is not None:
            plan = cls(config_plan)
        return plan

    @staticmethod
    def _as_ids(request_ids) -> tuple:
        return ((request_ids,) if isinstance(request_ids, int)
                else tuple(request_ids))

    @staticmethod
    def _lane_matches(fault: Dict[str, Any],
                      lane: Optional[int]) -> bool:
        """Lane-addressed faults fire only on the named replica lane
        (the executor passes its input-queue index); un-addressed
        faults fire anywhere."""
        fault_lane = fault.get("lane")
        return fault_lane is None or fault_lane == lane

    def _matches(self, fault_idx: int, fault: Dict[str, Any],
                 step_idx: int, request_ids: tuple) -> Optional[int]:
        """The first matching request id of the batch, or None."""
        step = fault.get("step")
        if step is not None and step != step_idx:
            return None
        ids = self._id_sets[fault_idx]
        for rid in request_ids:
            if ids is not None:
                if rid in ids:
                    return rid
            elif _hash_draw(self.seed, fault_idx, step_idx,
                            rid) < fault["probability"]:
                return rid
        return None

    def stall_ms(self, step_idx: int, request_ids,
                 lane: Optional[int] = None) -> float:
        """Total 'stall' milliseconds scheduled at this site (one id or
        a fused batch's id list — each fault contributes at most once
        per dispatch). A lane-addressed stall wedges only the named
        replica lane's dispatches (the slow-lane chaos class)."""
        request_ids = self._as_ids(request_ids)
        total = 0.0
        for idx, f in enumerate(self.faults):
            if f["kind"] == "stall" and self._lane_matches(f, lane) \
                    and self._matches(
                        idx, f, step_idx, request_ids) is not None:
                total += float(f["ms"])
        return total

    def fire(self, step_idx: int, request_ids,
             attempt: int = 0, lane: Optional[int] = None) -> None:
        """Sleep scheduled latency, then raise the first matching error
        fault whose ``times`` budget covers this attempt.

        ``lane`` is the calling executor's input-queue index on a
        replica-expanded step (None elsewhere): 'replica_crash' /
        'replica_stall' faults optionally address one lane with it and
        raise :class:`LaneDeathError` — a stall first wedges the
        executor for ``ms`` inside the dispatch (beats stop, the health
        board's circuit opens from the missing-liveness signal) before
        the lane is declared dead."""
        request_ids = self._as_ids(request_ids)
        for idx, f in enumerate(self.faults):
            kind = f["kind"]
            if kind not in LANE_KINDS or attempt > 0:
                continue
            if not self._lane_matches(f, lane):
                continue
            rid = self._matches(idx, f, step_idx, request_ids)
            if rid is None:
                continue
            fate = "crash" if kind == "replica_crash" else "stall"
            if kind == "replica_stall":
                time.sleep(float(f["ms"]) / 1000.0)
            exc = LaneDeathError(
                "injected %s at step %d lane %s (request %d)"
                % (kind, step_idx, lane, rid), fate)
            reason = f.get("reason")
            if reason:
                exc.fault_reason = reason
            raise exc
        for idx, f in enumerate(self.faults):
            kind = f["kind"]
            if kind == "latency" and attempt == 0 \
                    and self._lane_matches(f, lane) \
                    and self._matches(idx, f, step_idx,
                                      request_ids) is not None:
                time.sleep(float(f["ms"]) / 1000.0)
        for idx, f in enumerate(self.faults):
            kind = f["kind"]
            if kind not in ("transient", "permanent"):
                continue
            if attempt >= int(f.get("times", 1)):
                continue
            if not self._lane_matches(f, lane):
                continue
            rid = self._matches(idx, f, step_idx, request_ids)
            if rid is None:
                continue
            reason = f.get("reason")
            msg = ("injected %s fault at step %d, request %d (attempt %d)"
                   % (kind, step_idx, rid, attempt))
            if kind == "transient":
                exc: Exception = InjectedTransientError(msg)
            else:
                exc = InjectedPermanentError(msg)
            if reason:
                exc.fault_reason = reason
            raise exc

    def has_net_faults(self) -> bool:
        """True if any fault addresses the network edge — the launcher
        rejects such a plan when ``netedge`` is off, the same loud-typo
        posture as LANE_KINDS without replicas (the chaos run would
        otherwise read 'containment verified' with zero injections)."""
        return any(f["kind"] in NET_KINDS for f in self.faults)

    def net_fault(self, kind: str, request_id: int
                  ) -> Optional[tuple]:
        """First matching edge fault of ``kind`` for one request id, as
        ``(fault_idx, fault_dict)``, or None.

        Net faults draw at the edge site (:data:`NET_SITE`), not a
        step. The plan stays stateless (same thread-safety contract as
        :meth:`fire`), so the CALLER keeps a fired ledger keyed by the
        returned ``fault_idx`` + request id — a resend of the same
        request must re-match here without re-firing there, otherwise
        a net_reset would reset every resend of its victim forever.
        ``net_refused`` is consulted at dial time where no request is
        in scope: the sender passes its dial counter as the id, which
        keeps the draw deterministic per attempt.
        """
        request_ids = self._as_ids(request_id)
        for idx, f in enumerate(self.faults):
            if f["kind"] != kind:
                continue
            if self._matches(idx, f, NET_SITE, request_ids) is not None:
                return idx, f
        return None

    def describe(self) -> str:
        """One-line summary for --check output and logs."""
        kinds: Dict[str, int] = {}
        for f in self.faults:
            kinds[f["kind"]] = kinds.get(f["kind"], 0) + 1
        detail = ", ".join("%d %s" % (n, k)
                           for k, n in sorted(kinds.items()))
        return "seed=%d, %d fault(s)%s" % (
            self.seed, len(self.faults),
            (" [%s]" % detail) if detail else "")
