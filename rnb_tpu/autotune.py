"""Load-adaptive batching controller: SLO-aware hold/bucket autotuning.

Every batching knob in the pipeline used to be a static config
constant — ``max_hold_ms`` on the fusing loader, ``batch=N`` on the
Batcher, a fixed ``row_buckets`` set — and the round-5 matrix showed
the cost: bulk cells saturate the host (0.93-0.99 ``host_cpu_frac``)
while Poisson cells idle at 0.25-0.65, so low-rate traffic pays the
full hold-timeout latency for batches that never fill and high-rate
traffic is capped by whatever constant the config author guessed.
This module brings the R&B batch search online: a per-stage
:class:`BatchController` observes the live stream and, at every
emission decision, picks the hold deadline / accumulation target /
row bucket as the **largest batch whose predicted residual-fill wait
plus predicted service time stays inside a configured latency
budget** (``slo_ms``) — collapsing to immediate dispatch at low
arrival rates and growing to full warmed buckets at saturation.

Estimators (all EWMA, one ``ewma_alpha``):

* **arrival rate** — successive ``enqueue_filename`` TimeCard stamps
  (the client's wall-clock enqueue instants) feed an inter-arrival
  EWMA; the residual wait to grow a batch by ``k`` more requests is
  ``k * E[interarrival]``;
* **rows per request** — the loader's sampled clip counts (Batcher:
  incoming valid rows split over the emission's constituent requests,
  so the units match the per-request inter-arrival EWMA), converting
  a row-bucket target into a residual request count;
* **service time per (stage, row bucket)** — the stage's own
  dispatch->done span. The Batcher's is fed by the executor from the
  ``inference{i}_start``/``_finish`` stamps (the gap from the
  *last-swallowed* constituent's start, so accumulate-hold time is
  excluded); the fusing loader self-reports its batch-close ->
  ready-queue span (``AUTOTUNE_SELF_SERVICE``) because under
  ``transfer_async`` its emissions never return through a
  stamp-bearing call.

The budget is a **per-stage** bound on batching-added latency: hold
wait plus that stage's own batch service must stay inside ``slo_ms``.
It is not an end-to-end SLO — compose per-stage budgets for that.

Safety invariant: decisions are restricted to **already-warmed row
buckets** (the stage's validated ``row_buckets`` set, optionally
intersected with ``autotune.buckets``), so autotune can never trigger
a mid-run XLA recompile — the exact failure the static checker's
RNB-G006 exists to catch, and checks statically for the ``autotune``
root key too. Controller math is pure host arithmetic over the
existing monotonic/wall stamps: no syncs, no imports, no RNG — the
decision sequence is a deterministic function of the observed stamp
stream, so a seeded workload replays to identical decisions.

Config (root key, validated in rnb_tpu.config)::

    "autotune": {"enabled": true, "slo_ms": 50.0, "ewma_alpha": 0.2,
                 "min_hold_ms": 0.5, "max_hold_ms": 50.0,
                 "buckets": [6, 15]}   // optional candidate restriction

Per-step opt-out: ``"autotune": false`` on a pipeline step. Stages
advertise support via ``SUPPORTS_AUTOTUNE`` (R2P1DFusingLoader,
Batcher); the executor calls ``enable_autotune()`` after construction
and feeds the estimators from its hot loop (rnb_tpu.runner).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

from rnb_tpu import metrics, trace

#: defaults for the optional keys of the ``autotune`` root config
AUTOTUNE_DEFAULTS = {
    "slo_ms": 50.0,
    "ewma_alpha": 0.2,
    "min_hold_ms": 0.5,
    "max_hold_ms": 50.0,
}


@dataclasses.dataclass(frozen=True)
class AutotuneSettings:
    """Validated, defaulted view of the ``autotune`` root config key."""

    slo_ms: float
    ewma_alpha: float
    min_hold_ms: float
    max_hold_ms: float
    #: optional candidate restriction; None = every warmed bucket
    buckets: Optional[Tuple[int, ...]] = None

    @staticmethod
    def from_config(raw: Optional[dict]) -> Optional["AutotuneSettings"]:
        """Settings from the (schema-validated) config dict, or None
        when autotune is absent or ``enabled`` is false."""
        if not raw or not raw.get("enabled", True):
            return None
        buckets = raw.get("buckets")
        min_hold = float(raw.get("min_hold_ms",
                                 AUTOTUNE_DEFAULTS["min_hold_ms"]))
        # the omitted-max default tracks min_hold_ms exactly like
        # config-time validation (config.py) does — a flat 50.0 would
        # silently invert the clamp pair under min_hold_ms > 50
        max_hold = float(raw.get(
            "max_hold_ms",
            max(min_hold, AUTOTUNE_DEFAULTS["max_hold_ms"])))
        if max_hold < min_hold:
            raise ValueError(
                "autotune max_hold_ms (%g) must be >= min_hold_ms (%g)"
                % (max_hold, min_hold))
        return AutotuneSettings(
            slo_ms=float(raw.get("slo_ms", AUTOTUNE_DEFAULTS["slo_ms"])),
            ewma_alpha=float(raw.get("ewma_alpha",
                                     AUTOTUNE_DEFAULTS["ewma_alpha"])),
            min_hold_ms=min_hold,
            max_hold_ms=max_hold,
            buckets=(tuple(sorted(int(b) for b in buckets))
                     if buckets else None))


@dataclasses.dataclass(frozen=True)
class Decision:
    """One emission decision.

    ``target_rows`` — the row count worth accumulating toward (always
    a warmed candidate bucket); the stage emits once its ready rows
    reach it. ``hold_s`` — the hold deadline for the *oldest* ready
    request, measured from the instant it became ready; the stage
    emits once the oldest has waited this long (0.0 = dispatch now).
    ``bucket`` — the warmed bucket the current ready rows would pad
    to. ``immediate`` — the decision advises dispatching now (the
    hold already expired or growing the batch cannot meet the budget).
    """

    target_rows: int
    hold_s: float
    bucket: int
    immediate: bool


class BatchController:
    """Per-stage-instance online batch autotuner (module docstring).

    Single-threaded by design: the owning executor thread both feeds
    the estimators and asks for decisions, so no lock is needed (the
    snapshot is taken after the stage drained, like cache/staging).
    """

    def __init__(self, settings: AutotuneSettings,
                 candidates: Sequence[int], max_rows: int):
        if not candidates:
            raise ValueError("autotune needs at least one candidate "
                             "row bucket")
        self.slo_ms = float(settings.slo_ms)
        self.ewma_alpha = float(settings.ewma_alpha)
        self.min_hold_ms = float(settings.min_hold_ms)
        self.max_hold_ms = float(settings.max_hold_ms)
        self.candidates: Tuple[int, ...] = tuple(
            sorted(int(b) for b in candidates))
        self.max_rows = int(max_rows)
        # -- estimators (EWMA) ----------------------------------------
        self._ia_s: Optional[float] = None      # inter-arrival seconds
        self._last_enqueue: Optional[float] = None
        self._rows_per_req: Optional[float] = None
        self._service_s: Dict[int, float] = {}  # bucket -> seconds
        # -- accounting (snapshot/log-meta schema) --------------------
        self._decisions = 0
        self._immediate = 0
        self._held = 0
        self._emissions = 0
        self._bucket_counts: Dict[int, int] = {}
        self._deadline_us_min: Optional[int] = None
        self._deadline_us_max = 0
        self._deadline_us_sum = 0
        # every emission must be covered by a decision; forced
        # emissions (end-of-stream flush, slot-exhaustion drain) count
        # as immediate decisions so the invariant decisions >=
        # emissions holds on every path
        self._decided_since_emit = False

    @classmethod
    def for_stage(cls, settings: AutotuneSettings,
                  warmed_buckets: Sequence[int],
                  max_rows: int) -> "BatchController":
        """Build a controller for one stage instance, restricting the
        candidate set to the stage's *warmed* buckets. An
        ``autotune.buckets`` restriction naming an un-warmed bucket is
        rejected here (and statically by rnb-lint RNB-G006): a chosen
        un-warmed bucket would be a silent mid-run recompile."""
        warmed = tuple(sorted(int(b) for b in warmed_buckets))
        candidates = warmed
        if settings.buckets is not None:
            missing = sorted(set(settings.buckets) - set(warmed))
            if missing:
                raise ValueError(
                    "autotune.buckets %s include row bucket(s) %s this "
                    "stage never warms (warmed: %s) — decisions are "
                    "restricted to warmed buckets so autotune can never "
                    "recompile mid-run" % (list(settings.buckets),
                                           missing, list(warmed)))
            candidates = settings.buckets
        return cls(settings, candidates, max_rows)

    # -- estimator feeds ----------------------------------------------

    def _ewma(self, old: Optional[float], obs: float) -> float:
        if old is None:
            return obs
        a = self.ewma_alpha
        return a * obs + (1.0 - a) * old

    def observe_enqueue(self, t_enqueue: float) -> None:
        """One request's client enqueue stamp (wall clock); successive
        stamps feed the inter-arrival EWMA. Out-of-order stamps (fused
        upstream emissions interleaving) clamp to zero gap — a burst
        reads as a burst, never as negative time."""
        if self._last_enqueue is not None:
            dt = t_enqueue - self._last_enqueue
            if dt < 0.0:
                dt = 0.0
            self._ia_s = self._ewma(self._ia_s, dt)
        if self._last_enqueue is None or t_enqueue > self._last_enqueue:
            self._last_enqueue = t_enqueue

    def observe_rows(self, rows: float) -> None:
        """One request's row (clip) count (fractional when derived
        from a fused emission's per-request average; clamped to >= 1
        so the residual-request conversion can never divide by ~0)."""
        self._rows_per_req = self._ewma(self._rows_per_req,
                                        max(1.0, float(rows)))

    def observe_service(self, bucket_rows: int, service_s: float) -> None:
        """One dispatch's service span for the bucket shape it shipped
        (the executor feeds dispatch->done from the TimeCard stamps).
        Keyed by the ACTUAL shipped row count — a stage's static pad
        rule may legally emit at a warmed bucket outside a narrowed
        ``autotune.buckets`` candidate set, and rounding such a sample
        up to a candidate would pollute the larger bucket's EWMA with
        the smaller bucket's service times (``service_for`` already
        bridges candidates with no samples of their own)."""
        b = int(bucket_rows)
        self._service_s[b] = self._ewma(self._service_s.get(b),
                                        max(0.0, float(service_s)))

    # -- the decision --------------------------------------------------

    def bucket_for(self, rows: int) -> int:
        """Smallest candidate bucket holding ``rows``; the largest
        candidate when none does (the stage's hard cap applies)."""
        for b in self.candidates:
            if rows <= b:
                return b
        return self.candidates[-1]

    def service_for(self, bucket: int) -> float:
        """Predicted service seconds for a bucket: its own EWMA, else
        the nearest observed bucket's (larger preferred — conservative
        for growth decisions), else 0.0 (optimistic until the first
        observation lands)."""
        got = self._service_s.get(bucket)
        if got is not None:
            return got
        above = [b for b in self._service_s if b > bucket]
        if above:
            return self._service_s[min(above)]
        below = [b for b in self._service_s if b < bucket]
        if below:
            return self._service_s[max(below)]
        return 0.0

    def peek(self, n_ready: int, rows_ready: int,
             oldest_wait_s: float) -> Decision:
        """:meth:`decide` without the accounting side effects — for
        pure deadline queries (the executor's ``poll_plan`` asks for
        the next deadline every hot-loop tick, and charging each tick
        as a decision would make the ``Autotune:`` counters an
        artifact of poll frequency rather than controller behavior)."""
        del n_ready  # the row axis is what sizes the dispatch
        budget_s = self.slo_ms / 1000.0
        base = self.bucket_for(rows_ready)
        # the largest candidate bucket whose residual-fill wait plus
        # predicted service fits the budget; 0 = no feasible growth.
        # NOT seeded with `base` — padding the current rows to `base`
        # needs no growth, so it must never justify holding by itself
        # (an unknown arrival rate would otherwise hold forever)
        target = 0
        ia = self._ia_s
        if ia is not None and ia > 0.0:
            rpr = self._rows_per_req or 1.0
            for b in self.candidates:
                if b <= rows_ready or b > self.max_rows:
                    continue
                extra_reqs = math.ceil((b - rows_ready) / rpr)
                predicted = (oldest_wait_s + extra_reqs * ia
                             + self.service_for(b))
                if predicted <= budget_s:
                    target = max(target, b)
        if target > rows_ready:
            # worth holding: allow the oldest to wait until the batch
            # could no longer meet the budget, clamped to the
            # configured hold window
            hold_s = budget_s - self.service_for(target)
            hold_s = max(hold_s, self.min_hold_ms / 1000.0)
            hold_s = min(hold_s, self.max_hold_ms / 1000.0)
            if oldest_wait_s >= hold_s:
                return Decision(target, hold_s, base, True)
            return Decision(target, hold_s, base, False)
        # no feasible growth (or unknown arrival rate): dispatch now
        return Decision(base, 0.0, base, True)

    def decide(self, n_ready: int, rows_ready: int,
               oldest_wait_s: float) -> Decision:
        """The emission decision for the current accumulator state:
        ``n_ready`` ready requests totalling ``rows_ready`` rows, the
        oldest of which has waited ``oldest_wait_s``. Pure arithmetic
        over the estimators — no clock reads, no RNG. Counts toward
        the ``Autotune:`` accounting; deadline-only queries must use
        :meth:`peek`."""
        dec = self.peek(n_ready, rows_ready, oldest_wait_s)
        if trace.ACTIVE is not None:
            # decision marker on the deciding thread's trace track
            # (rnb_tpu.trace; args allocated only while tracing) —
            # still no clock reads or RNG on the decision path itself
            trace.instant("autotune.decision", args={
                "verdict": "immediate" if dec.immediate else "held",
                "target_rows": dec.target_rows,
                "hold_ms": dec.hold_s * 1000.0})
        if metrics.ACTIVE is not None:
            # live controller state (rnb_tpu.metrics): the arrival-
            # rate estimate and chosen target stream so an operator
            # (and the future elastic-serving controller, ROADMAP
            # item 5) can watch the adaptive loop act — still no
            # clock reads or RNG on the decision path
            metrics.gauge("autotune.arrival_hz",
                          1.0 / self._ia_s if self._ia_s else 0.0)
            metrics.gauge("autotune.target_rows", dec.target_rows)
        self._decisions += 1
        self._decided_since_emit = True
        if dec.immediate:
            self._immediate += 1
        else:
            self._held += 1
            us = int(round(dec.hold_s * 1e6))
            if self._deadline_us_min is None or us < self._deadline_us_min:
                self._deadline_us_min = us
            if us > self._deadline_us_max:
                self._deadline_us_max = us
            self._deadline_us_sum += us
        return dec

    def note_emission(self, bucket: int) -> None:
        """One emission shipped at ``bucket`` rows. Emissions no
        decision preceded (end-of-stream flush, forced drains) are
        counted as immediate decisions, keeping the --check invariant
        decisions >= emissions true on every path."""
        if not self._decided_since_emit:
            self._decisions += 1
            self._immediate += 1
        self._decided_since_emit = False
        self._emissions += 1
        b = int(bucket)
        self._bucket_counts[b] = self._bucket_counts.get(b, 0) + 1

    # -- reporting -----------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        """Final counters for the job-wide aggregation (BenchmarkResult
        ``autotune_*`` fields / log-meta ``Autotune:`` line)."""
        return {
            "decisions": self._decisions,
            "immediate": self._immediate,
            "held": self._held,
            "emissions": self._emissions,
            "deadline_us_min": self._deadline_us_min or 0,
            "deadline_us_max": self._deadline_us_max,
            "deadline_us_sum": self._deadline_us_sum,
            "bucket_counts": {str(b): n for b, n
                              in sorted(self._bucket_counts.items())},
        }


def aggregate_snapshots(snapshots: List[Dict[str, object]]
                        ) -> Dict[str, object]:
    """Sum per-instance controller snapshots into the job-wide view
    (min over non-empty mins, max over maxes, sums elsewhere)."""
    out: Dict[str, object] = {
        "decisions": 0, "immediate": 0, "held": 0, "emissions": 0,
        "deadline_us_min": 0, "deadline_us_max": 0, "deadline_us_sum": 0,
        "bucket_counts": {},
    }
    mins = [int(s.get("deadline_us_min", 0)) for s in snapshots
            if int(s.get("held", 0)) > 0]
    out["deadline_us_min"] = min(mins) if mins else 0
    for s in snapshots:
        for key in ("decisions", "immediate", "held", "emissions",
                    "deadline_us_sum"):
            out[key] += int(s.get(key, 0))
        out["deadline_us_max"] = max(int(out["deadline_us_max"]),
                                     int(s.get("deadline_us_max", 0)))
        for b, n in dict(s.get("bucket_counts", {})).items():
            counts = out["bucket_counts"]
            counts[b] = counts.get(b, 0) + int(n)
    return out
