"""Device resolution and availability probing.

Pipeline configs place stage groups on devices by logical index (or by
explicit ``platform:index`` label). Index ``-1`` means "run on the host"
— used for host-side stages like the aggregator (reference
runner.py:31-44 ran those without CUDA). The availability probe replaces
the reference's py3nvml memory-free check (reference benchmark.py:97-125)
with `jax.devices()` introspection: on TPU the runtime owns every core in
the slice, so existence is the meaningful check.
"""

from __future__ import annotations

from typing import List, Optional, Union

DeviceSpecLike = Union[int, str]

HOST_DEVICE_INDEX = -1


class DeviceResolutionError(RuntimeError):
    pass


def accelerator_devices() -> list:
    """Devices of the default JAX backend, in enumeration order.

    Under a TPU runtime this is the TPU cores of the slice; in tests it
    is the virtual CPU devices created by
    ``--xla_force_host_platform_device_count``.
    """
    import jax
    return list(jax.devices())


def host_device():
    """The first CPU device — where host-placed (-1) stages run."""
    import jax
    return jax.devices("cpu")[0]


class DeviceSpec:
    """A resolved placement: one JAX device plus a stable log label."""

    def __init__(self, spec: DeviceSpecLike):
        self.spec = spec
        self._device = None  # resolved lazily so parsing needs no backend

    @property
    def is_host(self) -> bool:
        return self.spec == HOST_DEVICE_INDEX

    def resolve(self):
        """Return the jax.Device this spec names (cached)."""
        if self._device is not None:
            return self._device
        import jax
        if isinstance(self.spec, int):
            if self.spec == HOST_DEVICE_INDEX:
                self._device = host_device()
            else:
                devices = accelerator_devices()
                if not 0 <= self.spec < len(devices):
                    raise DeviceResolutionError(
                        "pipeline configuration names device %d but only %d "
                        "devices are visible (%s)"
                        % (self.spec, len(devices),
                           [str(d) for d in devices]))
                self._device = devices[self.spec]
        elif isinstance(self.spec, str):
            platform, _, idx = self.spec.partition(":")
            try:
                candidates = jax.devices(platform)
            except RuntimeError as e:
                raise DeviceResolutionError(
                    "no %r backend available for device spec %r"
                    % (platform, self.spec)) from e
            index = int(idx) if idx else 0
            if not 0 <= index < len(candidates):
                raise DeviceResolutionError(
                    "device spec %r out of range: %d %s devices visible"
                    % (self.spec, len(candidates), platform))
            self._device = candidates[index]
        else:
            raise DeviceResolutionError(
                "unsupported device spec %r (want int or 'platform:idx')"
                % (self.spec,))
        return self._device

    @property
    def label(self) -> str:
        """Stable string used in TimeCard device trails and log names."""
        if self.is_host:
            return "host"
        if isinstance(self.spec, int):
            d = self.resolve()
            return "%s:%d" % (d.platform, d.id)
        return str(self.spec)

    def __repr__(self):
        return "DeviceSpec(%r)" % (self.spec,)

    def __eq__(self, other):
        return isinstance(other, DeviceSpec) and other.spec == self.spec

    def __hash__(self):
        return hash(self.spec)


def check_devices(specs: List[DeviceSpec]) -> None:
    """Resolve every spec, raising DeviceResolutionError for bad ones."""
    for spec in specs:
        spec.resolve()


#: bytes_in_use above this before we allocate anything suggests another
#: client holds buffers on the chip (the TPU runtime itself keeps a few
#: hundred KiB resident, so 0 is never the idle reading)
BUSY_BYTES_THRESHOLD = 16 * 1024 * 1024


def probe_busy_devices(specs: List[DeviceSpec]) -> List[str]:
    """Best-effort "device already in use" warning list.

    The reference refused to start unless every requested GPU reported
    zero bytes of used memory (reference benchmark.py:97-125). A TPU
    runtime owns the whole slice so exact parity is impossible, but
    ``Device.memory_stats()`` — where the backend implements it —
    exposes ``bytes_in_use`` before this job allocates anything; a
    non-trivial figure means some other client has live buffers on the
    chip (e.g. a concurrent tunnel session). Unlike the reference this
    returns warnings instead of aborting: shared-chip contention
    degrades throughput but does not make the run incorrect.
    """
    warnings: List[str] = []
    seen = set()
    for spec in specs:
        if spec.is_host:
            continue
        try:
            device = spec.resolve()
        except DeviceResolutionError:
            continue  # best-effort: resolution errors are check_devices' job
        if device in seen:
            continue
        seen.add(device)
        try:
            stats = device.memory_stats()
        except Exception:
            continue  # backend without memory introspection
        if not stats:
            continue
        in_use = stats.get("bytes_in_use", 0)
        if in_use > BUSY_BYTES_THRESHOLD:
            warnings.append(
                "device %s already has %.1f MiB in use before this job "
                "allocated anything — another process may be sharing the "
                "chip; expect degraded and noisy throughput"
                % (spec.label, in_use / (1024.0 * 1024.0)))
    return warnings
