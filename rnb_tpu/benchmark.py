"""Benchmark orchestrator: launch a pipeline job end-to-end.

CLI parity with the reference launcher (benchmark.py:127-305):
``python -m rnb_tpu.benchmark -mi <ms> -b <batch> -v <videos>
-qs <queue-size> -c <config.json> [--check]`` — plus TPU-runtime
extras: ``--platform cpu`` forces the virtual-CPU backend (useful with
``XLA_FLAGS=--xla_force_host_platform_device_count=N``), and
``--log-base`` relocates the log directory.

One controller process owns everything: it validates the config against
the visible JAX devices (replacing the reference's NVML free-GPU probe,
benchmark.py:97-125), builds the channel fabric, spawns the client and
one executor thread per (step, group, device instance), fences them all
with start/finish barriers so model compile/warm-up stays out of the
measured window (benchmark.py:276-288), and writes ``log-meta.txt``
plus a copy of the pipeline config into ``logs/<job_id>/``.
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import shutil
import sys
import threading
import time
from dataclasses import dataclass, field
from datetime import datetime
from typing import Any, Dict, Optional

from rnb_tpu.arg_utils import nonnegative_int, positive_int

BARRIER_TIMEOUT_S = 1800.0  # generous: first TPU compile can be slow


def _enable_compilation_cache() -> None:
    """Persist XLA executables across processes so repeat runs (and the
    round driver's bench invocations) skip the 20-40s first compile.
    Off with RNB_NO_COMPILE_CACHE=1; dir overridable via
    RNB_COMPILE_CACHE_DIR."""
    if os.environ.get("RNB_NO_COMPILE_CACHE"):
        return
    import jax
    cache_dir = os.environ.get(
        "RNB_COMPILE_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "rnb_tpu_xla"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax without the knobs: in-memory cache only


@dataclass
class BenchmarkResult:
    job_id: str
    total_time_s: float
    num_videos: int
    termination_flag: int
    throughput_vps: float
    log_dir: str
    #: end-to-end per-request latency percentiles (ms) over every
    #: final-step instance, steady-state records only; None when the
    #: run produced too few records
    p50_latency_ms: Optional[float] = None
    p99_latency_ms: Optional[float] = None
    #: total clips across every registered completion (0 when the
    #: pipeline never stamps num_clips) — clips/sec and MFU accounting
    clips_completed: int = 0
    #: process CPU seconds (utime+stime, all threads incl. the decode
    #: pool) over the measured window; / total_time_s ~ host-core
    #: saturation on a 1-core host
    host_cpu_s: float = 0.0
    #: fault-containment accounting (rnb_tpu.faults): requests
    #: dead-lettered with a permanent failure, dropped by the "shed"
    #: overload policy, and transient retry attempts. Successfully
    #: completed requests = num_completed; throughput_vps and the
    #: latency percentiles cover successes only.
    num_completed: int = 0
    num_failed: int = 0
    num_shed: int = 0
    num_retries: int = 0
    failure_reasons: Dict[str, int] = field(default_factory=dict)
    shed_sites: Dict[str, int] = field(default_factory=dict)
    #: decoded-clip cache accounting (rnb_tpu.cache), summed over every
    #: cache-owning stage instance; all zero when no step configures
    #: `cache_mb`. hits+misses = loader-side lookups (including for
    #: requests that later failed/shed); coalesced = requests that
    #: shared an in-flight decode instead of re-decoding.
    cache_hits: int = 0
    cache_misses: int = 0
    cache_inserts: int = 0
    cache_evictions: int = 0
    cache_coalesced: int = 0
    #: entries skipped because a single batch exceeded the whole
    #: cache_mb budget (was written to log-meta but missing here until
    #: the schema checker's BenchmarkResult cross-check caught it)
    cache_oversize: int = 0
    cache_bytes_resident: int = 0
    #: zero-copy decode-staging accounting (rnb_tpu.staging), summed
    #: over every staging-owning stage instance; all zero when no
    #: loader built a pool (staging_slots=0 / non-native backend).
    #: staged vs copied batches split the emissions between the
    #: zero-copy slot path and the seed copy fallback; acquire_waits
    #: counts backpressure blocks on slot exhaustion (never drops);
    #: reallocs counts alias-forced slot-buffer replacements.
    staging_slots: int = 0
    staging_slot_bytes: int = 0
    staging_acquires: int = 0
    staging_acquire_waits: int = 0
    staging_staged_batches: int = 0
    staging_copied_batches: int = 0
    staging_reallocs: int = 0
    #: load-adaptive batching accounting (rnb_tpu.autotune), summed
    #: over every controller-owning stage instance; all zero when the
    #: config carries no enabled `autotune` root key. decisions =
    #: controller consultations (every emission is covered by one, so
    #: decisions >= emissions); immediate/held split them by verdict;
    #: the deadline_us_* triple summarizes the held-decision deadline
    #: histogram (min/max/sum microseconds).
    autotune_decisions: int = 0
    autotune_immediate: int = 0
    autotune_held: int = 0
    autotune_emissions: int = 0
    autotune_deadline_us_min: int = 0
    autotune_deadline_us_max: int = 0
    autotune_deadline_us_sum: int = 0
    #: emissions per chosen row bucket (keys are stringified row
    #: counts; always a subset of the configured warmed buckets)
    autotune_bucket_counts: Dict[str, int] = field(default_factory=dict)
    #: per-edge queue-overflow counts under the "abort" overload
    #: policy (rnb_tpu.control.FaultStats.record_overflow) — the
    #: events that used to be an unparseable stdout warning
    queue_overflows: Dict[str, int] = field(default_factory=dict)
    #: per-request phase attribution (rnb_tpu.trace): {phase:
    #: {mean_ms, p99_ms, count}} over steady-state completions,
    #: phases summing to end-to-end latency per request. Empty unless
    #: the config's `trace` key enabled tracing (the same gating as
    #: the log-meta `Phases:` line, keeping trace-off runs byte-
    #: stable).
    phases: Dict[str, Dict[str, float]] = field(default_factory=dict)
    #: trace export accounting: events written to logs/<job>/
    #: trace.json and events dropped at the max_events cap (both 0 on
    #: trace-off runs)
    trace_events: int = 0
    trace_dropped: int = 0
    #: padding-waste accounting (rnb_tpu.stage.PadCounter), summed
    #: over every batching stage instance: pad rows shipped /
    #: total rows shipped / emissions. Under ragged dispatch the
    #: consumer's kernel computes no pad rows, so pad_rows stays ~0
    #: and the waste the bucketed rule would have burned lands in
    #: ragged_pad_rows_eliminated instead.
    pad_rows: int = 0
    total_rows: int = 0
    pad_emissions: int = 0
    #: ragged row-pool dispatch accounting (rnb_tpu.ops.ragged),
    #: summed over every ragged stage instance; all zero without the
    #: `ragged` root config key. rows = valid rows shipped across all
    #: pool emissions; pad_rows_eliminated = what the bucketed pad
    #: rule would have shipped on top; cache_hit_rows = rows served
    #: into pools from the row-extent clip cache.
    ragged_pool_rows: int = 0
    ragged_emissions: int = 0
    ragged_rows: int = 0
    ragged_pad_rows_eliminated: int = 0
    ragged_cache_hit_rows: int = 0
    #: intra-stage shard accounting (rnb_tpu.parallel.shardplan, step
    #: `shard` config key), summed over every declared-degree stage
    #: instance; all zero without the key. Degree buys per-device HBM
    #: feasibility, never speed: gathers counts logits-path merge
    #: collectives, collective_us their summed host-timed wall
    #: (nested inside the model_call span, so it never adds to
    #: inference time), rows the valid rows that crossed a sharded
    #: stage.
    shard_steps: int = 0
    shard_max_degree: int = 0
    shard_gathers: int = 0
    shard_collective_us: int = 0
    shard_rows: int = 0
    #: per-step shard detail (the `Shard steps:` JSON meta line):
    #: degree/axis, merge-gather counters, projected vs budget MiB,
    #: and the memledger-projected min feasible degree
    shard_step_detail: Dict[str, Any] = field(default_factory=dict)
    #: paged device-memory accounting (rnb_tpu.pager, root `pager`
    #: config key) — the `Pages:` meta line verbatim: page
    #: alloc/free/live occupancy, gather dispatches split by plane
    #: (clip arena vs feature arena), feature-cache
    #: lookup/hit/insert/evict counters, and bypassed_batches =
    #: emissions that shipped ZERO host->device bytes because every
    #: row gathered from pages. Empty without the key.
    pages: Dict[str, int] = field(default_factory=dict)
    #: per-step jit-entry signature accounting
    #: (rnb_tpu.compilestats): {step: {warmup, steady_new,
    #: steady_calls}} — steady_new > 0 means a mid-run recompile; a
    #: ragged stage's warmup is exactly 1
    compile_signatures: Dict[str, Dict[str, int]] = \
        field(default_factory=dict)
    #: per-step stage-construction wall seconds (weights + warmup
    #: compiles), summed over the step's instances
    warmup_s: Dict[str, float] = field(default_factory=dict)
    #: device-resident handoff accounting (rnb_tpu.handoff), summed
    #: over every consumer executor; all zero without the root
    #: `handoff` config key. Every ring-payload take is one edge
    #: event, classified d2d (adopted / resharded on-device) or host
    #: (the explicit host round trip), with the bytes each class
    #: moved — d2d_edges + host_edges == edges always, and a
    #: device-resident config must show host_bytes == 0.
    handoff_edges: int = 0
    handoff_d2d_edges: int = 0
    handoff_host_edges: int = 0
    handoff_d2d_bytes: int = 0
    handoff_host_bytes: int = 0
    #: per-edge-label handoff counters (the `Handoff edges:` JSON
    #: meta line)
    handoff_edge_detail: Dict[str, Dict[str, int]] = \
        field(default_factory=dict)
    #: measured-cost placement report (rnb_tpu.placement): per-step
    #: measured dispatch costs, the executed plan's predicted
    #: occupancy, and the recommendation over the device budget —
    #: the `Placement:` JSON meta line verbatim. Empty without the
    #: root `placement` config key.
    placement: Dict[str, Any] = field(default_factory=dict)
    #: lane health / circuit-breaker accounting (rnb_tpu.health,
    #: root `health` config key), summed over every replica step's
    #: board; all zero without the key. transitions counts every
    #: state-machine hop; evictions counts permanently dead lanes;
    #: redispatches counts items drained off evicted lanes onto
    #: healthy siblings; routes_after_open counts containment
    #: violations (routes to an open/evicted lane while a routable
    #: sibling existed) and must be 0 on a healthy run.
    health_lanes: int = 0
    health_transitions: int = 0
    health_opens: int = 0
    health_evictions: int = 0
    health_probes: int = 0
    health_redispatches: int = 0
    health_routes_after_open: int = 0
    #: per-lane health detail (the `Health lanes:` JSON meta line):
    #: final state, full transition path, redispatched-from count
    health_lane_detail: Dict[str, Any] = field(default_factory=dict)
    #: deadline-propagation accounting (rnb_tpu.health, root
    #: `deadline` config key): the configured budget and requests
    #: shed as deadline_expired across every check site; zero/empty
    #: without the key
    deadline_budget_ms: int = 0
    deadline_expired: int = 0
    deadline_sites: Dict[str, int] = field(default_factory=dict)
    #: hedged re-dispatch accounting (rnb_tpu.health, step key
    #: `hedge_ms`): fired re-issues, wins by the hedge copy, losses
    #: (original resolved first), and the losers' burned service
    #: milliseconds — won + lost == fired always; hedge work is
    #: counted here as overhead, never in throughput_vps
    hedges_fired: int = 0
    hedges_won: int = 0
    hedges_lost: int = 0
    hedges_wasted_ms: int = 0
    #: live-metrics plane accounting (rnb_tpu.metrics, root `metrics`
    #: config key): interval snapshots appended to metrics.jsonl,
    #: distinct series at teardown, flight-recorder dumps written and
    #: triggers observed — all zero without the key. --check holds
    #: the final snapshot's counters to the ledger lines exactly.
    metrics_snapshots: int = 0
    metrics_series: int = 0
    metrics_dumps: int = 0
    metrics_triggers: int = 0
    #: live SLO-layer accounting (same gating): completions tracked /
    #: within deadline / missed, plus the run's peak burn rate in
    #: milli-units (1000 = consuming the error budget exactly)
    slo_tracked: int = 0
    slo_within: int = 0
    slo_missed: int = 0
    slo_burn_max_milli: int = 0
    #: device compute plane accounting (rnb_tpu.devobs, root `devobs`
    #: config key): flops-bearing stages metered, dispatches/valid
    #: rows observed, total achieved FLOPs (per-row counts x rows),
    #: the measured window in microseconds, the job-level achieved
    #: TFLOP/s and MFU in bench.py's exact rounding (milli-tflops /
    #: 1e-4 mfu units; mfu_e4 == -1 when the platform has no known
    #: peak), and bounded capture windows taken — all zero without
    #: the key. --check cross-foots flops_total against the per-stage
    #: detail and the demo gate holds tflops/mfu to bench.py's
    #: evidence line to the digit.
    compute_stages: int = 0
    compute_dispatches: int = 0
    compute_rows: int = 0
    compute_flops_total: int = 0
    compute_window_us: int = 0
    compute_tflops_milli: int = 0
    compute_mfu_e4: int = 0
    compute_captures: int = 0
    #: per-stage roofline detail (the `Compute stages:` JSON meta
    #: line): rows, dispatches, flops_per_row, busy_us, tflops_busy,
    #: mfu_busy, ai_flops_per_byte
    compute_stage_detail: Dict[str, Any] = field(default_factory=dict)
    #: HBM footprint ledger accounting (rnb_tpu.memledger, same
    #: gating): declared owners and devices seen, final/peak resident
    #: bytes, the watermark threshold and below->above crossings, the
    #: backend's live-buffer byte total, and whether the ledger's
    #: live-backed claims reconciled against it (1 = checked and
    #: consistent; 0 = backend exposes no live list OR the check
    #: failed — --check flags the latter)
    memory_owners: int = 0
    memory_devices: int = 0
    memory_total_bytes: int = 0
    memory_peak_bytes: int = 0
    memory_watermark_bytes: int = 0
    memory_watermark_hits: int = 0
    memory_live_bytes: int = 0
    memory_reconciled: int = 0
    #: per-owner footprint detail (the `Memory owners:` JSON meta
    #: line): {owner: {bytes, peak_bytes}}
    memory_owner_detail: Dict[str, Any] = field(default_factory=dict)
    #: critical-path extraction accounting (rnb_tpu.critpath, root
    #: `critpath` config key): completed requests whose blocking
    #: chain was recovered, total chain segments, the worst
    #: per-request partition residual (microseconds — --check holds
    #: it under 1000), hedge-won and redispatched completions, and
    #: the binding stage's critical-path throughput bound — all zero
    #: without the key.
    critpath_requests: int = 0
    critpath_segments: int = 0
    critpath_residual_us_max: int = 0
    critpath_hedged: int = 0
    critpath_redispatched: int = 0
    critpath_bound_step: int = 0
    critpath_bound_vps_milli: int = 0
    #: per-stage blocking attribution (the `Critpath stages:` JSON
    #: meta line): lanes, per-class blocked totals, occupied ms,
    #: bound_vps
    critpath_stage_detail: Dict[str, Any] = field(default_factory=dict)
    #: calibrated queueing what-if engine accounting (rnb_tpu.whatif,
    #: root `whatif` config key — requires `metrics`): stages the
    #: model calibrated from the final metrics snapshot, whether
    #: calibration succeeded, the model's self-predicted throughput
    #: (milli-vps) and its predicted bottleneck step (-1 when
    #: uncalibrated) — all zero/-1 without the key. --check
    #: recomputes the prediction offline from metrics.jsonl + the
    #: config copy and holds it to +-1 milli-vps.
    whatif_stages: int = 0
    whatif_calibrated: int = 0
    whatif_pred_vps_milli: int = 0
    whatif_bottleneck_step: int = 0
    #: operator-plane request ledger (rnb_tpu.statusz, root `operator`
    #: config key): GET requests served (scrapes), POST actions
    #: accepted, POST actions denied by the allow_actions gate, and
    #: request errors (bad route / unavailable backing plane) — all
    #: zero without the key. --check holds the Operator: line to the
    #: operator.json artifact's presence both ways.
    operator_scrapes: int = 0
    operator_actions: int = 0
    operator_denied: int = 0
    operator_errors: int = 0
    #: wall-clock stack sampler ledger (rnb_tpu.stacksampler, gated on
    #: `operator.sample_hz` > 0): sampling ticks, distinct thread
    #: roles, distinct folded stacks, total per-thread samples — the
    #: stacks.folded artifact's counts sum to stacks_total exactly and
    #: ticks track sample_hz x wall within --check's tolerance.
    stacks_samples: int = 0
    stacks_threads: int = 0
    stacks_folded: int = 0
    stacks_total: int = 0
    # netedge transport ledger (root 'netedge' key; rnb_tpu.netedge)
    net_frames_sent: int = 0
    net_frames_acked: int = 0
    net_resent_pending: int = 0
    net_resends: int = 0
    net_beats: int = 0
    net_reconnects: int = 0
    net_remote: int = 0
    net_local: int = 0
    net_dedup_drops: int = 0
    net_dup_arrivals: int = 0
    net_wire_bytes: int = 0
    net_frame_bytes: int = 0
    net_window_stranded: int = 0
    net_open_before_timeout: int = 0
    net_err_total: int = 0
    net_err_refused: int = 0
    net_err_reset: int = 0
    net_err_timeout: int = 0
    net_err_partial_frame: int = 0
    net_err_corrupt: int = 0
    #: lock-order witness ledger (rnb_tpu.lockwitness, root `lint`
    #: config key with lock_witness true): witnessed locks, total
    #: acquisitions, distinct acquisition-order edges, discipline
    #: violations — all zero without the key. --check holds
    #: locks_violations to zero and the Lock edges: JSON detail to
    #: the static RNB-C lock-order graph (observed subset-of
    #: declared).
    locks_tracked: int = 0
    locks_acquires: int = 0
    locks_edges: int = 0
    locks_violations: int = 0


def run_benchmark(config_path: str,
                  mean_interval_ms: int = 3,
                  batch_size: int = 1,
                  num_videos: int = 2000,
                  queue_size: int = 50000,
                  log_base: str = "logs",
                  print_progress: bool = True,
                  seed: Optional[int] = None,
                  job_id: Optional[str] = None,
                  xprof: bool = False) -> BenchmarkResult:
    """Programmatic entry used by the CLI, tests and bench.py."""
    _enable_compilation_cache()
    # multi-host: honor RNB_TPU_COORDINATOR/_NUM_PROCESSES/_PROCESS_ID
    # before the first backend touch — jax.distributed must initialize
    # ahead of jax.devices() for DCN-attached devices to be visible
    # (SURVEY.md §2.4 TPU mapping; no-op for single-host runs)
    from rnb_tpu.parallel.distributed import maybe_initialize
    maybe_initialize()
    from rnb_tpu import devobs as devobs_mod
    from rnb_tpu import memledger as memledger_mod
    from rnb_tpu import metrics as metrics_mod
    from rnb_tpu import trace as trace_mod
    from rnb_tpu.client import bulk_client, poisson_client
    from rnb_tpu.config import load_config
    from rnb_tpu.control import (ChannelFabric, FaultStats,
                                 InferenceCounter, TerminationState)
    from rnb_tpu.faults import FaultPlan
    from rnb_tpu.runner import NUM_SUMMARY_SKIPS, RunnerContext, runner
    from rnb_tpu.telemetry import logmeta, logroot

    # defensive: a previous run that died mid-trace must not leave its
    # tracer active — this run's instrumentation would otherwise write
    # into a dead collector (and un-traced runs would stop being
    # byte-stable); same for the live-metrics registry
    trace_mod.ACTIVE = None
    metrics_mod.ACTIVE = None
    devobs_mod.ACTIVE = None
    memledger_mod.ACTIVE = None

    config = load_config(config_path)
    config.check_devices()
    # best-effort contention probe (reference benchmark.py:97-125
    # aborted here; we warn — see rnb_tpu.devices.probe_busy_devices)
    from rnb_tpu.devices import probe_busy_devices
    for warning in probe_busy_devices(config.all_devices()):
        print("[rnb-tpu] WARNING: %s" % warning, file=sys.stderr)

    # lock-order witness (rnb_tpu.lockwitness, root `lint` config
    # key): armed BEFORE pipeline construction — the witness wraps
    # locks at lockwitness.lock() creation time, so enabling it after
    # the cache/pager/staging/health objects exist would observe
    # nothing
    from rnb_tpu import lockwitness
    witness_armed = bool(config.lint
                         and config.lint.get("lock_witness", False))
    if witness_armed:
        lockwitness.enable()
        lockwitness.reset()

    if job_id is None:
        job_id = "%s-mi%d-b%d-v%d-qs%d" % (
            datetime.today().strftime("%y%m%d_%H%M%S"), mean_interval_ms,
            batch_size, num_videos, queue_size)

    num_runners = config.num_runners
    bar_total = num_runners + 2  # runners + client + this controller
    sta_bar = threading.Barrier(bar_total, timeout=BARRIER_TIMEOUT_S)
    fin_bar = threading.Barrier(bar_total, timeout=BARRIER_TIMEOUT_S)
    counter = InferenceCounter()
    termination = TerminationState()
    summary_sink: list = []
    cache_sink: list = []
    staging_sink: list = []
    autotune_sink: list = []
    compile_sink: list = []
    pad_sink: list = []
    ragged_sink: list = []
    shard_sink: list = []
    fault_stats = FaultStats()
    # load-adaptive batching (rnb_tpu.autotune): one validated settings
    # object shared by every participating stage; per-step opt-out via
    # "autotune": false on the step
    from rnb_tpu.autotune import AutotuneSettings
    autotune_settings = AutotuneSettings.from_config(config.autotune)
    if autotune_settings is not None:
        # enabled-but-inert is a measurement confound: an operator
        # A/B-ing against a static baseline must be able to tell a
        # pipeline where no stage participates (every step opted out,
        # or none SUPPORTS_AUTOTUNE) from an adaptive run. Class-load
        # failures are deferred to the runner thread, which owns that
        # error path.
        from rnb_tpu.utils.class_utils import load_class

        def _may_participate(step):
            try:
                return getattr(load_class(step.model),
                               "SUPPORTS_AUTOTUNE", False)
            except Exception:
                return True
        if not any(step.autotune and _may_participate(step)
                   for step in config.steps):
            print("[rnb-tpu] WARNING: autotune is enabled but no "
                  "pipeline stage participates (every step opted out "
                  "or unsupported) — batching stays static and no "
                  "Autotune: telemetry will be emitted",
                  file=sys.stderr)
    # ragged row-pool dispatch (root 'ragged' config key,
    # rnb_tpu.ops.ragged): supporting stages get the kwargs injected —
    # the keys are runtime wiring, not user config, so the static
    # unconsumed-key check never sees them and a non-supporting stage
    # (mesh runner, single-step baseline) simply stays bucketed
    from rnb_tpu.ops.ragged import RaggedSettings
    ragged_settings = RaggedSettings.from_config(config.ragged)
    ragged_kwargs_by_step: Dict[int, Dict[str, Any]] = {}
    if ragged_settings is not None:
        from rnb_tpu.utils.class_utils import load_class as _load_cls
        any_ragged = False
        for step_idx, step in enumerate(config.steps):
            try:
                supports = getattr(_load_cls(step.model),
                                   "SUPPORTS_RAGGED", False)
            except Exception:
                supports = False
            if supports:
                any_ragged = True
                ragged_kwargs_by_step[step_idx] = {
                    "ragged": True,
                    "ragged_pool_rows": ragged_settings.pool_rows}
        if not any_ragged:
            print("[rnb-tpu] WARNING: ragged is enabled but no "
                  "pipeline stage supports it — every emission stays "
                  "bucketed and no Ragged: telemetry will be emitted",
                  file=sys.stderr)

    # paged device memory (root 'pager' config key, rnb_tpu.pager):
    # ONE page allocator per job — executors hand it to every
    # SUPPORTS_PAGER stage before the start barrier (the loader's
    # clip cache switches to page tables, the consuming stage
    # attaches the feature-page arena). Absent => None, byte-stable
    # logs, no arenas allocated.
    from rnb_tpu.pager import Pager, PagerSettings
    pager_settings = PagerSettings.from_config(config.pager)
    pager = Pager(pager_settings) if pager_settings is not None \
        else None

    # device-resident handoff (root 'handoff' key, rnb_tpu.handoff):
    # consumer executors apply the edge contract to every ring payload
    # take and account d2d vs host-hop moves; absent => the stage
    # models' own re-homing, no accounting, byte-stable logs
    from rnb_tpu.handoff import HandoffSettings, InflightDepths
    handoff_settings = HandoffSettings.from_config(config.handoff)
    handoff_sink: list = []
    # measured-cost placement (root 'placement' key,
    # rnb_tpu.placement): every executor measures its dispatch busy
    # spans; the launcher turns them into the Placement: plan line.
    # (Apply-mode replica counts were already expanded at parse time.)
    from rnb_tpu.placement import PlacementSettings
    placement_settings = PlacementSettings.from_config(config.placement)
    placement_sink = [] if placement_settings is not None else None
    # replica-lane depth counters: one shared InflightDepths per
    # replica-expanded step, feeding the upstream ReplicaSelector's
    # least-loaded routing and settled by the replica executors
    depths_by_step = {
        step_idx: InflightDepths(step.replica_queues)
        for step_idx, step in enumerate(config.steps)
        if step.replica_queues}
    # self-healing layer (rnb_tpu.health): lane health boards per
    # replica step (root 'health' key), the job-wide deadline ledger
    # (root 'deadline' key; budget seeded from autotune.slo_ms), and
    # hedge governors per replicated edge ('hedge_ms' step key)
    from rnb_tpu.health import (DeadlineSettings, DeadlineStats,
                                HealthSettings, HedgeGovernor,
                                LaneHealthBoard)
    health_settings = HealthSettings.from_config(config.health)
    boards_by_step: Dict[int, LaneHealthBoard] = {}
    if health_settings is not None:
        boards_by_step = {
            step_idx: LaneHealthBoard(step.replica_queues,
                                      health_settings)
            for step_idx, step in enumerate(config.steps)
            if step.replica_queues}
        if not boards_by_step and not (config.netedge
                                       or {}).get("enabled"):
            # a netedge run has no replica lanes but DOES have a lane
            # to circuit-break — the remote peer's board
            print("[rnb-tpu] WARNING: health is enabled but no step "
                  "declares replica lanes — there is nothing to "
                  "circuit-break and no Health: telemetry will be "
                  "emitted", file=sys.stderr)
    deadline_settings = DeadlineSettings.from_config(config.deadline,
                                                     config.autotune)
    deadline_stats = (DeadlineStats()
                      if deadline_settings is not None else None)
    governors_by_step = {
        step_idx: HedgeGovernor(step.hedge_ms)
        for step_idx, step in enumerate(config.steps)
        if step.replica_queues and step.hedge_ms is not None}

    fault_plan = FaultPlan.resolve(config.fault_plan)
    if fault_plan is not None:
        # env-provided plans bypass config parsing — re-check their
        # step indices against this pipeline before launching
        fault_plan.check_steps(config.num_steps)
        from rnb_tpu.faults import LANE_KINDS
        if not boards_by_step and any(f["kind"] in LANE_KINDS
                                      for f in fault_plan.faults):
            # a lane death without the health layer cannot be
            # contained: there is no eviction, no drain pump, and no
            # sibling linger — the queued work would strand and the
            # run would hang to the barrier timeout. Fail at launch
            # with the fix, not 30 minutes in.
            raise ValueError(
                "the fault plan injects replica_crash/replica_stall "
                "but the config has no enabled root 'health' key (or "
                "no replica lanes) — lane deaths need the health "
                "layer's eviction/drain machinery to stay contained")
    # cross-host ingest edge (rnb_tpu.netedge, root 'netedge' key):
    # a peer process serves step 0 over the wire with a local
    # fallback path behind a dedicated LaneHealthBoard
    from rnb_tpu.netedge import (NET_LANE, NetEdgeClient,
                                 NetEdgeSettings, NetStats, spawn_peer)
    netedge_settings = NetEdgeSettings.from_config(config.netedge)
    if fault_plan is not None and fault_plan.has_net_faults() \
            and netedge_settings is None:
        # same loud-typo posture as LANE_KINDS without replicas: a
        # net fault with no edge never fires, and the chaos run would
        # read 'containment verified' with zero injections
        raise ValueError(
            "the fault plan injects net_* faults but the config has "
            "no enabled root 'netedge' key — there is no network "
            "edge to address")
    if fault_plan is not None and print_progress:
        print("[rnb-tpu] fault plan active: %s" % fault_plan.describe())

    # bulk mode pre-enqueues everything; size the queues accordingly
    # (reference benchmark.py:209 — but unlike the reference, account
    # for segmentation fan-out: a step with num_segments=k multiplies
    # the messages in flight downstream of it — and for the exit
    # markers that share the queue with the payload items: a slow
    # consumer must never leave a producer's end-of-stream markers
    # undeliverable past the send deadline)
    if mean_interval_ms > 0:
        effective_queue_size = queue_size
    else:
        from rnb_tpu.control import NUM_EXIT_MARKERS
        seg_factor = 1
        for step in config.steps:
            seg_factor *= step.num_segments
        effective_queue_size = (num_videos * seg_factor + num_runners
                                + max(NUM_EXIT_MARKERS, num_runners) + 1)
    fabric = ChannelFabric(config, effective_queue_size)
    # netedge interposition: the dispatcher becomes the filename
    # queue's sole consumer; step-0 executors read this local queue
    # instead (same capacity, same item/marker protocol), and the
    # receiver injects remote emissions straight into step 0's first
    # out-queue as DirectPayload items
    netedge_client = None
    netedge_stats = None
    netedge_board = None
    netedge_peer = None
    netedge_local_q = None
    if netedge_settings is not None:
        if netedge_settings.spawn:
            netedge_peer, peer_addr = spawn_peer(
                config_path, netedge_settings, seed=seed or 0)
            netedge_settings.connect = peer_addr
        netedge_board = LaneHealthBoard(
            (NET_LANE,), health_settings or HealthSettings())
        netedge_stats = NetStats()
        netedge_local_q = queue.Queue(maxsize=effective_queue_size)
        netedge_client = NetEdgeClient(
            netedge_settings,
            board=netedge_board,
            stats=netedge_stats,
            fault_plan=fault_plan,
            fault_stats=fault_stats,
            deadline_stats=deadline_stats,
            counter=counter,
            num_videos=num_videos,
            termination=termination,
            filename_queue=fabric.get_filename_queue(),
            local_queue=netedge_local_q,
            inject_queue=fabric.get_queues(0, 0)[1][0],
            num_markers=fabric.filename_num_markers,
            seed=seed or 0)
    # one queue-occupancy probe list — (series name, qsize fn,
    # capacity) per edge in step-major enumeration order — shared by
    # the metrics gauge sources and the operator server's /statusz so
    # their edge naming can never diverge. The trace block below
    # keeps its own enumeration of the SAME edges in the SAME order
    # only because RNB-T008/T009 each require literal trace.name/
    # metrics.name call sites for their registries — any change to
    # this walk must be mirrored there
    queue_probes = [(metrics_mod.name("queue.filename.depth"),
                     fabric.get_filename_queue().qsize,
                     effective_queue_size)]
    _edge_idx = 0
    for _step_queues in fabric.queues:
        # edge ordinal in step-major enumeration order (queue indices
        # may legally repeat across steps, so the ordinal — not the
        # config's queue index — keys the series)
        for _q_idx in sorted(_step_queues):
            queue_probes.append(
                (metrics_mod.name("queue.e%d.depth", _edge_idx),
                 _step_queues[_q_idx].qsize, effective_queue_size))
            _edge_idx += 1

    # unified pipeline tracing (rnb_tpu.trace, root 'trace' config
    # key): one per-job collector every thread role records spans
    # into, plus a low-rate background sampler over the inter-stage
    # queue depths (stage-owned sources — staging occupancy, in-flight
    # decode counts — register in the runner via enable_trace)
    tracer = None
    trace_settings = trace_mod.TraceSettings.from_config(config.trace)
    if trace_settings is not None:
        tracer = trace_mod.Tracer(trace_settings)
        # mirrors the shared queue_probes walk above (same edges, same
        # step-major ordinal naming); kept as explicit trace.name
        # sites because RNB-T008 requires the literals here
        tracer.add_counter_source(
            trace_mod.name("queue.filename.depth"),
            fabric.get_filename_queue().qsize)
        edge_idx = 0
        for step_queues in fabric.queues:
            # edge ordinal in step-major enumeration order (queue
            # indices may legally repeat across steps, so the ordinal
            # — not the config's queue index — keys the counter track)
            for q_idx in sorted(step_queues):
                tracer.add_counter_source(
                    trace_mod.name("queue.e%d.depth", edge_idx),
                    step_queues[q_idx].qsize)
                edge_idx += 1
        trace_mod.ACTIVE = tracer

    # live metrics plane (rnb_tpu.metrics, root 'metrics' config key):
    # a time-series registry + background flusher streaming interval
    # snapshots to logs/<job>/metrics.jsonl while the run is live. It
    # BRIDGES existing signals instead of re-measuring: a SpanBridge
    # installs as the trace collector (forwarding to the real tracer
    # when tracing is also on) so the hot-loop spans feed latency
    # histograms and the flight-recorder ring, and the shared ledgers
    # (faults, deadline, hedge, health) + queue depths become poll
    # sources read each tick. Stage-owned subsystems register in the
    # runner (metrics.register_stage).
    metrics_registry = None
    metrics_settings = metrics_mod.MetricsSettings.from_config(
        config.metrics)
    if metrics_settings is not None:
        slo_budget = None
        if deadline_settings is not None:
            slo_budget = deadline_settings.budget_ms
        elif autotune_settings is not None:
            slo_budget = autotune_settings.slo_ms
        metrics_registry = metrics_mod.MetricsRegistry(
            metrics_settings, job_dir=logroot(job_id, base=log_base),
            job_id=job_id, slo_budget_ms=slo_budget)
        for probe_name, probe_fn, probe_cap in queue_probes:
            metrics_registry.add_gauge_source(probe_name, probe_fn,
                                              capacity=probe_cap)
        metrics_registry.add_poll(metrics_mod.snapshot_poll(
            "faults", fault_stats.snapshot,
            counters=("num_failed", "num_shed", "num_retries")))
        if deadline_stats is not None:
            metrics_registry.add_poll(metrics_mod.snapshot_poll(
                "deadline", deadline_stats.snapshot,
                counters=("expired",)))
        for gov in governors_by_step.values():
            # live_counters, NOT snapshot(): the teardown snapshot
            # resolves leftover hedges, and a per-tick poll must
            # never perturb the claim ledger
            metrics_registry.add_poll(metrics_mod.snapshot_poll(
                "hedge", gov.live_counters,
                counters=("fired", "won", "lost")))
        for board in boards_by_step.values():
            metrics_registry.add_poll(metrics_mod.snapshot_poll(
                "health", board.snapshot,
                counters=("transitions", "opens", "evictions",
                          "probes", "redispatches")))
        if netedge_stats is not None:
            metrics_registry.add_poll(metrics_mod.snapshot_poll(
                "net", netedge_stats.snapshot,
                counters=("frames_sent", "frames_acked", "resends",
                          "beats", "reconnects", "remote", "local",
                          "dedup_drops", "dup_arrivals", "wire_bytes",
                          "frame_bytes", "err_total"),
                gauges=("peer_depth",)))
            metrics_registry.add_poll(metrics_mod.snapshot_poll(
                "health", netedge_board.snapshot,
                counters=("transitions", "opens", "evictions",
                          "probes", "redispatches")))
        if pager is not None:
            metrics_registry.add_poll(metrics_mod.snapshot_poll(
                "pages", pager.snapshot,
                counters=("allocs", "frees", "alloc_fails", "gathers",
                          "gather_rows", "feature_lookups",
                          "feature_hits", "feature_inserts",
                          "feature_evictions", "feature_gathers",
                          "feature_gather_rows",
                          "feature_bytes_saved"),
                gauges=("live", "limbo", "bytes")))
        bridge = metrics_mod.SpanBridge(
            metrics_registry, forward=tracer,
            ring_events=(metrics_settings.ring_events
                         if metrics_settings.flight_enabled else 0))
        metrics_registry.bridge = bridge
        trace_mod.ACTIVE = bridge
        metrics_mod.ACTIVE = metrics_registry

    # device observability plane (rnb_tpu.devobs, root 'devobs' config
    # key): bounded jax.profiler capture windows (config window /
    # RNB_DEVOBS_FORCE env / flight-recorder triggers via the metrics
    # registry's trigger hooks) merged into the trace export as device
    # tracks, per-stage compute meters behind the Compute: line and
    # compute.* series, and the HBM footprint ledger
    # (rnb_tpu.memledger) behind the Memory: line and memory.* gauges.
    # Stages register their meters/byte sources in the runner
    # (devobs.register_stage) before the start barrier.
    devobs_plane = None
    devobs_settings = devobs_mod.DevObsSettings.from_config(
        config.devobs)
    if devobs_settings is not None:
        devobs_plane = devobs_mod.DevObsPlane(
            devobs_settings, job_dir=logroot(job_id, base=log_base),
            job_id=job_id)
        devobs_mod.ACTIVE = devobs_plane
        memledger_mod.ACTIVE = devobs_plane.ledger
        if metrics_registry is not None:
            metrics_registry.add_poll(devobs_plane.metrics_poll)
            metrics_registry.trigger_hooks.append(
                devobs_plane.on_trigger)

    # the explanation plane (rnb_tpu.critpath / rnb_tpu.whatif):
    # blocking-chain extraction over completed requests' stamps, and
    # the calibrated queueing what-if model built from the metrics
    # plane at teardown — both fully off (byte-stable logs) without
    # their root config keys
    from rnb_tpu.critpath import CritpathSettings
    from rnb_tpu.whatif import WhatifSettings
    critpath_settings = CritpathSettings.from_config(config.critpath)
    whatif_settings = WhatifSettings.from_config(config.whatif)

    # the operator plane (rnb_tpu.statusz / rnb_tpu.stacksampler, root
    # 'operator' config key): a threaded loopback HTTP server over the
    # registries built above — /healthz (lane boards), /metrics (the
    # live Prometheus exposition), /statusz, /whatif (the calibrated
    # counterfactual, live), /stacks, and allow_actions-gated POST
    # /flight and /capture — plus a continuous wall-clock stack
    # sampler over the named pipeline threads (sample_hz > 0). Bound
    # address lands in logs/<job>/operator.json; nothing here measures
    # anything new, it only serves what the planes already hold.
    from rnb_tpu.statusz import OperatorServer, OperatorSettings
    operator_settings = OperatorSettings.from_config(config.operator)
    operator_server = None
    stack_sampler = None
    operator_window: Dict[str, Any] = {"t0": None}
    if operator_settings is not None:
        if operator_settings.sample_hz > 0:
            from rnb_tpu.stacksampler import StackSampler
            stack_sampler = StackSampler(operator_settings.sample_hz)
        topology = {"steps": [
            {"step": step_idx, "model": step.model,
             "groups": len(step.groups),
             "instances": sum(len(g.devices) for g in step.groups),
             "replica_lanes": list(step.replica_queues or [])}
            for step_idx, step in enumerate(config.steps)]}
        operator_server = OperatorServer(
            operator_settings, job_dir=logroot(job_id, base=log_base),
            job_id=job_id, metrics_registry=metrics_registry,
            boards=boards_by_step, devobs_plane=devobs_plane,
            config_raw=config.raw, topology=topology,
            queue_probes=queue_probes, termination=termination,
            window=operator_window, sampler=stack_sampler)
        operator_server.start()
        if print_progress:
            print("[rnb-tpu] operator server on http://127.0.0.1:%d "
                  "(actions %s)"
                  % (operator_server.port,
                     "enabled" if operator_settings.allow_actions
                     else "disabled"))

    threads = []
    client_kwargs = dict(overload_policy=config.overload_policy,
                         fault_stats=fault_stats, counter=counter,
                         target_num_videos=num_videos,
                         popularity=config.popularity,
                         deadline_budget_s=(
                             deadline_settings.budget_ms / 1000.0
                             if deadline_settings is not None
                             else None))
    if mean_interval_ms > 0:
        client_args = (config.video_path_iterator,
                       fabric.get_filename_queue(), mean_interval_ms,
                       termination, sta_bar, fin_bar, seed,
                       fabric.filename_num_markers)
        client_impl = poisson_client
    else:
        client_args = (config.video_path_iterator,
                       fabric.get_filename_queue(), num_videos,
                       termination, sta_bar, fin_bar, seed,
                       fabric.filename_num_markers)
        client_impl = bulk_client
    threads.append(threading.Thread(target=client_impl, args=client_args,
                                    kwargs=client_kwargs,
                                    name="client", daemon=True))

    for step_idx, step in enumerate(config.steps):
        is_final = step_idx == config.num_steps - 1
        for group_idx, group in enumerate(step.groups):
            model_kwargs = step.kwargs_for_group(group_idx)
            if step_idx in ragged_kwargs_by_step:
                model_kwargs = dict(model_kwargs,
                                    **ragged_kwargs_by_step[step_idx])
            for instance_idx, device in enumerate(group.devices):
                in_queue, out_queues = fabric.get_queues(step_idx,
                                                         group_idx)
                if netedge_local_q is not None and step_idx == 0:
                    # netedge: the dispatcher owns the filename
                    # queue; local step-0 executors serve the
                    # fallback path off the interposed local queue
                    in_queue = netedge_local_q
                ctx = RunnerContext(
                    in_queue=in_queue,
                    out_queues=out_queues,
                    queue_selector_path=group.queue_selector,
                    print_progress=(is_final and group_idx == 0
                                    and instance_idx == 0
                                    and print_progress),
                    job_id=job_id,
                    device=device,
                    group_idx=group_idx,
                    instance_idx=instance_idx,
                    counter=counter,
                    num_videos=num_videos,
                    termination=termination,
                    step_idx=step_idx,
                    sta_bar=sta_bar,
                    fin_bar=fin_bar,
                    model_class_path=step.model,
                    num_segments=step.num_segments,
                    input_rings=fabric.get_input_rings(step_idx, group_idx),
                    output_ring=fabric.get_output_ring(step_idx, group_idx,
                                                       instance_idx),
                    out_trackers=fabric.get_out_trackers(step_idx,
                                                         group_idx),
                    sync_outputs=not step.async_dispatch,
                    log_base=log_base,
                    model_kwargs=model_kwargs,
                    summary_sink=summary_sink if is_final else None,
                    containment=config.fault_containment,
                    overload_policy=config.overload_policy,
                    max_retries=step.max_retries,
                    retry_backoff_ms=step.retry_backoff_ms,
                    fault_plan=fault_plan,
                    fault_stats=fault_stats,
                    cache_sink=cache_sink,
                    staging_sink=staging_sink,
                    autotune=(autotune_settings if step.autotune
                              else None),
                    autotune_sink=autotune_sink,
                    pager=pager,
                    compile_sink=compile_sink,
                    pad_sink=pad_sink,
                    ragged_sink=ragged_sink,
                    shard_sink=shard_sink,
                    tracer=tracer,
                    handoff_settings=handoff_settings,
                    handoff_edge=("step%d->step%d"
                                  % (step_idx - 1, step_idx)
                                  if step_idx > 0 else ""),
                    handoff_sink=handoff_sink,
                    placement_sink=placement_sink,
                    out_depths=depths_by_step.get(step_idx + 1),
                    out_queue_indices=(list(group.out_queues)
                                       if group.out_queues else None),
                    in_depths=(depths_by_step.get(step_idx)
                               if step.replica_queues
                               and group.in_queue
                               in step.replica_queues else None),
                    in_queue_idx=group.in_queue,
                    health_board=(boards_by_step.get(step_idx)
                                  if step.replica_queues
                                  and group.in_queue
                                  in step.replica_queues else None),
                    out_health_board=boards_by_step.get(step_idx + 1),
                    sibling_queues=(
                        {q: fabric.queues[step_idx - 1][q]
                         for q in step.replica_queues}
                        if step_idx > 0 and step.replica_queues
                        and group.in_queue in step.replica_queues
                        else None),
                    deadline=deadline_settings,
                    deadline_stats=deadline_stats,
                    out_hedges=governors_by_step.get(step_idx + 1),
                    in_hedges=(governors_by_step.get(step_idx)
                               if step.replica_queues
                               and group.in_queue
                               in step.replica_queues else None),
                    critpath=critpath_settings is not None,
                )
                threads.append(threading.Thread(
                    target=runner, args=(ctx,),
                    name="runner-s%d-g%d-i%d" % (step_idx, group_idx,
                                                 instance_idx),
                    daemon=True))

    for t in threads:
        t.start()

    if netedge_client is not None:
        # transport threads, not stages: they never join the barriers
        netedge_client.start()

    if xprof:
        # device-op tracing of the measured window only: wait until
        # every other participant is parked on the start barrier (model
        # compile/warm-up happens in the runner ctors BEFORE they reach
        # it) so the trace contains no warm-up ops, then start capture
        # before releasing the barrier so neither the trace nor
        # time_start is skewed by profiler setup. The reference left its
        # CUPTI bridge unwired from the runner (SURVEY.md §5 tracing);
        # here the same three-call contract covers the job.
        from rnb_tpu import profiler
        deadline = time.time() + BARRIER_TIMEOUT_S
        while sta_bar.n_waiting < bar_total - 1:
            if time.time() > deadline:
                break  # let sta_bar.wait() raise the real timeout
            time.sleep(0.01)
        # Window markers: a uniquely named jitted no-op dispatched at
        # window start and end. Its module name lands in the device
        # trace ON THE DEVICE'S OWN CLOCK, delimiting the measured
        # window without any host-epoch mapping — necessary because
        # the remote (axon) xplane timeline is session-scoped and its
        # tick rate is not host-nanoseconds (observed ~4.3x wall), so
        # epoch arithmetic cannot locate the window. Compiled here,
        # BEFORE capture starts, so no compile lands in the trace.
        import jax

        def rnb_window_marker(x):
            return x + 1

        _marker = jax.jit(rnb_window_marker)
        _marker_arg = jax.numpy.zeros((3, 91), jax.numpy.float32)
        jax.block_until_ready(_marker(_marker_arg))
        profiler.initialize(os.path.join(logroot(job_id, base=log_base),
                                         "xprof"))
        jax.block_until_ready(_marker(_marker_arg))
    import resource

    from rnb_tpu import hostprof
    if hostprof.ENABLED:
        # scope the section accumulator to THIS measured window — a
        # multi-run process (config sweep) must not fold earlier runs'
        # totals (or this run's warmup) into this run's report
        hostprof.reset()
    if tracer is not None:
        # occupancy sampling covers the measured window (plus the
        # short drain); started here so warm-up/compile never lands
        # in the timeline
        tracer.start_sampler()
    if metrics_registry is not None:
        # the flusher covers the measured window: every poll source
        # is registered by now (runner registration happens before
        # the start barrier)
        metrics_registry.start()
    if devobs_plane is not None:
        # worker up before the barrier (sources are all registered),
        # but capture windows stay armed until note_run_started below
        # so warmup compile never lands in a capture
        devobs_plane.start()
    sta_bar.wait()
    ru_start = resource.getrusage(resource.RUSAGE_SELF)
    if devobs_plane is not None:
        devobs_plane.note_run_started()
    time_start = time.time()
    # the operator server's measured-window clock (/whatif wall_s,
    # /statusz) starts ticking with the window itself
    operator_window["t0"] = time_start
    if stack_sampler is not None:
        # the wall-clock sampler covers the measured window (plus the
        # short drain to thread join) — started AFTER the barrier so
        # multi-minute warmup compiles never land in the folded
        # stacks and the samples ~ sample_hz x wall invariant holds
        stack_sampler.start()
    if print_progress:
        print("START! %f" % time_start)

    fin_bar.wait()
    time_end = time.time()
    # host-core accounting over the measured window: on the 1-core
    # bench host, (utime+stime)/wall ~ 1.0 means the host core is the
    # ceiling — the quantitative side of any "host-bound" claim. Taken
    # between the same barriers as the wall clock. Decode-pool threads
    # are in-process, so their CPU time is included.
    ru_end = resource.getrusage(resource.RUSAGE_SELF)
    host_cpu_s = ((ru_end.ru_utime + ru_end.ru_stime)
                  - (ru_start.ru_utime + ru_start.ru_stime))
    total_time = time_end - time_start
    if xprof:
        jax.block_until_ready(_marker(_marker_arg))  # end-of-window mark
        # anchor BEFORE stop_trace: stopping pulls the whole trace
        # through the tunnel (measured ~70 s for 265k events), so an
        # after-the-fact stamp would place the device timeline's end
        # over a minute past the last captured op. Taken here, the
        # stamp coincides with the device's last ops up to the short
        # post-window drain (EOS flush dispatches), which biases the
        # mapped window late by at most that drain.
        flush_epoch = time.time()
        profiler.flush()
        ops = profiler.report(keep_trace=True, include_plane=True)
        with open(os.path.join(logroot(job_id, base=log_base),
                               "xprof-ops.txt"), "w") as f:
            # per-plane clock bases differ (XLine timestamps have no
            # shared origin across host/device planes), so the plane
            # is part of the record: busy-time aggregation is only
            # valid within one plane (scripts/device_busy.py groups).
            f.write("# t0_ns t1_ns plane op_name\n")
            # The axon/remote xplane contains the device's whole
            # session, not just [start_trace, stop_trace] (observed:
            # 52 s of device timeline for a 4.4 s measured window), so
            # the measured window is recorded in host epoch; the
            # analyzer maps it into device clock by anchoring
            # flush_epoch to the last device timestamp.
            f.write("# window_epoch %f %f flush_epoch %f\n"
                    % (time_start, time_end, flush_epoch))
            for name, t0, t1, plane in ops:
                f.write("%d %d %s %s\n"
                        % (t0, t1, plane.replace(" ", "_") or "-",
                           name))
        if print_progress:
            print("xprof: %d device-op intervals -> xprof-ops.txt"
                  % len(ops))
    if print_progress:
        print("FINISH! %f" % time_end)
        print("Time: %f sec" % total_time)
        print("Number of videos: %d videos" % num_videos)

    for t in threads:
        t.join(timeout=60)

    if netedge_client is not None:
        # after the stage joins: the window is drained (or rerouted),
        # so teardown counters are final. Remote cards carry the
        # peer loader's pad_rows stamps but the peer's PadCounter
        # dies with the peer — the receiver's re-count of shipped
        # emissions keeps the Padding: ledger covering them (--check
        # holds per-request trailer pads <= the meta counter)
        netedge_client.stop()
        netedge_pads = netedge_client.pad_snapshot()
        if netedge_pads["emissions"]:
            pad_sink.append(netedge_pads)
    if netedge_peer is not None:
        netedge_peer.terminate()
        try:
            netedge_peer.wait(timeout=10)
        except Exception:
            netedge_peer.kill()

    if metrics_registry is not None:
        # stop bridging the trace hooks (the tracer export below
        # reads its own buffer, not the module hook); the registry
        # itself keeps running until the final footing flush after
        # every ledger snapshot settled
        trace_mod.ACTIVE = None

    if devobs_plane is not None:
        # stop the capture worker (any still-armed capture is drained
        # with a zero-length window first) and clear the module hooks,
        # then merge the captured device-op intervals into the tracer
        # as device:<plane> tracks — rid-correlated to the model_call
        # spans so the exporter's flow chains draw the host->device
        # arrows — BEFORE the export below writes trace.json
        devobs_mod.ACTIVE = None
        memledger_mod.ACTIVE = None
        devobs_plane.stop()
        if tracer is not None:
            tracer.extend(devobs_plane.device_events(
                devobs_mod.model_call_spans(tracer.snapshot_events())))

    # wall-clock stack sampler: stop, write the flamegraph-folded
    # artifact, and merge the per-role top-frame timeline into the
    # tracer as stacks:<role> tracks BEFORE the export below writes
    # trace.json (the devobs device-track pattern)
    stacks_summary = None
    if stack_sampler is not None:
        stack_sampler.stop()
        stack_sampler.write_folded(
            os.path.join(logroot(job_id, base=log_base),
                         "stacks.folded"))
        if tracer is not None:
            tracer.extend(stack_sampler.trace_events())
        stacks_summary = stack_sampler.summary()

    # trace export: every thread is drained, so the event set is
    # final; clear the module hook BEFORE exporting so a later run in
    # this process can never write into this job's collector
    trace_events = trace_dropped = 0
    if tracer is not None:
        trace_mod.ACTIVE = None
        tracer.stop_sampler()
        trace_path = os.path.join(logroot(job_id, base=log_base),
                                  "trace.json")
        trace_events = tracer.export(trace_path, job_id)
        trace_dropped = tracer.dropped
        if print_progress:
            print("Trace: %d event(s) -> %s (%d dropped at the "
                  "max_events cap)"
                  % (trace_events, trace_path, trace_dropped))

    # per-request phase attribution (rnb_tpu.trace): aggregated over
    # every final-step instance's steady-state records — surfaced only
    # on trace-enabled runs so earlier logs stay byte-stable
    phases_stats = None
    if tracer is not None and summary_sink:
        from rnb_tpu.trace import phase_stats, sorted_phases
        merged: Dict[str, list] = {}
        for s in summary_sink:
            for phase, vals in s.phase_samples(
                    NUM_SUMMARY_SKIPS).items():
                merged.setdefault(phase, []).extend(vals)
        phases_stats = phase_stats(merged) or None

    # critical-path extraction (rnb_tpu.critpath): the blocking-chain
    # aggregation over every final instance's steady completions —
    # stamps only, so it costs nothing on the hot path; hedge/
    # redispatch content stamps ride along from the summaries
    critpath_report = None
    if critpath_settings is not None and summary_sink:
        from rnb_tpu.critpath import aggregate as critpath_aggregate
        lanes_by_step = {
            step_idx: sum(len(g.devices) for g in step.groups)
            for step_idx, step in enumerate(config.steps)}
        critpath_report = critpath_aggregate(
            (row for s in summary_sink
             for row in s.steady_rows(NUM_SUMMARY_SKIPS)),
            lanes_by_step)

    # decoded-clip cache accounting: cache-owning stages appended
    # their final snapshots before the finish barrier (rnb_tpu.runner)
    cache_stats = None
    if cache_sink:
        from rnb_tpu.cache import aggregate_snapshots
        cache_stats = aggregate_snapshots(cache_sink)
    staging_stats = None
    if staging_sink:
        from rnb_tpu.staging import aggregate_snapshots as \
            aggregate_staging
        staging_stats = aggregate_staging(staging_sink)
    autotune_stats = None
    if autotune_sink:
        from rnb_tpu.autotune import aggregate_snapshots as \
            aggregate_autotune
        autotune_stats = aggregate_autotune(autotune_sink)
    # compile/warmup + padding + ragged accounting (every stage reports
    # warmup; jit-owning stages report signatures; batching stages
    # report pad counters; ragged stages report pool counters)
    from rnb_tpu.compilestats import aggregate_compile_records
    compile_stats, warmup_stats = aggregate_compile_records(compile_sink)
    pad_stats = None
    if pad_sink:
        pad_stats = {"pad_rows": 0, "total_rows": 0, "emissions": 0}
        for snap in pad_sink:
            for key in pad_stats:
                pad_stats[key] += int(snap.get(key, 0))
    ragged_stats = None
    if ragged_sink:
        ragged_stats = {"pool_rows": 0, "emissions": 0, "rows": 0,
                        "pad_rows_eliminated": 0, "cache_hit_rows": 0}
        for snap in ragged_sink:
            ragged_stats["pool_rows"] = max(
                ragged_stats["pool_rows"],
                int(snap.get("pool_rows") or 0))
            for key in ("emissions", "rows", "pad_rows_eliminated",
                        "cache_hit_rows"):
                ragged_stats[key] += int(snap.get(key, 0))

    # intra-stage shard accounting (rnb_tpu.parallel.shardplan):
    # declared-degree stages snapshot their merge-collective counters
    # at teardown; replica lanes of the same step sum, the static
    # facts (degree/axis/budgets) are per-step constants
    shard_stats = None
    if shard_sink:
        per_step: Dict[int, Dict[str, Any]] = {}
        for shard_step_idx, snap in shard_sink:
            row = per_step.setdefault(shard_step_idx, {
                "degree": int(snap.get("degree", 1)),
                "axis": str(snap.get("axis", "")),
                "gathers": 0, "collective_us": 0, "rows": 0,
                "budget_mb": round(float(snap.get("budget_mb") or 0.0),
                                   3),
                "projected_mb": round(
                    float(snap.get("projected_mb", 0.0)), 3),
                "min_degree": int(snap.get("min_degree", 0)),
            })
            row["gathers"] += int(snap.get("gathers", 0))
            row["collective_us"] += int(round(
                float(snap.get("collective_ms", 0.0)) * 1e3))
            row["rows"] += int(snap.get("rows", 0))
        shard_stats = {
            "steps": len(per_step),
            "max_degree": max(r["degree"] for r in per_step.values()),
            "gathers": sum(r["gathers"] for r in per_step.values()),
            "collective_us": sum(r["collective_us"]
                                 for r in per_step.values()),
            "rows": sum(r["rows"] for r in per_step.values()),
            "step_detail": {str(k): per_step[k]
                            for k in sorted(per_step)},
        }

    handoff_stats = None
    if handoff_sink:
        from rnb_tpu.handoff import aggregate_snapshots as \
            aggregate_handoff
        handoff_stats = aggregate_handoff(handoff_sink)
    # self-healing accounting (rnb_tpu.health): boards/governors are
    # shared objects, stable once every thread joined above
    health_stats = None
    if boards_by_step or netedge_board is not None:
        from rnb_tpu.health import aggregate_board_snapshots
        snapshots = [b.snapshot() for b in boards_by_step.values()]
        if netedge_board is not None:
            snapshots.append(netedge_board.snapshot())
        health_stats = aggregate_board_snapshots(snapshots)
    deadline_snap = (deadline_stats.snapshot()
                     if deadline_stats is not None else None)
    net_snap = (netedge_stats.snapshot()
                if netedge_stats is not None else None)
    # final witness ledger: every pipeline thread joined above, so the
    # edge set and violation list are settled (config-armed runs only
    # — an externally enabled witness, e.g. the test harness, keeps
    # un-armed runs' logs byte-stable)
    lock_snap = lockwitness.summary() if witness_armed else None
    hedge_stats = None
    if governors_by_step:
        from rnb_tpu.health import aggregate_hedge_snapshots
        hedge_stats = aggregate_hedge_snapshots(
            [g.snapshot() for g in governors_by_step.values()])
    placement_report = None
    if placement_sink is not None:
        import jax
        from rnb_tpu.placement import build_report
        placement_report = build_report(placement_sink, total_time,
                                        len(jax.devices()),
                                        placement_settings.mode)

    metrics_summary = None
    if metrics_registry is not None:
        # the FINAL footing flush: every pipeline thread joined and
        # every ledger snapshot above settled (the hedge snapshot
        # resolves leftover unresolved hedges), so this last
        # metrics.jsonl record's counters must equal the log-meta
        # ledgers exactly — parse_utils --check asserts it. Also
        # services the forced-dump env hook and writes metrics.prom.
        metrics_registry.stop()
        metrics_mod.ACTIVE = None
        metrics_summary = metrics_registry.summary()

    operator_summary = None
    if operator_server is not None:
        # the server outlives the pipeline into teardown (a live
        # scraper may still read the settling /metrics state), and
        # stops before the log-meta write so the Operator: ledger
        # below is final
        operator_server.stop()
        operator_summary = operator_server.summary()

    # what-if engine calibration (rnb_tpu.whatif): built from the
    # FINAL metrics snapshot — the same dict metrics.jsonl holds as
    # its last record, so parse_utils --check can recompute the
    # Whatif: line from the artifacts alone and hold the two equal
    whatif_counters = None
    if whatif_settings is not None:
        from rnb_tpu import whatif as whatif_mod
        whatif_model = None
        if metrics_registry is not None:
            final_snap = metrics_registry.final_snapshot()
            if final_snap is not None:
                whatif_model = whatif_mod.calibrate_from_snapshot(
                    final_snap,
                    whatif_mod.steps_info_from_config(config.raw),
                    wall_s=total_time,
                    arrival_hz=whatif_mod.arrival_hz_from_snapshot(
                        final_snap))
        whatif_counters = whatif_mod.summary_counters(whatif_model)

    compute_summary = None
    memory_summary = None
    if devobs_plane is not None:
        # job-level tflops/mfu use bench.py's exact arithmetic over
        # the SAME measured window, so the Compute: line cross-foots
        # the bench evidence line to the digit on a clean run; the
        # memory snapshot re-samples after every thread joined, so
        # owner rows reflect the settled end-of-run state
        compute_summary = devobs_plane.compute_summary(
            total_time, devobs_mod.devices_used(config.raw))
        memory_summary = devobs_plane.memory_summary()

    # paged-memory ledger (rnb_tpu.pager): every pipeline thread
    # joined, so live/limbo occupancy is settled and the teardown
    # invariant (allocs == frees + live-held pages) is checkable from
    # the line alone; bypassed_batches rides along from the staging
    # plane (the zero-transfer emissions only the pager can produce)
    pages_summary = None
    if pager is not None:
        pages_summary = pager.snapshot()
        pages_summary["bypassed_batches"] = int(
            staging_stats.get("bypassed_batches", 0)
            if staging_stats else 0)

    faults = fault_stats.snapshot()
    num_failed = faults["num_failed"]
    num_shed = faults["num_shed"]
    num_retries = faults["num_retries"]
    # every disposal (success, contained failure, shed) lands in the
    # shared counter; successes are what remains
    num_completed = max(0, counter.value - num_failed - num_shed)

    args_repr = ("Namespace(mean_interval_ms=%d, batch_size=%d, videos=%d, "
                 "queue_size=%d, config_file_path=%r)"
                 % (mean_interval_ms, batch_size, num_videos, queue_size,
                    config_path))
    with open(logmeta(job_id, base=log_base), "w") as f:
        f.write("Args: %s\n" % args_repr)
        f.write("%f %f\n" % (time_start, time_end))
        f.write("Termination flag: %d\n" % termination.value)
        f.write("Faults: num_failed=%d num_shed=%d num_retries=%d\n"
                % (num_failed, num_shed, num_retries))
        if faults["failure_reasons"]:
            f.write("Failure reasons: %s\n"
                    % json.dumps(faults["failure_reasons"],
                                 sort_keys=True))
        if faults["shed_sites"]:
            f.write("Shed sites: %s\n"
                    % json.dumps(faults["shed_sites"], sort_keys=True))
        if faults["overflow_sites"]:
            # abort-policy full-queue events, counted per edge — the
            # parseable replacement for the old stdout warning
            f.write("Queue overflows: %s\n"
                    % json.dumps(faults["overflow_sites"],
                                 sort_keys=True))
        if cache_stats is not None:
            # only cache-enabled runs carry the line, keeping cacheless
            # logs byte-stable with the pre-cache schema
            f.write("Cache: hits=%d misses=%d inserts=%d evictions=%d "
                    "coalesced=%d oversize=%d bytes_resident=%d\n"
                    % (cache_stats["hits"], cache_stats["misses"],
                       cache_stats["inserts"], cache_stats["evictions"],
                       cache_stats["coalesced"], cache_stats["oversize"],
                       cache_stats["bytes_resident"]))
        if staging_stats is not None:
            # only staging-enabled runs carry the line, keeping
            # staging-free logs byte-stable with the earlier schema
            f.write("Staging: slots=%d slot_bytes=%d acquires=%d "
                    "acquire_waits=%d staged_batches=%d "
                    "copied_batches=%d reallocs=%d\n"
                    % (staging_stats["slots"],
                       staging_stats["slot_bytes"],
                       staging_stats["acquires"],
                       staging_stats["acquire_waits"],
                       staging_stats["staged_batches"],
                       staging_stats["copied_batches"],
                       staging_stats["reallocs"]))
        if pages_summary is not None:
            # only pager-enabled runs carry the line, keeping pager-off
            # logs (including the Staging: line above) byte-stable with
            # the earlier schema; --check holds allocs == frees + live
            # at teardown, feature_hits <= feature_lookups, and
            # gather_rows <= the ragged cache_hit_rows it serves
            f.write("Pages: arenas=%d pages=%d page_rows=%d live=%d "
                    "limbo=%d bytes=%d allocs=%d frees=%d "
                    "alloc_fails=%d gathers=%d gather_rows=%d "
                    "feature_lookups=%d feature_hits=%d "
                    "feature_inserts=%d feature_evictions=%d "
                    "feature_gathers=%d feature_gather_rows=%d "
                    "feature_bytes_saved=%d feature_entries=%d "
                    "bypassed_batches=%d\n"
                    % (pages_summary["arenas"], pages_summary["pages"],
                       pages_summary["page_rows"],
                       pages_summary["live"], pages_summary["limbo"],
                       pages_summary["bytes"],
                       pages_summary["allocs"], pages_summary["frees"],
                       pages_summary["alloc_fails"],
                       pages_summary["gathers"],
                       pages_summary["gather_rows"],
                       pages_summary["feature_lookups"],
                       pages_summary["feature_hits"],
                       pages_summary["feature_inserts"],
                       pages_summary["feature_evictions"],
                       pages_summary["feature_gathers"],
                       pages_summary["feature_gather_rows"],
                       pages_summary["feature_bytes_saved"],
                       pages_summary["feature_entries"],
                       pages_summary["bypassed_batches"]))
        if autotune_stats is not None:
            # only autotune-enabled runs carry the lines, keeping
            # static-batching logs byte-stable with the earlier schema
            f.write("Autotune: decisions=%d immediate=%d held=%d "
                    "emissions=%d deadline_us_min=%d "
                    "deadline_us_max=%d deadline_us_sum=%d\n"
                    % (autotune_stats["decisions"],
                       autotune_stats["immediate"],
                       autotune_stats["held"],
                       autotune_stats["emissions"],
                       autotune_stats["deadline_us_min"],
                       autotune_stats["deadline_us_max"],
                       autotune_stats["deadline_us_sum"]))
            if autotune_stats["bucket_counts"]:
                f.write("Autotune buckets: %s\n"
                        % json.dumps(autotune_stats["bucket_counts"],
                                     sort_keys=True))
        if pad_stats is not None:
            # padding-waste accounting over every batching stage: the
            # bucketed path quantifies its pad work; a ragged run shows
            # ~0 here (pad FLOPs land in Ragged: pad_rows_eliminated)
            f.write("Padding: pad_rows=%d total_rows=%d "
                    "pad_emissions=%d\n"
                    % (pad_stats["pad_rows"], pad_stats["total_rows"],
                       pad_stats["emissions"]))
        if ragged_stats is not None:
            # only ragged-enabled runs carry the line, keeping bucketed
            # logs byte-stable with the earlier schema
            f.write("Ragged: pool_rows=%d emissions=%d rows=%d "
                    "pad_rows_eliminated=%d cache_hit_rows=%d\n"
                    % (ragged_stats["pool_rows"],
                       ragged_stats["emissions"], ragged_stats["rows"],
                       ragged_stats["pad_rows_eliminated"],
                       ragged_stats["cache_hit_rows"]))
        if shard_stats is not None:
            # only declared-shard runs carry the lines, keeping
            # unsharded logs byte-stable with the earlier schema;
            # --check holds degree x replicas <= the device budget,
            # collective_us <= the inference span sum (the merge is
            # nested inside model_call), and per-step rows footing
            f.write("Shard: steps=%d max_degree=%d gathers=%d "
                    "collective_us=%d rows=%d\n"
                    % (shard_stats["steps"],
                       shard_stats["max_degree"],
                       shard_stats["gathers"],
                       shard_stats["collective_us"],
                       shard_stats["rows"]))
            f.write("Shard steps: %s\n"
                    % json.dumps(shard_stats["step_detail"],
                                 sort_keys=True))
        if handoff_stats is not None:
            # only handoff-enabled runs carry the lines, keeping
            # pre-handoff logs byte-stable with the earlier schema;
            # d2d_edges + host_edges == edges and host_bytes == 0 on
            # device-resident edges are --check invariants
            f.write("Handoff: edges=%d d2d_edges=%d host_edges=%d "
                    "d2d_bytes=%d host_bytes=%d\n"
                    % (handoff_stats["edges"],
                       handoff_stats["d2d_edges"],
                       handoff_stats["host_edges"],
                       handoff_stats["d2d_bytes"],
                       handoff_stats["host_bytes"]))
            if handoff_stats["edge_detail"]:
                f.write("Handoff edges: %s\n"
                        % json.dumps(handoff_stats["edge_detail"],
                                     sort_keys=True))
        if placement_report is not None:
            # the measured-cost plan: per-step dispatch costs, the
            # executed plan's predicted occupancy (parse_utils --check
            # holds it to the traced busy fraction), and the
            # recommendation over the device budget
            f.write("Placement: %s\n"
                    % json.dumps(placement_report, sort_keys=True))
        if health_stats is not None:
            # only health-enabled replica runs carry the lines (logs
            # stay byte-stable otherwise); --check replays every
            # lane's path against the legal automaton and holds
            # routes_after_open to 0
            f.write("Health: lanes=%d transitions=%d opens=%d "
                    "evictions=%d probes=%d redispatches=%d "
                    "routes_after_open=%d\n"
                    % (health_stats["lanes"],
                       health_stats["transitions"],
                       health_stats["opens"],
                       health_stats["evictions"],
                       health_stats["probes"],
                       health_stats["redispatches"],
                       health_stats["routes_after_open"]))
            if health_stats["lane_detail"]:
                f.write("Health lanes: %s\n"
                        % json.dumps(health_stats["lane_detail"],
                                     sort_keys=True))
        if deadline_snap is not None:
            # only deadline-enabled runs carry the lines; --check
            # cross-foots the per-site counts against the
            # deadline-suffixed entries of the Shed sites: ledger
            f.write("Deadline: budget_ms=%d expired=%d\n"
                    % (round(deadline_settings.budget_ms),
                       deadline_snap["expired"]))
            if deadline_snap["sites"]:
                f.write("Deadline sites: %s\n"
                        % json.dumps(deadline_snap["sites"],
                                     sort_keys=True))
        if hedge_stats is not None:
            # only hedge_ms runs carry the line; won + lost == fired
            # is a --check invariant (every fired hedge resolves
            # exactly once), and wasted_ms is the honesty counter —
            # hedge compute is overhead, never throughput
            f.write("Hedge: fired=%d won=%d lost=%d wasted_ms=%d\n"
                    % (hedge_stats["fired"], hedge_stats["won"],
                       hedge_stats["lost"], hedge_stats["wasted_ms"]))
        if compile_stats:
            # per-step jit-entry signatures: warmup vocabulary size +
            # signatures first seen inside the measured window
            # (steady_new > 0 = mid-run recompile; --check fails it)
            f.write("Compiles: %s\n"
                    % json.dumps(compile_stats, sort_keys=True))
        if warmup_stats:
            f.write("Warmup: %s\n"
                    % json.dumps(warmup_stats, sort_keys=True))
        if tracer is not None:
            # trace-export accounting: events written to trace.json
            # and events dropped at the max_events cap — parse_utils
            # --check cross-checks the count against the artifact
            f.write("Trace: events=%d dropped=%d\n"
                    % (trace_events, trace_dropped))
        if phases_stats is not None:
            # only trace-enabled runs carry the line: per-phase
            # mean/p99/count, phases summing to end-to-end latency
            # per request (parse_utils --check asserts it)
            f.write("Phases: %s\n"
                    % json.dumps(phases_stats, sort_keys=True))
        if metrics_summary is not None:
            # only metrics-enabled runs carry the lines, keeping
            # metrics-off logs byte-stable with the earlier schema;
            # --check cross-foots metrics.jsonl's final snapshot
            # against the ledger lines above and validates every
            # flight dump per validate_trace
            f.write("Metrics: snapshots=%d series=%d dumps=%d "
                    "triggers=%d\n"
                    % (metrics_summary["snapshots"],
                       metrics_summary["series"],
                       metrics_summary["dumps"],
                       metrics_summary["triggers"]))
            f.write("Slo: tracked=%d within=%d missed=%d "
                    "burn_max_milli=%d\n"
                    % (metrics_summary["slo_tracked"],
                       metrics_summary["slo_within"],
                       metrics_summary["slo_missed"],
                       metrics_summary["burn_max_milli"]))
        if compute_summary is not None:
            # every devobs run carries the line (zero-flops when no
            # stage declares a compute profile — the captures counter
            # must stay checkable), devobs-off logs stay byte-stable;
            # --check cross-foots flops_total against the per-stage
            # detail, recomputes tflops_milli from the integer
            # fields, and bounds the mfu
            f.write("Compute: stages=%d dispatches=%d rows=%d "
                    "flops_total=%d window_us=%d tflops_milli=%d "
                    "mfu_e4=%d captures=%d\n"
                    % (compute_summary["stages"],
                       compute_summary["dispatches"],
                       compute_summary["rows"],
                       compute_summary["flops_total"],
                       compute_summary["window_us"],
                       compute_summary["tflops_milli"],
                       compute_summary["mfu_e4"],
                       compute_summary["captures"]))
            f.write("Compute stages: %s\n"
                    % json.dumps(compute_summary["stage_detail"],
                                 sort_keys=True))
        if memory_summary is not None:
            # owner rows MUST sum to total_bytes and peak >= final —
            # the --check footing invariants; reconciled=1 means the
            # ledger's live-backed claims fit inside the backend's
            # own live-buffer total
            f.write("Memory: owners=%d devices=%d total_bytes=%d "
                    "peak_bytes=%d watermark_bytes=%d "
                    "watermark_hits=%d live_bytes=%d reconciled=%d\n"
                    % (len(memory_summary["owners"]),
                       len(memory_summary["devices"]),
                       memory_summary["total_bytes"],
                       memory_summary["peak_bytes"],
                       memory_summary["watermark_bytes"],
                       memory_summary["watermark_hits"],
                       memory_summary["live_bytes"],
                       memory_summary["reconciled"]))
            if memory_summary["owners"]:
                f.write("Memory owners: %s\n"
                        % json.dumps(memory_summary["owners"],
                                     sort_keys=True))
        if critpath_report is not None:
            # only critpath-enabled runs carry the lines, keeping
            # earlier logs byte-stable; --check re-derives every
            # field from the timing tables and holds the partition
            # residual under 1 ms per request
            f.write("Critpath: requests=%d segments=%d "
                    "residual_us_max=%d hedged=%d redispatched=%d "
                    "bound_step=%d bound_vps_milli=%d\n"
                    % (critpath_report["requests"],
                       critpath_report["segments"],
                       critpath_report["residual_us_max"],
                       critpath_report["hedged"],
                       critpath_report["redispatched"],
                       critpath_report["bound_step"],
                       critpath_report["bound_vps_milli"]))
            f.write("Critpath stages: %s\n"
                    % json.dumps(critpath_report["stage_detail"],
                                 sort_keys=True))
        if whatif_counters is not None:
            # only whatif-enabled runs carry the line; --check
            # recomputes the prediction from metrics.jsonl + the
            # config copy alone and holds it to +-1 milli-vps
            f.write("Whatif: stages=%d calibrated=%d "
                    "pred_vps_milli=%d bottleneck_step=%d\n"
                    % (whatif_counters["stages"],
                       whatif_counters["calibrated"],
                       whatif_counters["pred_vps_milli"],
                       whatif_counters["bottleneck_step"]))
        if operator_summary is not None:
            # only operator-enabled runs carry the line (logs stay
            # byte-stable otherwise); --check holds it to the
            # operator.json artifact both ways
            f.write("Operator: scrapes=%d actions=%d denied=%d "
                    "errors=%d\n"
                    % (operator_summary["scrapes"],
                       operator_summary["actions"],
                       operator_summary["denied"],
                       operator_summary["errors"]))
        if stacks_summary is not None:
            # operator runs with sample_hz > 0 only; the stacks.folded
            # counts sum to total and samples track sample_hz x wall
            # (--check invariants)
            f.write("Stacks: samples=%d threads=%d folded=%d "
                    "total=%d\n"
                    % (stacks_summary["samples"],
                       stacks_summary["threads"],
                       stacks_summary["folded"],
                       stacks_summary["total"]))
        if net_snap is not None:
            # the edge's exactly-once ledger, cross-footed by --check:
            # frames_sent == frames_acked + resent_pending, dedup
            # drops == dup arrivals, zero stranded on target-reached
            f.write("Net: frames_sent=%d frames_acked=%d "
                    "resent_pending=%d resends=%d beats=%d "
                    "reconnects=%d remote=%d local=%d dedup_drops=%d "
                    "dup_arrivals=%d wire_bytes=%d frame_bytes=%d "
                    "window_stranded=%d open_before_timeout=%d\n"
                    % (net_snap["frames_sent"],
                       net_snap["frames_acked"],
                       net_snap["resent_pending"],
                       net_snap["resends"], net_snap["beats"],
                       net_snap["reconnects"], net_snap["remote"],
                       net_snap["local"], net_snap["dedup_drops"],
                       net_snap["dup_arrivals"],
                       net_snap["wire_bytes"],
                       net_snap["frame_bytes"],
                       net_snap["window_stranded"],
                       net_snap["open_before_timeout"]))
            f.write("Net errors: total=%d refused=%d reset=%d "
                    "timeout=%d partial_frame=%d corrupt=%d\n"
                    % (net_snap["err_total"], net_snap["err_refused"],
                       net_snap["err_reset"], net_snap["err_timeout"],
                       net_snap["err_partial_frame"],
                       net_snap["err_corrupt"]))
        if lock_snap is not None:
            # witness-armed runs only; --check holds violations to
            # zero, the Lock edges: detail to these counts, and every
            # observed edge to the static RNB-C lock-order graph
            f.write("Locks: tracked=%d acquires=%d edges=%d "
                    "violations=%d\n"
                    % (lock_snap["locks"], lock_snap["acquires"],
                       len(lock_snap["edges"]),
                       len(lock_snap["violations"])))
            f.write("Lock edges: %s\n"
                    % lockwitness.format_edges(lock_snap))
    if faults["dead_letters"]:
        # the controller's dead-letter record: one line per contained
        # failure (detail capped at FaultStats.MAX_DEAD_LETTERS; the
        # counters above stay exact regardless)
        with open(os.path.join(logroot(job_id, base=log_base),
                               "failed-requests.txt"), "w") as f:
            f.write("# request_id step reason\n")
            for rid, step_idx, reason in faults["dead_letters"]:
                f.write("%s %d %s\n" % (rid, step_idx, reason))
    shutil.copyfile(config_path,
                    os.path.join(logroot(job_id, base=log_base),
                                 os.path.basename(config_path)))

    # aggregate end-to-end latency percentiles over every final-step
    # instance, skipping warm records per the summary convention
    from rnb_tpu.telemetry import latency_percentiles
    latencies = []
    clips_completed = 0
    for s in summary_sink:
        latencies.extend(s.latencies_ms(NUM_SUMMARY_SKIPS))
        clips_completed += s.total_clips()
    pct = latency_percentiles(latencies)
    p50, p99 = pct.get(50.0), pct.get(99.0)
    if pct and print_progress:
        print("Latency p50: %.3f ms  p99: %.3f ms (%d steady-state "
              "records, successes only)" % (p50, p99, len(latencies)))
    if (num_failed or num_shed or num_retries) and print_progress:
        print("Faults: %d failed, %d shed, %d retries (%s)"
              % (num_failed, num_shed, num_retries,
                 ", ".join("%s=%d" % kv for kv in sorted(
                     faults["failure_reasons"].items())) or "-"))
    if cache_stats is not None and print_progress:
        lookups = cache_stats["hits"] + cache_stats["misses"]
        print("Cache: %d hits / %d lookups (%.1f%% hit-rate), "
              "%d coalesced, %d evictions, %.1f MiB resident"
              % (cache_stats["hits"], lookups,
                 100.0 * cache_stats["hits"] / lookups if lookups else 0.0,
                 cache_stats["coalesced"], cache_stats["evictions"],
                 cache_stats["bytes_resident"] / (1 << 20)))
    if staging_stats is not None and print_progress:
        emissions = (staging_stats["staged_batches"]
                     + staging_stats["copied_batches"])
        print("Staging: %d/%d emissions zero-copy, %d slot(s) "
              "(%.1f MiB), %d acquire wait(s), %d realloc(s)"
              % (staging_stats["staged_batches"], emissions,
                 staging_stats["slots"],
                 staging_stats["slot_bytes"] / (1 << 20),
                 staging_stats["acquire_waits"],
                 staging_stats["reallocs"]))
    if pages_summary is not None and print_progress:
        print("Pages: %d/%d pages live (%.1f MiB slab), %d gathers "
              "(%d rows), feature %d/%d hits, %d emission(s) with "
              "zero transfer bytes"
              % (pages_summary["live"], pages_summary["pages"],
                 pages_summary["bytes"] / (1 << 20),
                 pages_summary["gathers"] + pages_summary["feature_gathers"],
                 pages_summary["gather_rows"]
                 + pages_summary["feature_gather_rows"],
                 pages_summary["feature_hits"],
                 pages_summary["feature_lookups"],
                 pages_summary["bypassed_batches"]))
    if autotune_stats is not None and print_progress:
        print("Autotune: %d decision(s) (%d immediate / %d held), "
              "%d emission(s), buckets %s"
              % (autotune_stats["decisions"],
                 autotune_stats["immediate"], autotune_stats["held"],
                 autotune_stats["emissions"],
                 json.dumps(autotune_stats["bucket_counts"],
                            sort_keys=True)))
    if handoff_stats is not None and print_progress:
        print("Handoff: %d edge take(s) — %d d2d (%.1f MiB on-device) "
              "/ %d host (%.1f MiB through host memory)"
              % (handoff_stats["edges"], handoff_stats["d2d_edges"],
                 handoff_stats["d2d_bytes"] / (1 << 20),
                 handoff_stats["host_edges"],
                 handoff_stats["host_bytes"] / (1 << 20)))
    if placement_report is not None and print_progress:
        print("Placement plan (predicted occupancy over %d devices): %s"
              % (placement_report["device_budget"],
                 json.dumps(placement_report["plan"], sort_keys=True)))
    if health_stats is not None and print_progress:
        print("Health: %d lane(s), %d transition(s), %d open(s), "
              "%d eviction(s), %d probe(s), %d redispatch(es)"
              % (health_stats["lanes"], health_stats["transitions"],
                 health_stats["opens"], health_stats["evictions"],
                 health_stats["probes"],
                 health_stats["redispatches"]))
    if deadline_snap is not None and print_progress:
        print("Deadline: budget %d ms, %d expired request(s) shed (%s)"
              % (round(deadline_settings.budget_ms),
                 deadline_snap["expired"],
                 ", ".join("%s=%d" % kv for kv in sorted(
                     deadline_snap["sites"].items())) or "-"))
    if metrics_summary is not None and print_progress:
        print("Metrics: %d snapshot(s) over %d series -> "
              "metrics.jsonl, %d flight dump(s) from %d trigger(s); "
              "SLO %d/%d within (peak burn %.3f)"
              % (metrics_summary["snapshots"],
                 metrics_summary["series"],
                 metrics_summary["dumps"],
                 metrics_summary["triggers"],
                 metrics_summary["slo_within"],
                 metrics_summary["slo_tracked"],
                 metrics_summary["burn_max_milli"] / 1000.0))
    if compute_summary is not None and print_progress:
        print("Compute: %d stage(s), %d dispatch(es), %d row(s), "
              "%.3f achieved TFLOP/s over the window (mfu %s), "
              "%d capture(s)"
              % (compute_summary["stages"],
                 compute_summary["dispatches"],
                 compute_summary["rows"],
                 compute_summary["tflops_milli"] / 1000.0,
                 ("%.4f" % (compute_summary["mfu_e4"] / 10000.0)
                  if compute_summary["mfu_e4"] >= 0
                  else "n/a: unknown device peak"),
                 compute_summary["captures"]))
    if memory_summary is not None and print_progress:
        print("Memory: %.2f MiB resident (peak %.2f MiB) across %d "
              "owner(s); live-buffer reconcile: %s"
              % (memory_summary["total_bytes"] / (1 << 20),
                 memory_summary["peak_bytes"] / (1 << 20),
                 len(memory_summary["owners"]),
                 "ok" if memory_summary["reconciled"]
                 else ("%.2f MiB live"
                       % (memory_summary["live_bytes"] / (1 << 20))
                       if memory_summary["live_bytes"]
                       else "unavailable")))
    if hedge_stats is not None and print_progress:
        print("Hedge: %d fired, %d won by the hedge / %d by the "
              "original, %d ms of loser service wasted"
              % (hedge_stats["fired"], hedge_stats["won"],
                 hedge_stats["lost"], hedge_stats["wasted_ms"]))
    if net_snap is not None and print_progress:
        print("Net: %d frame(s) sent / %d acked, %d resend(s), "
              "%d reconnect(s), %d remote / %d local route(s), "
              "%d error(s)"
              % (net_snap["frames_sent"], net_snap["frames_acked"],
                 net_snap["resends"], net_snap["reconnects"],
                 net_snap["remote"], net_snap["local"],
                 net_snap["err_total"]))
    if lock_snap is not None and print_progress:
        print("Locks: %d witnessed lock(s), %d acquisition(s), "
              "%d order edge(s), %d violation(s)"
              % (lock_snap["locks"], lock_snap["acquires"],
                 len(lock_snap["edges"]),
                 len(lock_snap["violations"])))
    if ragged_stats is not None and print_progress:
        print("Ragged: %d emission(s), %d valid row(s) at pool_rows=%d"
              ", %d pad row(s) eliminated vs the bucketed rule, "
              "%d cache-hit row(s)"
              % (ragged_stats["emissions"], ragged_stats["rows"],
                 ragged_stats["pool_rows"],
                 ragged_stats["pad_rows_eliminated"],
                 ragged_stats["cache_hit_rows"]))
    recompiled = sorted(step for step, sigs in compile_stats.items()
                        if sigs.get("steady_new", 0) > 0)
    if recompiled:
        # a signature first seen inside the measured window is a
        # silent XLA compile on the hot path — exactly what warmup
        # (and the ragged one-shape contract) exists to prevent
        print("[rnb-tpu] WARNING: mid-run recompile signature(s) on %s "
              "(Compiles: steady_new > 0)" % ", ".join(recompiled),
              file=sys.stderr)
    if phases_stats is not None and print_progress:
        print("Phases (per-request attribution, mean/p99 ms):")
        for phase in sorted_phases(phases_stats):
            s = phases_stats[phase]
            print("  %-18s %8.3f / %8.3f  (n=%d)"
                  % (phase, s["mean_ms"], s["p99_ms"], s["count"]))
    if critpath_report is not None and print_progress:
        from rnb_tpu.critpath import ranking as critpath_ranking
        ranked = critpath_ranking(critpath_report["stage_detail"])
        print("Critpath: %d request(s), top blockers %s; bound "
              "step%d at %.3f videos/s"
              % (critpath_report["requests"],
                 ", ".join("%s %.1f ms" % (seg, total)
                           for seg, total, _mean in ranked[:3]),
                 critpath_report["bound_step"],
                 critpath_report["bound_vps_milli"] / 1000.0))
    if whatif_counters is not None and print_progress:
        print("Whatif: %d stage(s) calibrated=%d, self-predicted "
              "%.3f videos/s (bottleneck step %d)"
              % (whatif_counters["stages"],
                 whatif_counters["calibrated"],
                 whatif_counters["pred_vps_milli"] / 1000.0,
                 whatif_counters["bottleneck_step"]))
    if operator_summary is not None and print_progress:
        print("Operator: %d scrape(s), %d action(s), %d denied, "
              "%d error(s)"
              % (operator_summary["scrapes"],
                 operator_summary["actions"],
                 operator_summary["denied"],
                 operator_summary["errors"]))
    if stacks_summary is not None and print_progress:
        print("Stacks: %d tick(s) over %d role(s) -> %d folded "
              "stack(s) (%d samples) in stacks.folded"
              % (stacks_summary["samples"], stacks_summary["threads"],
                 stacks_summary["folded"], stacks_summary["total"]))

    if hostprof.ENABLED:
        lines = hostprof.report_lines(total_time)
        with open(os.path.join(logroot(job_id, base=log_base),
                               "hostprof.txt"), "w") as f:
            f.write("# wall_s %.3f host_cpu_s %.3f host_cpu_frac %.3f\n"
                    % (total_time, host_cpu_s,
                       host_cpu_s / total_time if total_time else 0.0))
            f.write("\n".join(lines) + "\n")
        if print_progress:
            print("\n".join(lines))

    return BenchmarkResult(
        job_id=job_id,
        total_time_s=total_time,
        num_videos=num_videos,
        termination_flag=int(termination.value),
        # successes only: shed/failed requests must not inflate the
        # headline rate (success-rate and shed-rate are first-class
        # metrics next to it)
        throughput_vps=(num_completed / total_time if total_time > 0
                        else 0.0),
        log_dir=logroot(job_id, base=log_base),
        p50_latency_ms=p50,
        p99_latency_ms=p99,
        clips_completed=clips_completed,
        host_cpu_s=host_cpu_s,
        num_completed=num_completed,
        num_failed=num_failed,
        num_shed=num_shed,
        num_retries=num_retries,
        failure_reasons=dict(faults["failure_reasons"]),
        shed_sites=dict(faults["shed_sites"]),
        cache_hits=cache_stats["hits"] if cache_stats else 0,
        cache_misses=cache_stats["misses"] if cache_stats else 0,
        cache_inserts=cache_stats["inserts"] if cache_stats else 0,
        cache_evictions=cache_stats["evictions"] if cache_stats else 0,
        cache_coalesced=cache_stats["coalesced"] if cache_stats else 0,
        cache_oversize=cache_stats["oversize"] if cache_stats else 0,
        cache_bytes_resident=(cache_stats["bytes_resident"]
                              if cache_stats else 0),
        staging_slots=staging_stats["slots"] if staging_stats else 0,
        staging_slot_bytes=(staging_stats["slot_bytes"]
                            if staging_stats else 0),
        staging_acquires=(staging_stats["acquires"]
                          if staging_stats else 0),
        staging_acquire_waits=(staging_stats["acquire_waits"]
                               if staging_stats else 0),
        staging_staged_batches=(staging_stats["staged_batches"]
                                if staging_stats else 0),
        staging_copied_batches=(staging_stats["copied_batches"]
                                if staging_stats else 0),
        staging_reallocs=(staging_stats["reallocs"]
                          if staging_stats else 0),
        autotune_decisions=(autotune_stats["decisions"]
                            if autotune_stats else 0),
        autotune_immediate=(autotune_stats["immediate"]
                            if autotune_stats else 0),
        autotune_held=autotune_stats["held"] if autotune_stats else 0,
        autotune_emissions=(autotune_stats["emissions"]
                            if autotune_stats else 0),
        autotune_deadline_us_min=(autotune_stats["deadline_us_min"]
                                  if autotune_stats else 0),
        autotune_deadline_us_max=(autotune_stats["deadline_us_max"]
                                  if autotune_stats else 0),
        autotune_deadline_us_sum=(autotune_stats["deadline_us_sum"]
                                  if autotune_stats else 0),
        autotune_bucket_counts=(dict(autotune_stats["bucket_counts"])
                                if autotune_stats else {}),
        queue_overflows=dict(faults["overflow_sites"]),
        phases=dict(phases_stats) if phases_stats else {},
        trace_events=trace_events,
        trace_dropped=trace_dropped,
        pad_rows=pad_stats["pad_rows"] if pad_stats else 0,
        total_rows=pad_stats["total_rows"] if pad_stats else 0,
        pad_emissions=pad_stats["emissions"] if pad_stats else 0,
        ragged_pool_rows=(ragged_stats["pool_rows"]
                          if ragged_stats else 0),
        ragged_emissions=(ragged_stats["emissions"]
                          if ragged_stats else 0),
        ragged_rows=ragged_stats["rows"] if ragged_stats else 0,
        ragged_pad_rows_eliminated=(
            ragged_stats["pad_rows_eliminated"] if ragged_stats else 0),
        ragged_cache_hit_rows=(ragged_stats["cache_hit_rows"]
                               if ragged_stats else 0),
        shard_steps=shard_stats["steps"] if shard_stats else 0,
        shard_max_degree=(shard_stats["max_degree"]
                          if shard_stats else 0),
        shard_gathers=shard_stats["gathers"] if shard_stats else 0,
        shard_collective_us=(shard_stats["collective_us"]
                             if shard_stats else 0),
        shard_rows=shard_stats["rows"] if shard_stats else 0,
        shard_step_detail=(dict(shard_stats["step_detail"])
                           if shard_stats else {}),
        pages=dict(pages_summary) if pages_summary else {},
        compile_signatures=compile_stats,
        warmup_s=warmup_stats,
        handoff_edges=handoff_stats["edges"] if handoff_stats else 0,
        handoff_d2d_edges=(handoff_stats["d2d_edges"]
                           if handoff_stats else 0),
        handoff_host_edges=(handoff_stats["host_edges"]
                            if handoff_stats else 0),
        handoff_d2d_bytes=(handoff_stats["d2d_bytes"]
                           if handoff_stats else 0),
        handoff_host_bytes=(handoff_stats["host_bytes"]
                            if handoff_stats else 0),
        handoff_edge_detail=(dict(handoff_stats["edge_detail"])
                             if handoff_stats else {}),
        placement=placement_report or {},
        health_lanes=health_stats["lanes"] if health_stats else 0,
        health_transitions=(health_stats["transitions"]
                            if health_stats else 0),
        health_opens=health_stats["opens"] if health_stats else 0,
        health_evictions=(health_stats["evictions"]
                          if health_stats else 0),
        health_probes=health_stats["probes"] if health_stats else 0,
        health_redispatches=(health_stats["redispatches"]
                             if health_stats else 0),
        health_routes_after_open=(health_stats["routes_after_open"]
                                  if health_stats else 0),
        health_lane_detail=(dict(health_stats["lane_detail"])
                            if health_stats else {}),
        deadline_budget_ms=(int(round(deadline_settings.budget_ms))
                            if deadline_settings is not None else 0),
        deadline_expired=(deadline_snap["expired"]
                          if deadline_snap else 0),
        deadline_sites=(dict(deadline_snap["sites"])
                        if deadline_snap else {}),
        hedges_fired=hedge_stats["fired"] if hedge_stats else 0,
        hedges_won=hedge_stats["won"] if hedge_stats else 0,
        hedges_lost=hedge_stats["lost"] if hedge_stats else 0,
        hedges_wasted_ms=(hedge_stats["wasted_ms"]
                          if hedge_stats else 0),
        metrics_snapshots=(metrics_summary["snapshots"]
                           if metrics_summary else 0),
        metrics_series=(metrics_summary["series"]
                        if metrics_summary else 0),
        metrics_dumps=(metrics_summary["dumps"]
                       if metrics_summary else 0),
        metrics_triggers=(metrics_summary["triggers"]
                          if metrics_summary else 0),
        slo_tracked=(metrics_summary["slo_tracked"]
                     if metrics_summary else 0),
        slo_within=(metrics_summary["slo_within"]
                    if metrics_summary else 0),
        slo_missed=(metrics_summary["slo_missed"]
                    if metrics_summary else 0),
        slo_burn_max_milli=(metrics_summary["burn_max_milli"]
                            if metrics_summary else 0),
        compute_stages=(compute_summary["stages"]
                        if compute_summary else 0),
        compute_dispatches=(compute_summary["dispatches"]
                            if compute_summary else 0),
        compute_rows=compute_summary["rows"] if compute_summary else 0,
        compute_flops_total=(compute_summary["flops_total"]
                             if compute_summary else 0),
        compute_window_us=(compute_summary["window_us"]
                           if compute_summary else 0),
        compute_tflops_milli=(compute_summary["tflops_milli"]
                              if compute_summary else 0),
        compute_mfu_e4=(compute_summary["mfu_e4"]
                        if compute_summary else 0),
        compute_captures=(compute_summary["captures"]
                          if compute_summary else 0),
        compute_stage_detail=(dict(compute_summary["stage_detail"])
                              if compute_summary else {}),
        memory_owners=(len(memory_summary["owners"])
                       if memory_summary else 0),
        memory_devices=(len(memory_summary["devices"])
                        if memory_summary else 0),
        memory_total_bytes=(memory_summary["total_bytes"]
                            if memory_summary else 0),
        memory_peak_bytes=(memory_summary["peak_bytes"]
                           if memory_summary else 0),
        memory_watermark_bytes=(memory_summary["watermark_bytes"]
                                if memory_summary else 0),
        memory_watermark_hits=(memory_summary["watermark_hits"]
                               if memory_summary else 0),
        memory_live_bytes=(memory_summary["live_bytes"]
                           if memory_summary else 0),
        memory_reconciled=(memory_summary["reconciled"]
                           if memory_summary else 0),
        memory_owner_detail=(dict(memory_summary["owners"])
                             if memory_summary else {}),
        critpath_requests=(critpath_report["requests"]
                           if critpath_report else 0),
        critpath_segments=(critpath_report["segments"]
                           if critpath_report else 0),
        critpath_residual_us_max=(critpath_report["residual_us_max"]
                                  if critpath_report else 0),
        critpath_hedged=(critpath_report["hedged"]
                         if critpath_report else 0),
        critpath_redispatched=(critpath_report["redispatched"]
                               if critpath_report else 0),
        critpath_bound_step=(critpath_report["bound_step"]
                             if critpath_report else 0),
        critpath_bound_vps_milli=(critpath_report["bound_vps_milli"]
                                  if critpath_report else 0),
        critpath_stage_detail=(dict(critpath_report["stage_detail"])
                               if critpath_report else {}),
        whatif_stages=(whatif_counters["stages"]
                       if whatif_counters else 0),
        whatif_calibrated=(whatif_counters["calibrated"]
                           if whatif_counters else 0),
        whatif_pred_vps_milli=(whatif_counters["pred_vps_milli"]
                               if whatif_counters else 0),
        whatif_bottleneck_step=(whatif_counters["bottleneck_step"]
                                if whatif_counters else 0),
        operator_scrapes=(operator_summary["scrapes"]
                          if operator_summary else 0),
        operator_actions=(operator_summary["actions"]
                          if operator_summary else 0),
        operator_denied=(operator_summary["denied"]
                         if operator_summary else 0),
        operator_errors=(operator_summary["errors"]
                         if operator_summary else 0),
        stacks_samples=(stacks_summary["samples"]
                        if stacks_summary else 0),
        stacks_threads=(stacks_summary["threads"]
                        if stacks_summary else 0),
        stacks_folded=(stacks_summary["folded"]
                       if stacks_summary else 0),
        stacks_total=(stacks_summary["total"]
                      if stacks_summary else 0),
        net_frames_sent=(net_snap["frames_sent"] if net_snap else 0),
        net_frames_acked=(net_snap["frames_acked"] if net_snap else 0),
        net_resent_pending=(net_snap["resent_pending"]
                            if net_snap else 0),
        net_resends=(net_snap["resends"] if net_snap else 0),
        net_beats=(net_snap["beats"] if net_snap else 0),
        net_reconnects=(net_snap["reconnects"] if net_snap else 0),
        net_remote=(net_snap["remote"] if net_snap else 0),
        net_local=(net_snap["local"] if net_snap else 0),
        net_dedup_drops=(net_snap["dedup_drops"] if net_snap else 0),
        net_dup_arrivals=(net_snap["dup_arrivals"] if net_snap else 0),
        net_wire_bytes=(net_snap["wire_bytes"] if net_snap else 0),
        net_frame_bytes=(net_snap["frame_bytes"] if net_snap else 0),
        net_window_stranded=(net_snap["window_stranded"]
                             if net_snap else 0),
        net_open_before_timeout=(net_snap["open_before_timeout"]
                                 if net_snap else 0),
        locks_tracked=(lock_snap["locks"] if lock_snap else 0),
        locks_acquires=(lock_snap["acquires"] if lock_snap else 0),
        locks_edges=(len(lock_snap["edges"]) if lock_snap else 0),
        locks_violations=(len(lock_snap["violations"])
                          if lock_snap else 0),
        net_err_total=(net_snap["err_total"] if net_snap else 0),
        net_err_refused=(net_snap["err_refused"] if net_snap else 0),
        net_err_reset=(net_snap["err_reset"] if net_snap else 0),
        net_err_timeout=(net_snap["err_timeout"] if net_snap else 0),
        net_err_partial_frame=(net_snap["err_partial_frame"]
                               if net_snap else 0),
        net_err_corrupt=(net_snap["err_corrupt"] if net_snap else 0),
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="TPU-native streaming video-analytics benchmark")
    parser.add_argument("-mi", "--mean_interval_ms",
                        help="Mean request interval (Poisson), ms; "
                             "0 = bulk max-throughput mode",
                        type=nonnegative_int, default=3)
    parser.add_argument("-b", "--batch_size",
                        help="Video batch size per replica",
                        type=positive_int, default=1)
    parser.add_argument("-v", "--videos",
                        help="Total number of videos to run",
                        type=positive_int, default=2000)
    parser.add_argument("-qs", "--queue_size",
                        help="Max size of inter-stage queues",
                        type=positive_int, default=50000)
    parser.add_argument("-c", "--config_file_path",
                        help="Pipeline configuration JSON",
                        type=str, default="configs/r2p1d-whole.json")
    parser.add_argument("--check", action="store_true",
                        help="Quick import smoke test, then exit")
    parser.add_argument("--platform", choices=["auto", "cpu"],
                        default="auto",
                        help="'cpu' forces the (virtual) CPU backend")
    parser.add_argument("--log-base", type=str, default="logs")
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--xprof", action="store_true",
                        help="Capture device-op timelines for the "
                             "measured window into <logdir>/xprof-ops.txt")
    args = parser.parse_args(argv)

    if args.platform == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    if args.check:
        import jax  # noqa: F401
        import flax  # noqa: F401
        from rnb_tpu import control, runner, client  # noqa: F401
        from rnb_tpu.models.r2p1d import model  # noqa: F401
        # validate the named config against the full (extended) schema
        # and surface its robustness posture — the knobs an operator
        # needs to know before pointing traffic at the pipeline
        from rnb_tpu.config import load_config
        from rnb_tpu.faults import FaultPlan
        cfg = load_config(args.config_file_path)
        retries = ", ".join(
            "step%d: %d@%gms" % (i, s.max_retries, s.retry_backoff_ms)
            for i, s in enumerate(cfg.steps) if s.max_retries) or "none"
        print("config %s: %d step(s), overload_policy=%s, "
              "fault_containment=%s, retries: %s"
              % (args.config_file_path, cfg.num_steps,
                 cfg.overload_policy, cfg.fault_containment, retries))
        plan = FaultPlan.resolve(cfg.fault_plan)
        if plan is not None:
            plan.check_steps(cfg.num_steps)
        print("fault plan: %s"
              % (plan.describe() if plan is not None else "none"))
        caches = ", ".join(
            "step%d: %g MB" % (i, s.extras["cache_mb"])
            for i, s in enumerate(cfg.steps)
            if s.extras.get("cache_mb")) or "none"
        print("clip cache: %s; popularity: %s"
              % (caches, json.dumps(cfg.popularity, sort_keys=True)
                 if cfg.popularity else "none"))
        opted_out = [i for i, s in enumerate(cfg.steps)
                     if not s.autotune]
        print("autotune: %s%s"
              % (json.dumps(cfg.autotune, sort_keys=True)
                 if cfg.autotune else "none",
                 "; opted-out steps: %s" % opted_out
                 if opted_out else ""))
        print("ragged: %s"
              % (json.dumps(cfg.ragged, sort_keys=True)
                 if cfg.ragged else "none"))
        print("handoff: %s"
              % (json.dumps(cfg.handoff, sort_keys=True)
                 if cfg.handoff else "none"))
        replicated = {"step%d" % i: len(s.replica_queues)
                      for i, s in enumerate(cfg.steps)
                      if s.replica_queues}
        print("placement: %s%s"
              % (json.dumps(cfg.placement, sort_keys=True)
                 if cfg.placement else "none",
                 "; replica lanes: %s" % json.dumps(replicated,
                                                    sort_keys=True)
                 if replicated else ""))
        print("trace: %s"
              % (json.dumps(cfg.trace, sort_keys=True)
                 if cfg.trace else "none"))
        print("metrics: %s"
              % (json.dumps(cfg.metrics, sort_keys=True)
                 if cfg.metrics else "none"))
        print("devobs: %s"
              % (json.dumps(cfg.devobs, sort_keys=True)
                 if cfg.devobs else "none"))
        print("critpath: %s; whatif: %s"
              % (json.dumps(cfg.critpath, sort_keys=True)
                 if cfg.critpath else "none",
                 json.dumps(cfg.whatif, sort_keys=True)
                 if cfg.whatif else "none"))
        print("operator: %s"
              % (json.dumps(cfg.operator, sort_keys=True)
                 if cfg.operator else "none"))
        hedged = {"step%d" % i: s.hedge_ms
                  for i, s in enumerate(cfg.steps)
                  if s.hedge_ms is not None}
        print("health: %s; deadline: %s; hedging: %s"
              % (json.dumps(cfg.health, sort_keys=True)
                 if cfg.health else "none",
                 json.dumps(cfg.deadline, sort_keys=True)
                 if cfg.deadline else "none",
                 json.dumps(hedged, sort_keys=True)
                 if hedged else "none"))
        print("rnb_tpu is ready to go!")
        return 0

    print("Args:", args)
    result = run_benchmark(
        config_path=args.config_file_path,
        mean_interval_ms=args.mean_interval_ms,
        batch_size=args.batch_size,
        num_videos=args.videos,
        queue_size=args.queue_size,
        log_base=args.log_base,
        seed=args.seed,
        xprof=args.xprof,
    )
    print("Throughput: %.3f videos/s" % result.throughput_vps)
    print("Logs: %s" % result.log_dir)
    return 0 if result.termination_flag == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
