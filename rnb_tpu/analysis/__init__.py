"""rnb-lint: static pipeline/config/telemetry analysis.

Three analyzer families, all runnable with no JAX device and no
dataset (``scripts/rnb_lint.py`` is the CLI; a tier-1 pytest runs them
over the repo and every shipped config):

* :mod:`rnb_tpu.analysis.graph` — pipeline graph checker: resolves
  every stage class named by a config and propagates declared
  PaddedBatch max-shape/dtype/row-bucket metadata step-to-step,
  rejecting shape-incompatible wiring, selector-arity violations,
  unconsumed config keys and invalid cache settings before any device
  is touched.
* :mod:`rnb_tpu.analysis.hotpath` — AST lint over the package: flags
  host-sync calls inside jitted regions, imports/``device_put`` on
  per-request paths, nondeterminism in fault-injection code, and
  ring-slot writes that precede the shed decision.
* :mod:`rnb_tpu.analysis.schema` — telemetry schema checker: extracts
  every TimeCard stamp, log-meta line, table trailer and
  BenchmarkResult counter written anywhere in the tree and
  cross-checks them against the declared registries in
  :mod:`rnb_tpu.telemetry` and against what
  ``scripts/parse_utils.py`` parses.

Findings carry ``file:line``, a rule id and a stable anchor;
intentional exceptions live in the checked-in ``rnb-lint-baseline.txt``
with a one-line justification (:mod:`rnb_tpu.analysis.findings`).
"""

from rnb_tpu.analysis.findings import (Baseline, Finding,  # noqa: F401
                                       apply_baseline, format_findings)
