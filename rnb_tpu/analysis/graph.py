"""Static pipeline graph checker: reject broken configs pre-launch.

Loads any pipeline config (shipped or user), resolves every class it
names, and propagates the stages' *declared* PaddedBatch metadata —
max shapes (``output_shape_for`` / ``input_shape_for``), dtypes
(``output_dtype_for`` / ``input_dtype_for``) and row-bucket sets —
step-to-step along the queue wiring, with no JAX device, no dataset
and no stage construction. The compile-before-run discipline of
full-program TPU compilation, applied to pipeline wiring: a config
that would abort (or silently recompile) ten minutes into a TPU run
is rejected in milliseconds instead.

Rules
-----
* ``RNB-G001`` config-parse: the config fails schema validation
  (rnb_tpu.config) — covers queue wiring, fault-plan step ranges,
  popularity keys, segment/ring arithmetic.
* ``RNB-G002`` unresolvable-class: a ``model`` /
  ``video_path_iterator`` / ``queue_selector`` class path does not
  import or the module lacks the class.
* ``RNB-G003`` shape-mismatch: a producer group's declared (and
  segment-shrunk) output shapes cannot feed a wired consumer group's
  declared input shapes (tensor count, trailing dims, or a row axis
  exceeding the consumer's capacity).
* ``RNB-G004`` selector-arity: a group's queue selector rejects its
  out-queue count (e.g. LargeSmallSelector on != 2 queues).
* ``RNB-G005`` unconsumed-config-key: a step/group extra key is not a
  named constructor parameter of the stage class (its MRO union plus
  declared ``FORWARDS_CONFIG_TO`` targets) — the open kwargs
  passthrough would silently swallow the typo.
* ``RNB-G006`` bucket-mismatch: the row-count set a producer group can
  emit is not covered by the consumer's warmed bucket set — every
  uncovered bucket is a silent XLA recompile inside the measured
  window. Consumers with ``REPACKS_ROWS`` (Batcher) accept any
  upstream buckets and are skipped. Also covers the ``autotune`` root
  key: an ``autotune.buckets`` restriction naming a row bucket some
  participating stage (``SUPPORTS_AUTOTUNE``, not opted out via the
  step's ``"autotune": false``) never warms — the controller refuses
  it at launch precisely because a chosen un-warmed bucket would be a
  mid-run recompile, and this rule rejects it statically.
* ``RNB-G007`` invalid-cache-mb: a ``cache_mb`` value the stage would
  reject at construction (non-numeric or negative; 0 disables).
* ``RNB-G008`` dtype-mismatch: producer output dtype and consumer
  input dtype are both declared and differ (e.g. a yuv420 loader wired
  into an rgb network stage).
* ``RNB-G009`` ragged-pool-mismatch: the root ``ragged`` key's
  ``pool_rows`` does not equal a participating stage's declared max
  row axis — the pool is the stage's ONE compiled shape, so a
  different capacity would silently change every declared wire shape
  and warmup signature (the stage constructor rejects it at launch;
  this rule rejects it statically).
* ``RNB-G010`` shard-spec: a step's ``shard`` key is unusable as
  declared — the model class declares no partition spec
  (``SUPPORTS_SHARD``), the degree does not divide every declared
  output-channel width of the stage's layer range
  (rnb_tpu.parallel.shardplan.validate_degree — a non-dividing degree
  cannot slice the weights), or the expanded shard rings oversubscribe
  the step's mesh (a device appearing twice in one ring, or shared
  between two replica lanes' rings). The stage constructor rejects the
  first two at launch; this rule rejects all three statically.

Ragged interplay: with the root ``ragged`` key enabled, participating
stages ship exactly one shape (the pool) with a traced ``rows_valid``
scalar, so the RNB-G006 bucket-coverage check and the
``autotune.buckets`` warmed-subset check relax — any row count up to
the pool capacity is dispatchable without a recompile, and configured
``row_buckets`` are only the counterfactual pad rule the
``pad_rows_eliminated`` counter is measured against.
"""

from __future__ import annotations

import functools
import inspect
import os
from typing import Any, Dict, List, Optional

from rnb_tpu.analysis.findings import Finding
from rnb_tpu.config import ConfigError, load_config
from rnb_tpu.control import get_segmented_shapes
from rnb_tpu.stage import normalize_row_buckets
from rnb_tpu.utils.class_utils import load_class


def _rel(path: str, root: str) -> str:
    """Repo-relative finding path — the stable half of the baseline
    key; paths outside ``root`` stay absolute rather than dotted."""
    rel = os.path.relpath(path, root)
    return path if rel.startswith("..") else rel.replace(os.sep, "/")


def _resolve(class_path: str, rel: str, anchor: str,
             findings: List[Finding]):
    """load_class with an RNB-G002 finding instead of an exception."""
    try:
        return load_class(class_path)
    except Exception as e:
        findings.append(Finding(
            "RNB-G002", rel, 0, anchor,
            "cannot resolve class %r: %s" % (class_path, e)))
        return None


@functools.lru_cache(maxsize=None)
def consumed_config_keys(cls) -> frozenset:
    """Named constructor parameters a stage class actually consumes:
    the union over its MRO plus any classes it declares forwarding its
    open kwargs to (``FORWARDS_CONFIG_TO``)."""
    keys: set = set()
    stack = [cls]
    seen = set()
    while stack:
        c = stack.pop()
        if c in seen:
            continue
        seen.add(c)
        for base in getattr(c, "__mro__", ()):
            init = base.__dict__.get("__init__")
            if init is None:
                continue
            try:
                sig = inspect.signature(init)
            except (TypeError, ValueError):
                continue
            for name, param in sig.parameters.items():
                if param.kind in (param.POSITIONAL_OR_KEYWORD,
                                  param.KEYWORD_ONLY):
                    keys.add(name)
        stack.extend(getattr(c, "FORWARDS_CONFIG_TO", ()))
    keys.discard("self")
    keys.discard("device")
    return frozenset(keys)


def _declared(cls, method: str, kwargs: Dict[str, Any], rel: str,
              anchor: str, findings: List[Finding],
              sentinel=None):
    """Call a static declaration classmethod, turning an exception
    (the stage statically rejects these kwargs) into a finding under
    the rule family the declaration belongs to."""
    try:
        return getattr(cls, method)(**kwargs)
    except Exception as e:
        rule = "RNB-G008" if "dtype" in method else "RNB-G003"
        findings.append(Finding(
            rule, rel, 0, anchor,
            "%s.%s rejects the configured kwargs: %s"
            % (cls.__name__, method, e)))
        return sentinel


def _emission_rows(shapes, row_buckets, rel: str, anchor: str,
                   findings: List[Finding]) -> Optional[set]:
    """The set of row counts (axis 0 of tensor 0) a group can emit."""
    max_rows = int(shapes[0][0])
    if not row_buckets:
        return {max_rows}
    try:
        return set(normalize_row_buckets(row_buckets, max_rows,
                                         "declared max rows"))
    except Exception as e:
        findings.append(Finding("RNB-G006", rel, 0, anchor,
                                "invalid row_buckets: %s" % e))
        return None


def check_config(path: str, root: str = ".") -> List[Finding]:
    """All graph findings for one config file."""
    rel = _rel(path, root)
    try:
        config = load_config(path)
    except ConfigError as e:
        return [Finding("RNB-G001", rel, 0, "parse", str(e))]
    findings: List[Finding] = []
    _resolve(config.video_path_iterator, rel, "video_path_iterator",
             findings)

    classes = []
    for step_idx, step in enumerate(config.steps):
        classes.append(_resolve(step.model, rel, "step%d" % step_idx,
                                findings))

    # per-group local checks: selector arity, unconsumed keys, cache_mb
    for step_idx, (step, cls) in enumerate(zip(config.steps, classes)):
        for group_idx, group in enumerate(step.groups):
            anchor = "step%d.group%d" % (step_idx, group_idx)
            kwargs = step.kwargs_for_group(group_idx)

            if group.out_queues:
                sel_cls = _resolve(group.queue_selector, rel, anchor,
                                   findings)
                if sel_cls is not None:
                    try:
                        sel_cls(len(group.out_queues))
                    except Exception as e:
                        findings.append(Finding(
                            "RNB-G004", rel, 0, anchor,
                            "queue selector %s rejects %d out-queue(s): "
                            "%s" % (group.queue_selector,
                                    len(group.out_queues), e)))

            if "cache_mb" in kwargs:
                cache_mb = kwargs["cache_mb"]
                if (not isinstance(cache_mb, (int, float))
                        or isinstance(cache_mb, bool) or cache_mb < 0):
                    findings.append(Finding(
                        "RNB-G007", rel, 0, anchor,
                        "'cache_mb' must be a non-negative number "
                        "(0 disables caching), got %r" % (cache_mb,)))

            if cls is not None:
                # shard_* keys are parse-time wiring from the step's
                # 'shard' object, not user config — a class that can't
                # consume them is RNB-G010's finding, not a typo
                unknown = sorted(
                    k for k in kwargs
                    if k not in consumed_config_keys(cls)
                    and not k.startswith("_")
                    and k not in ("shard_devices", "shard_degree",
                                  "shard_axis", "shard_hbm_budget_mb"))
                for key in unknown:
                    findings.append(Finding(
                        "RNB-G005", rel, 0, "%s.%s" % (anchor, key),
                        "config key %r is not a constructor parameter "
                        "of %s — the open kwargs passthrough would "
                        "silently drop it" % (key, cls.__name__)))

    # ragged row-pool dispatch (root 'ragged' key,
    # rnb_tpu.ops.ragged): an explicit pool_rows must equal every
    # participating stage's declared max row axis — the same invariant
    # resolve_pool_rows enforces at construction, checked statically
    ragged_cfg = config.ragged
    ragged_on = ragged_cfg is not None and ragged_cfg.get("enabled",
                                                          True)
    if ragged_on and ragged_cfg.get("pool_rows") is not None:
        pool_rows = int(ragged_cfg["pool_rows"])
        for step_idx, (step, cls) in enumerate(zip(config.steps,
                                                   classes)):
            if cls is None or not getattr(cls, "SUPPORTS_RAGGED",
                                          False):
                continue
            for group_idx, group in enumerate(step.groups):
                anchor = "step%d.group%d.ragged" % (step_idx,
                                                    group_idx)
                kwargs = step.kwargs_for_group(group_idx)
                shapes = _declared(cls, "output_shape_for", kwargs,
                                   rel, anchor, findings)
                if shapes is None:
                    # final-style stages declare via input_shape_for
                    shapes = _declared(cls, "input_shape_for", kwargs,
                                       rel, anchor, findings)
                if not shapes:
                    continue
                declared_max = int(tuple(shapes[0])[0])
                if pool_rows != declared_max:
                    findings.append(Finding(
                        "RNB-G009", rel, 0, anchor,
                        "'ragged.pool_rows'=%d does not match %s's "
                        "declared max row axis %d — the pool is the "
                        "stage's one compiled shape, so its capacity "
                        "must equal the declared max"
                        % (pool_rows, cls.__name__, declared_max)))

    # intra-stage sharding (step 'shard' key,
    # rnb_tpu.parallel.shardplan): the declared degree must have a
    # partition spec to act on (SUPPORTS_SHARD), must divide every
    # declared output-channel width of the stage's layer range, and
    # the expanded rings must not oversubscribe the step's mesh —
    # the constructor-time gates, checked statically
    for step_idx, (step, cls) in enumerate(zip(config.steps, classes)):
        seen_ring_devices: set = set()
        for group_idx, group in enumerate(step.groups):
            kwargs = step.kwargs_for_group(group_idx)
            degree = kwargs.get("shard_degree")
            if degree is None:
                continue
            anchor = "step%d.group%d.shard" % (step_idx, group_idx)
            if cls is not None and not getattr(cls, "SUPPORTS_SHARD",
                                               False):
                findings.append(Finding(
                    "RNB-G010", rel, 0, anchor,
                    "'shard' on a %s step, but the class declares no "
                    "partition spec (SUPPORTS_SHARD) — no parameter "
                    "axis is declared shardable, so the degree has "
                    "nothing to slice" % cls.__name__))
                continue
            if cls is not None:
                from rnb_tpu.parallel.shardplan import validate_degree
                try:
                    sig = inspect.signature(cls.__init__)
                except (TypeError, ValueError):
                    sig = None

                def _resolved_kwarg(name, fallback):
                    if name in kwargs:
                        return kwargs[name]
                    if sig is not None:
                        param = sig.parameters.get(name)
                        if param is not None and param.default \
                                is not inspect.Parameter.empty:
                            return param.default
                    return fallback
                try:
                    validate_degree(
                        int(degree),
                        int(_resolved_kwarg("start_index", 1)),
                        int(_resolved_kwarg("end_index", 5)),
                        int(_resolved_kwarg("num_classes", 400)))
                except ValueError as e:
                    findings.append(Finding(
                        "RNB-G010", rel, 0, anchor, str(e)))
            ring = list(kwargs.get("shard_devices") or [])
            if len(set(ring)) != len(ring):
                findings.append(Finding(
                    "RNB-G010", rel, 0, anchor,
                    "shard ring %s lists a device more than once — a "
                    "degree-%s ring needs that many DISTINCT devices"
                    % (ring, degree)))
            overlap = sorted(set(ring) & seen_ring_devices)
            if overlap:
                findings.append(Finding(
                    "RNB-G010", rel, 0, anchor,
                    "shard ring %s shares device(s) %s with another "
                    "replica lane of the same step — lanes' rings "
                    "oversubscribe the step's mesh"
                    % (ring, overlap)))
            seen_ring_devices.update(ring)

    # load-adaptive batching (root 'autotune' key, rnb_tpu.autotune):
    # an autotune.buckets restriction must stay inside each
    # participating stage's warmed bucket set — the same invariant
    # BatchController.for_stage enforces at launch, checked statically.
    # Under ragged dispatch the warmed set is continuous (1..pool), so
    # any restriction within the declared max passes.
    autotune = config.autotune
    if autotune is not None and autotune.get("enabled", True) \
            and autotune.get("buckets"):
        restricted = set(int(b) for b in autotune["buckets"])
        for step_idx, (step, cls) in enumerate(zip(config.steps,
                                                   classes)):
            if cls is None or not step.autotune \
                    or not getattr(cls, "SUPPORTS_AUTOTUNE", False):
                continue
            for group_idx, group in enumerate(step.groups):
                anchor = "step%d.group%d.autotune" % (step_idx,
                                                      group_idx)
                kwargs = step.kwargs_for_group(group_idx)
                shapes = _declared(cls, "output_shape_for", kwargs,
                                   rel, anchor, findings)
                if not shapes:
                    continue
                if ragged_on and getattr(cls, "SUPPORTS_RAGGED",
                                         False):
                    # ragged stage: one compiled pool shape serves
                    # every row count up to its capacity, so the
                    # controller's candidate set is continuous
                    warmed = set(range(
                        1, int(tuple(shapes[0])[0]) + 1))
                else:
                    warmed = _emission_rows(
                        tuple(map(tuple, shapes)),
                        kwargs.get("row_buckets"), rel, anchor,
                        findings)
                if warmed is None:
                    continue
                missing = sorted(restricted - warmed)
                if missing:
                    findings.append(Finding(
                        "RNB-G006", rel, 0, anchor,
                        "'autotune.buckets' %s name row bucket(s) %s "
                        "that %s never warms (warmed: %s) — an "
                        "autotune decision for one would be a silent "
                        "mid-run recompile, so the controller rejects "
                        "this config at launch"
                        % (sorted(restricted), missing, cls.__name__,
                           sorted(warmed))))

    # step-to-step metadata propagation along the queue wiring
    for step_idx in range(1, config.num_steps):
        p_step, c_step = config.steps[step_idx - 1], config.steps[step_idx]
        p_cls, c_cls = classes[step_idx - 1], classes[step_idx]
        if p_cls is None or c_cls is None:
            continue
        for cg_idx, cgroup in enumerate(c_step.groups):
            ckwargs = c_step.kwargs_for_group(cg_idx)
            c_anchor = "step%d.group%d" % (step_idx, cg_idx)
            cin = _declared(c_cls, "input_shape_for", ckwargs, rel,
                            c_anchor, findings)
            cdtype = _declared(c_cls, "input_dtype_for", ckwargs, rel,
                               c_anchor, findings)
            for pg_idx, pgroup in enumerate(p_step.groups):
                if cgroup.in_queue not in pgroup.out_queues:
                    continue
                pkwargs = p_step.kwargs_for_group(pg_idx)
                edge = "step%d.group%d->step%d.group%d" % (
                    step_idx - 1, pg_idx, step_idx, cg_idx)
                pout = _declared(p_cls, "output_shape_for", pkwargs, rel,
                                 edge, findings)
                pdtype = _declared(p_cls, "output_dtype_for", pkwargs,
                                   rel, edge, findings)
                _check_edge(rel, edge, p_cls, c_cls, pkwargs, ckwargs,
                            p_step.num_segments, pout, pdtype,
                            cin, cdtype, findings, ragged_on)
    return findings


def _check_edge(rel: str, edge: str, p_cls, c_cls,
                pkwargs: Dict[str, Any], ckwargs: Dict[str, Any],
                num_segments: int,
                pout, pdtype, cin, cdtype,
                findings: List[Finding],
                ragged_on: bool = False) -> None:
    """Shape/dtype/bucket compatibility of one wired producer-group ->
    consumer-group edge."""
    if cin is None:
        return  # consumer declares no tensor expectations
    if pout is None:
        findings.append(Finding(
            "RNB-G003", rel, 0, edge,
            "%s declares no tensor outputs but %s expects input "
            "shapes %r" % (p_cls.__name__, c_cls.__name__, cin)))
        return
    pout = tuple(map(tuple, pout))
    cin = tuple(map(tuple, cin))
    try:
        seg_out = get_segmented_shapes(pout, num_segments)
    except ValueError as e:
        findings.append(Finding("RNB-G003", rel, 0, edge, str(e)))
        return
    if len(seg_out) != len(cin):
        findings.append(Finding(
            "RNB-G003", rel, 0, edge,
            "%s emits %d tensor(s) %r but %s expects %d %r"
            % (p_cls.__name__, len(seg_out), seg_out, c_cls.__name__,
               len(cin), cin)))
        return
    for idx, (got, want) in enumerate(zip(seg_out, cin)):
        if (len(got) != len(want) or tuple(got[1:]) != tuple(want[1:])
                or got[0] > want[0]):
            findings.append(Finding(
                "RNB-G003", rel, 0, edge,
                "output %d declares %r but the consumer expects %r "
                "(row axis may be smaller, never larger; trailing "
                "dims must match exactly)" % (idx, got, want)))
    if pdtype is not None and cdtype is not None and pdtype != cdtype:
        findings.append(Finding(
            "RNB-G008", rel, 0, edge,
            "%s emits dtype %s but %s expects %s"
            % (p_cls.__name__, pdtype, c_cls.__name__, cdtype)))

    # row-bucket coverage: every row count the producer can emit must
    # be a shape the consumer warmed/compiled, or the first occurrence
    # is a silent recompile inside the measured window
    if getattr(c_cls, "REPACKS_ROWS", False):
        return
    if ragged_on and getattr(p_cls, "SUPPORTS_RAGGED", False):
        # ragged producer: every emission ships the ONE pool shape
        # (its declared max); any configured row_buckets are the
        # counterfactual pad rule, never shipped shapes
        emission = {int(seg_out[0][0])}
    else:
        emission = _emission_rows(seg_out, pkwargs.get("row_buckets")
                                  if num_segments <= 1 else None,
                                  rel, edge, findings)
    if emission is None:
        return
    # the consumer's warmed set: its configured row_buckets when the
    # class consumes them, else the single declared input max. A
    # RAGGED consumer warms exactly its pool (the declared max) —
    # any configured row_buckets are only the counterfactual pad
    # rule — so a producer pool smaller than the consumer's is a
    # mid-run recompile this check must catch (e.g. loader
    # max_clips=15 feeding a runner max_rows=30 under an omitted
    # ragged.pool_rows: both resolve their own declared max)
    c_max = int(cin[0][0])
    warmed = {c_max}
    if not (ragged_on and getattr(c_cls, "SUPPORTS_RAGGED", False)) \
            and ("row_buckets" in consumed_config_keys(c_cls)
                 and ckwargs.get("row_buckets")):
        try:
            warmed = set(normalize_row_buckets(
                ckwargs["row_buckets"], c_max, "declared input max"))
        except Exception as e:
            findings.append(Finding("RNB-G006", rel, 0, edge,
                                    "invalid consumer row_buckets: %s"
                                    % e))
            return
    uncovered = sorted(emission - warmed)
    if uncovered:
        findings.append(Finding(
            "RNB-G006", rel, 0, edge,
            "producer can emit row counts %s the consumer never "
            "warmed (warmed: %s) — each is a silent recompile in the "
            "measured window; align 'row_buckets'/'max_rows' across "
            "the edge" % (uncovered, sorted(warmed))))


def check_configs(paths: List[str], root: str = ".") -> List[Finding]:
    findings: List[Finding] = []
    for path in paths:
        findings.extend(check_config(path, root))
    return findings
