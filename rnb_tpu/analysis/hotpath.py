"""Hot-path AST lint: host-sync and import hygiene, statically.

The runtime's throughput ceiling is the single host core (RESULTS.md),
so the per-request code paths have hard rules the tree learned the
expensive way — PR 2 measured per-emission ``import`` machinery and
``np.zeros`` staging as whole percentage points of the core. This
module encodes those rules over the AST so they hold by construction
instead of by review.

What counts as *hot*: the executor's thread entry
(``rnb_tpu/runner.py::runner``) and every stage-contract entry point —
``__call__``, ``submit``, ``complete``, ``poll``, ``select`` — plus
everything reachable from them through same-module ``self.method()`` /
bare-function calls (an intra-module call graph; cross-module calls
are out of scope and covered by linting the callee's own module).

Rules
-----
* ``RNB-H001`` jit-host-sync: a host-sync/host-data call
  (``np.asarray``, ``.block_until_ready()``, ``float()``/``int()``,
  ``.valid_data()``, ``time.time``, ``print``, ``device_put``) inside
  a function handed to ``jax.jit`` in the same module — under jit
  these either break tracing or silently force a device round-trip.
* ``RNB-H002`` hot-import: an ``import`` statement inside a hot
  function — per-request interpreter import machinery; hoist to the
  module top or use :mod:`rnb_tpu.utils.lazy_jax`.
* ``RNB-H003`` device-put-in-loop: ``device_put`` inside a ``for`` /
  ``while`` loop of a hot function — per-item transfers serialize on
  transfer latency; batch first, transfer once.
* ``RNB-H004`` fault-nondeterminism: wall-clock (``time.time``) or
  unseeded RNG (``random.*``, ``np.random.*``, ``datetime.now``) in
  deterministic fault-injection code (``rnb_tpu/faults.py`` and any
  ``*FaultPlan*`` class) — injection schedules must be reproducible.
* ``RNB-H005`` ring-write-before-shed: within one function, a write
  into an ``output_ring`` slot at a line preceding the shed decision
  (``_shed_item``) — a written-but-never-signalled slot deadlocks the
  producer on the next wrap-around.
* ``RNB-H006`` host-sync-in-hot-path: ``.block_until_ready()``,
  ``np.asarray``, ``.valid_data()``, or ``float()``/``int()`` over a
  ``jax``/``jnp`` expression in a hot function — a deliberate sync
  belongs in the baseline with its justification, everything else is
  a stall of the executor thread.
* ``RNB-H007`` bucket-alloc-per-emission: ``np.empty``/``np.zeros``
  of a bucket/batch shape (an argument referencing a
  ``_batch_shape``-style helper) in a hot function — a fresh
  bucket-shaped host allocation per request/emission is the staging
  anti-pattern PR 4 removed; decode into a ``rnb_tpu.staging``
  StagingPool slot instead, and baseline the copy fallback with its
  justification.
* ``RNB-H009`` unbounded-blocking-wait: a no-argument ``.get()`` /
  ``.wait()`` / ``.acquire()`` / ``.result()`` call without a
  ``timeout`` keyword in an executor/stage hot path (or any ``wait``
  method, the blocking leaves hot paths call through) — a consumer
  blocked forever on a dead producer's queue/event hangs the drain
  path past every containment mechanism. Bound the wait and re-check
  liveness (termination flag, pool failure, deadline) each lap, or
  baseline the site with the justification for why it cannot hang
  (e.g. a Barrier carrying a construction-time timeout).
* ``RNB-H010`` device-alloc-per-emission: a pool/bucket-shaped
  DEVICE allocation (``jnp.zeros``/``jnp.empty``/``jnp.ones`` of a
  stage-declared shape, or a ``device_put`` whose payload expression
  derives from one) in a hot function outside the page allocator —
  the device twin of RNB-H007. A fresh pool-shaped device array per
  emission fragments HBM and defeats the single-slab page allocator
  (rnb_tpu.pager) that exists to own exactly these bytes; allocate
  once at stage init (an arena, a preallocated zero pool) and reuse,
  or baseline a deliberate staged fallback with its justification.
  ``rnb_tpu/pager.py`` itself is exempt: its arena slab is the one
  legal pool-shaped device allocation.
* ``RNB-H008`` host-materialization-on-device-edge: a host
  materialization call (``device_get``, ``np.asarray``/``np.array``,
  ``.copy_to_host_async``, ``.tolist``) inside a device-resident
  handoff path — a ``*Handoff*`` class method (or a module-level
  function of a ``handoff*.py`` module) whose name does not mark it
  as the host-mode path with a ``host`` component. The device-
  resident edge contract (rnb_tpu.handoff) promises zero host-hop
  bytes; a host bounce creeping into its take path would silently
  void the contract while the ``Handoff:`` accounting kept claiming
  d2d. Route the call through a ``*host*``-named method (the
  explicit host-mode arm) or fix it.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from rnb_tpu.analysis.findings import (Finding, package_py_files,
                                       parse_py)

#: stage-contract entry points — hot by definition
HOT_ROOT_METHODS = {"__call__", "submit", "complete", "poll", "select"}

#: module-level functions that are hot loops, keyed by path suffix
EXTRA_HOT_ROOTS = {"rnb_tpu/runner.py": {"runner"}}

#: receivers recognized as the numpy module
_NP_NAMES = {"np", "numpy"}


def _qual(owner: Optional[str], name: str) -> str:
    return "%s.%s" % (owner, name) if owner else name


class _ModuleIndex(ast.NodeVisitor):
    """Collect defs, class structure and jitted-function names."""

    def __init__(self):
        self.functions: Dict[str, ast.AST] = {}   # qualname -> def node
        self.by_name: Dict[str, List[str]] = {}   # bare name -> qualnames
        self.class_bases: Dict[str, List[str]] = {}
        self.class_methods: Dict[str, Set[str]] = {}
        self.jit_names: Set[str] = set()
        self._class: Optional[str] = None
        self._stack: List[str] = []  # enclosing function names

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, self._class = self._class, node.name
        self.class_bases[node.name] = [
            b.id if isinstance(b, ast.Name) else
            b.attr if isinstance(b, ast.Attribute) else ""
            for b in node.bases]
        self.class_methods[node.name] = set()
        self.generic_visit(node)
        self._class = prev

    def _visit_def(self, node) -> None:
        qual = _qual(self._class,
                     ".".join(self._stack + [node.name]))
        if qual in self.functions:
            # same-name defs (e.g. per-branch closures): keep each
            # registered so every jitted variant gets linted. The
            # suffix is an occurrence ordinal — stable for baselining
            # (no line numbers, no '#' which baseline syntax reserves
            # for justifications)
            ordinal = 2
            while "%s~%d" % (qual, ordinal) in self.functions:
                ordinal += 1
            qual = "%s~%d" % (qual, ordinal)
        self.functions[qual] = node
        self.by_name.setdefault(node.name, []).append(qual)
        if self._class is not None and not self._stack:
            self.class_methods[self._class].add(node.name)
        for deco in node.decorator_list:
            if _is_jit(deco) or (isinstance(deco, ast.Call)
                                 and _is_jit(deco.func)):
                self.jit_names.add(node.name)
        # recurse: the real jit sites live INSIDE function bodies
        # (`fn = jax.jit(apply)` in a factory), and nested defs need
        # their own registration so by_name can resolve them
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def

    def visit_Call(self, node: ast.Call) -> None:
        if _is_jit(node.func) and node.args \
                and isinstance(node.args[0], ast.Name):
            self.jit_names.add(node.args[0].id)
        self.generic_visit(node)


def _is_jit(node) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "jit"
    if isinstance(node, ast.Attribute):
        return node.attr == "jit"
    return False


def _own_walk(node):
    """ast.walk over a function's OWN statements, not descending into
    nested function defs — nested defs are registered under their own
    qualname and linted there, so one call site yields one finding
    with one stable anchor (never a parent+closure duplicate)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        sub = stack.pop()
        yield sub
        if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(sub))


def _attr_chain_has(node, names: Set[str]) -> bool:
    """Does any Name/attr component of an expression match ``names``?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in names:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in names:
            return True
    return False


def _method_owner(index: _ModuleIndex, cls: str, method: str
                  ) -> Optional[str]:
    """Resolve ``self.method`` against a class and its in-module
    ancestors; -> owning class name or None."""
    seen = set()
    stack = [cls]
    while stack:
        c = stack.pop()
        if c in seen or c not in index.class_methods:
            continue
        seen.add(c)
        if method in index.class_methods[c]:
            return c
        stack.extend(index.class_bases.get(c, ()))
    return None


def _hot_set(index: _ModuleIndex, rel: str) -> Set[str]:
    """Qualnames reachable from the hot roots via the intra-module
    call graph."""
    roots: List[str] = []
    for cls, methods in index.class_methods.items():
        for m in methods & HOT_ROOT_METHODS:
            roots.append(_qual(cls, m))
    for suffix, names in EXTRA_HOT_ROOTS.items():
        if rel.endswith(suffix):
            roots.extend(n for n in names if n in index.functions)
    hot: Set[str] = set()
    stack = list(roots)
    while stack:
        qual = stack.pop()
        if qual in hot or qual not in index.functions:
            continue
        hot.add(qual)
        # closures of a hot function run on the same hot path; they
        # are linted under their own qualname (one finding per site)
        prefix = qual + "."
        stack.extend(q for q in index.functions
                     if q.startswith(prefix))
        cls = qual.rsplit(".", 1)[0] if "." in qual else None
        for node in ast.walk(index.functions[qual]):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self" and cls is not None):
                owner = _method_owner(index, cls, f.attr)
                if owner is not None:
                    stack.append(_qual(owner, f.attr))
            elif isinstance(f, ast.Name) and f.id in index.functions:
                stack.append(f.id)
    return hot


def _host_sync_kind(node: ast.Call) -> Optional[str]:
    """Classify one call as a host-sync pattern, or None."""
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "block_until_ready":
            return ".block_until_ready()"
        if f.attr == "valid_data":
            return ".valid_data()"
        if f.attr == "asarray" and isinstance(f.value, ast.Name) \
                and f.value.id in _NP_NAMES:
            return "np.asarray()"
    if isinstance(f, ast.Name) and f.id in ("float", "int") and node.args:
        if any(_attr_chain_has(a, {"jax", "jnp"}) for a in node.args):
            return "%s() on a device value" % f.id
    return None


#: attribute accesses that make an int()/float() argument static
#: metadata (legal under jit) rather than a traced value
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def _lint_jit_body(rel: str, qual: str, node, findings: List[Finding]
                   ) -> None:
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        kind = _host_sync_kind(sub)
        f = sub.func
        if kind is None and isinstance(f, ast.Name) \
                and f.id in ("float", "int", "print") and sub.args:
            # int(x.shape[0]) & friends are static shape arithmetic,
            # idiomatic and legal under jit — only traced values sync
            if f.id == "print" or not all(
                    _attr_chain_has(a, _STATIC_ATTRS)
                    for a in sub.args):
                kind = "%s()" % f.id
        if kind is None and isinstance(f, ast.Attribute) \
                and f.attr == "device_put":
            kind = "device_put()"
        if kind is None and isinstance(f, ast.Attribute) \
                and f.attr == "time" and isinstance(f.value, ast.Name) \
                and f.value.id == "time":
            kind = "time.time()"
        if kind is not None:
            findings.append(Finding(
                "RNB-H001", rel, sub.lineno, qual,
                "%s inside a jit-compiled function — breaks tracing or "
                "forces a device round-trip" % kind))


#: every looping construct a per-item device_put can hide in —
#: comprehensions are the idiomatic JAX spelling of the same bug
_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While, ast.ListComp,
               ast.SetComp, ast.DictComp, ast.GeneratorExp)

#: helper names whose result is a bucket/batch shape — an np.empty/
#: np.zeros over one of these on a hot path is a per-emission staging
#: allocation (RNB-H007)
_BATCH_SHAPE_HELPERS = {"_batch_shape", "batch_shape", "bucket_shape"}


def _bucket_alloc_kind(node: ast.Call) -> Optional[str]:
    """Classify one call as a bucket-shaped host allocation, or None."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in ("empty", "zeros") \
            and isinstance(f.value, ast.Name) \
            and f.value.id in _NP_NAMES and node.args:
        if _attr_chain_has(node.args[0], _BATCH_SHAPE_HELPERS):
            return "np.%s() of a bucket shape" % f.attr
    return None


#: receivers recognized as the jax.numpy module (RNB-H010)
_JNP_NAMES = {"jnp"}

#: the one module whose pool-shaped device allocation IS the design —
#: the page allocator's arena slab (rnb_tpu.pager); everything else
#: must draw from it or preallocate at stage init
_H010_EXEMPT_BASENAMES = {"pager.py"}


def _device_alloc_kind(node: ast.Call) -> Optional[str]:
    """Classify one call as a pool/bucket-shaped DEVICE allocation
    (RNB-H010), or None: a jnp zeros/empty/ones whose shape comes
    from a stage-declared shape helper, or a device_put whose payload
    expression derives from one (``device_put(np.zeros(
    self._batch_shape(n)))`` is the canonical spelling)."""
    f = node.func
    if isinstance(f, ast.Attribute) \
            and f.attr in ("empty", "zeros", "ones") \
            and isinstance(f.value, ast.Name) \
            and f.value.id in _JNP_NAMES and node.args:
        if _attr_chain_has(node.args[0], _BATCH_SHAPE_HELPERS):
            return "jnp.%s() of a stage-declared shape" % f.attr
    if isinstance(f, ast.Attribute) and f.attr == "device_put" \
            and node.args:
        if any(_attr_chain_has(a, _BATCH_SHAPE_HELPERS)
               for a in node.args):
            return "device_put() of a stage-declared shape"
    return None


def _lint_hot_body(rel: str, qual: str, node,
                   findings: List[Finding]) -> None:
    loop_spans: List[Tuple[int, int]] = []
    for sub in _own_walk(node):
        if isinstance(sub, _LOOP_NODES):
            loop_spans.append((sub.lineno,
                               max(getattr(sub, "end_lineno", sub.lineno),
                                   sub.lineno)))

    def in_loop(lineno: int) -> bool:
        # inclusive bounds: one-line `for ...: device_put(...)` bodies
        # and comprehension headers are still per-item transfers
        return any(lo <= lineno <= hi for lo, hi in loop_spans)

    for sub in _own_walk(node):
        if isinstance(sub, (ast.Import, ast.ImportFrom)):
            findings.append(Finding(
                "RNB-H002", rel, sub.lineno, qual,
                "import inside a per-request hot path — hoist to the "
                "module top or use rnb_tpu.utils.lazy_jax"))
        elif isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Attribute) and f.attr == "device_put" \
                    and in_loop(sub.lineno):
                findings.append(Finding(
                    "RNB-H003", rel, sub.lineno, qual,
                    "device_put inside a loop on a hot path — per-item "
                    "transfers serialize; batch first, transfer once"))
            kind = _host_sync_kind(sub)
            if kind is not None:
                findings.append(Finding(
                    "RNB-H006", rel, sub.lineno, qual,
                    "%s on a hot path stalls the executor thread — fix "
                    "it, or baseline it with the justification"
                    % kind))
            alloc = _bucket_alloc_kind(sub)
            if alloc is not None:
                findings.append(Finding(
                    "RNB-H007", rel, sub.lineno, qual,
                    "%s on a per-emission loader path — decode into a "
                    "staging slot (rnb_tpu.staging) instead, or "
                    "baseline the copy fallback with its justification"
                    % alloc))
            if os.path.basename(rel) not in _H010_EXEMPT_BASENAMES:
                dev_alloc = _device_alloc_kind(sub)
                if dev_alloc is not None:
                    findings.append(Finding(
                        "RNB-H010", rel, sub.lineno, qual,
                        "%s on a hot path — a fresh pool-shaped device "
                        "array per emission fragments HBM; draw from "
                        "the page allocator (rnb_tpu.pager) or a "
                        "stage-init preallocation, or baseline the "
                        "deliberate fallback with its justification"
                        % dev_alloc))


#: attribute names whose NO-ARGUMENT call blocks until someone else
#: acts — with no timeout, forever (dict.get and Queue.get(key-ish)
#: take positional args, so zero-arg calls are the queue/event/lock/
#: future shapes)
_H009_BLOCKING_ATTRS = {"get", "wait", "acquire", "result"}


def _unbounded_wait_kind(node: ast.Call) -> Optional[str]:
    """Classify one call as an unbounded blocking wait, or None."""
    f = node.func
    if not isinstance(f, ast.Attribute) \
            or f.attr not in _H009_BLOCKING_ATTRS:
        return None
    if node.args:
        return None  # positional args: dict.get(key), pool.wait(t)
    if any(kw.arg == "timeout" for kw in node.keywords):
        return None
    return ".%s()" % f.attr


#: socket verbs whose blocking is bounded only by the SOCKET's
#: configured timeout — unlike queue/event waits there is no per-call
#: ``timeout=`` to demand, so the rule instead demands VISIBLE timeout
#: discipline in the enclosing function: a ``settimeout(...)`` call
#: (configuring the socket before/around the blocking verb) or a
#: ``gettimeout()`` consult (guarding against an unconfigured one,
#: the rnb_tpu.ops.wire.recv_exact idiom)
_H009_SOCKET_ATTRS = {"recv", "recv_into", "accept", "connect"}

#: the in-function evidence that a socket's blocking is bounded
_H009_SOCKET_MARKERS = {"settimeout", "gettimeout"}


def _socket_wait_kind(node: ast.Call) -> Optional[str]:
    """Classify one call as a timeout-governed socket verb, or None."""
    f = node.func
    if not isinstance(f, ast.Attribute) \
            or f.attr not in _H009_SOCKET_ATTRS:
        return None
    return ".%s()" % f.attr


def _lint_unbounded_waits(rel: str, index: _ModuleIndex,
                          findings: List[Finding],
                          hot: Set[str]) -> None:
    """RNB-H009 over the hot set plus every ``wait`` method — the
    blocking leaf hot paths call through cross-object (the intra-
    module call graph cannot follow ``handle.wait()``), so the leaves
    are linted under their own anchors.

    Socket verbs (recv/recv_into/accept/connect) are linted over
    EVERY function for the same leaf reason — receiver loops are
    thread targets the hot-root graph cannot reach — and their
    compliance evidence is per-function: the socket's timeout cannot
    ride the call, so the function that blocks must be the one seen
    configuring (``settimeout``) or guarding (``gettimeout``) it.
    """
    scope = set(hot)
    for qual in index.functions:
        name = qual.rsplit(".", 1)[-1]
        if name == "wait":
            scope.add(qual)
    for qual in sorted(scope):
        node = index.functions.get(qual)
        if node is None:
            continue
        for sub in _own_walk(node):
            if not isinstance(sub, ast.Call):
                continue
            kind = _unbounded_wait_kind(sub)
            if kind is not None:
                findings.append(Finding(
                    "RNB-H009", rel, sub.lineno, qual,
                    "%s without a timeout on a hot/blocking path — a "
                    "dead counterpart hangs this thread forever; "
                    "bound the wait and re-check liveness each lap, "
                    "or baseline it with the justification" % kind))
    for qual in sorted(index.functions):
        node = index.functions[qual]
        bounded = any(
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr in _H009_SOCKET_MARKERS
            for sub in _own_walk(node))
        if bounded:
            continue
        for sub in _own_walk(node):
            if not isinstance(sub, ast.Call):
                continue
            kind = _socket_wait_kind(sub)
            if kind is not None:
                findings.append(Finding(
                    "RNB-H009", rel, sub.lineno, qual,
                    "socket%s with no configured timeout in sight — "
                    "a silently dead peer blocks this thread forever "
                    "instead of classifying as net_timeout; settimeout "
                    "the socket (or gettimeout-guard it) in this "
                    "function, or baseline it with the justification"
                    % kind))


def _lint_fault_determinism(rel: str, index: _ModuleIndex,
                            findings: List[Finding]) -> None:
    is_faults_module = os.path.basename(rel) == "faults.py"
    for qual, node in index.functions.items():
        cls = qual.rsplit(".", 1)[0] if "." in qual else ""
        if not (is_faults_module or "FaultPlan" in cls
                or "fault_plan" in node.name):
            continue
        for sub in _own_walk(node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            bad = None
            if isinstance(f, ast.Attribute):
                if f.attr == "time" and isinstance(f.value, ast.Name) \
                        and f.value.id == "time":
                    bad = "time.time()"
                elif isinstance(f.value, ast.Name) \
                        and f.value.id == "random":
                    bad = "random.%s()" % f.attr
                elif isinstance(f.value, ast.Attribute) \
                        and f.value.attr == "random" \
                        and isinstance(f.value.value, ast.Name) \
                        and f.value.value.id in _NP_NAMES:
                    bad = "np.random.%s()" % f.attr
                elif f.attr in ("now", "utcnow") \
                        and _attr_chain_has(f, {"datetime"}):
                    bad = "datetime.%s()" % f.attr
            if bad is not None:
                findings.append(Finding(
                    "RNB-H004", rel, sub.lineno, qual,
                    "%s in deterministic fault-injection code — "
                    "schedules must be reproducible (use seeded, "
                    "stateless draws like faults._hash_draw)" % bad))


#: host-materialization calls RNB-H008 rejects on device-resident
#: handoff paths (attribute names; np-receiver checked for asarray/
#: array)
_H008_NP_CALLS = {"asarray", "array"}
_H008_ATTR_CALLS = {"device_get", "copy_to_host_async", "tolist"}


def _lint_handoff_device_paths(rel: str, index: _ModuleIndex,
                               findings: List[Finding]) -> None:
    """RNB-H008: no host materialization inside a device-resident
    handoff path. Scope: methods of ``*Handoff*`` classes and
    module-level functions of ``handoff*.py`` modules; a ``host``
    component in the function name marks the designated host-mode
    path and exempts it (that arm exists to bounce, measurably)."""
    is_handoff_module = os.path.basename(rel).startswith("handoff")
    for qual, node in index.functions.items():
        cls, _, meth = qual.rpartition(".")
        name = meth or qual
        in_scope = "Handoff" in cls or (is_handoff_module and not cls)
        if not in_scope or "host" in name.lower():
            continue
        for sub in _own_walk(node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            bad = None
            if isinstance(f, ast.Attribute):
                if f.attr in _H008_ATTR_CALLS:
                    bad = ".%s()" % f.attr
                elif f.attr in _H008_NP_CALLS \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in _NP_NAMES:
                    bad = "np.%s()" % f.attr
            if bad is not None:
                findings.append(Finding(
                    "RNB-H008", rel, sub.lineno, qual,
                    "%s on a device-resident handoff path — the edge "
                    "contract promises zero host-hop bytes; move the "
                    "call into the '*host*'-named host-mode path or "
                    "fix it" % bad))


def _lint_shed_ordering(rel: str, index: _ModuleIndex,
                        findings: List[Finding]) -> None:
    for qual, node in index.functions.items():
        write_line = shed_line = None
        for sub in _own_walk(node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if isinstance(f, ast.Attribute) and f.attr == "write" \
                    and _attr_chain_has(f.value, {"output_ring"}):
                if write_line is None or sub.lineno < write_line:
                    write_line = sub.lineno
            if isinstance(f, ast.Name) and f.id == "_shed_item":
                if shed_line is None or sub.lineno < shed_line:
                    shed_line = sub.lineno
        if write_line is not None and shed_line is not None \
                and write_line < shed_line:
            findings.append(Finding(
                "RNB-H005", rel, write_line, qual,
                "ring-slot write at line %d precedes the shed decision "
                "at line %d — a written-but-never-signalled slot "
                "deadlocks the producer on wrap-around; decide shed "
                "first" % (write_line, shed_line)))


def check_file(path: str, root: str = ".") -> List[Finding]:
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    try:
        tree = parse_py(path)
    except SyntaxError as e:
        return [Finding("RNB-H000", rel, e.lineno or 0, "parse",
                        "file does not parse: %s" % e)]
    index = _ModuleIndex()
    index.visit(tree)
    findings: List[Finding] = []

    def is_direct_method(qual: str) -> bool:
        # "Class.method" (exactly one dot, class known): methods are
        # never handed to jax.jit by bare name — a same-named method
        # elsewhere in the module must not be linted as a jit body
        head, _, tail = qual.partition(".")
        return bool(tail) and "." not in tail \
            and head in index.class_methods

    jit_quals = {q for n in index.jit_names
                 for q in index.by_name.get(n, ())
                 if not is_direct_method(q)}
    for qual in sorted(jit_quals):
        _lint_jit_body(rel, qual, index.functions[qual], findings)

    hot = _hot_set(index, rel)
    for qual in sorted(hot - jit_quals):
        _lint_hot_body(rel, qual, index.functions[qual], findings)

    _lint_unbounded_waits(rel, index, findings, hot)
    _lint_fault_determinism(rel, index, findings)
    _lint_shed_ordering(rel, index, findings)
    _lint_handoff_device_paths(rel, index, findings)
    return findings


def check_package(package_dir: str, root: str = ".") -> List[Finding]:
    findings: List[Finding] = []
    for path in package_py_files(package_dir):
        findings.extend(check_file(path, root))
    return findings
