"""rnb-lint concurrency family: declared lock contracts + discipline.

The repo's threaded modules guard their cross-thread state machines
(staging slot lifecycle, hedge claim ledger, pager pin/limbo, lane
boards) by convention; this family turns the convention into declared,
checkable contracts — same philosophy as the telemetry registries
(rnb_tpu.telemetry): declare once, cross-check everywhere.

Declaration seams (class attributes on lock-owning classes):

``GUARDED_BY = {"_entries": "_lock", ...}``
    Which lock guards which attribute. Values are attribute chains on
    ``self`` — ``"_lock"`` for an own lock, ``"pager.lock"`` for a
    lock owned by a collaborator (the rnb_tpu.pager discipline).
``UNGUARDED_OK = {"_evicted": "tx-thread confined", ...}``
    Attributes that are lock-free by design, each with its one-line
    justification (thread confinement, immutable-after-publish, ...).
``READ_ONLY_ROLES = {"hot": "pollers must never mutate", ...}``
    Thread roles (see below) from which every method must be
    read-only on shared state.

Rules:

RNB-C001
    A ``GUARDED_BY`` attribute is read or written at a site where the
    declared lock is not statically held. Lock-held-at-site tracks
    ``with self._lock:`` blocks, paired ``acquire()``/``release()``
    calls (including the acquire/try/finally-release shape), the
    Condition-on-lock alias (``threading.Condition(self._lock)``
    counts as the lock), and the ``*_locked`` naming convention
    (callee asserts the caller holds the class's locks). ``__init__``
    is exempt (no concurrent aliases exist yet).
RNB-C002
    A method whose inferred thread role is declared read-only writes a
    shared attribute. Roles come from the existing seams: hotpath's
    executor roots (``HOT_ROOT_METHODS`` -> role ``hot``) and
    ``threading.Thread(target=self.x, name="...")`` entry points
    (role = the thread-name prefix, the trace/hostprof convention),
    propagated through self-method calls.
RNB-C003
    A lock-owning class mutates attributes after ``__init__`` without
    declaring them (neither ``GUARDED_BY`` nor ``UNGUARDED_OK``).
    Attributes only ever assigned in ``__init__`` are
    immutable-by-convention and exempt.
RNB-C004
    The static lock-acquisition order graph has a cycle. Lock identity
    is ``(class, attr)``; edges come from nested ``with`` blocks and
    from self-method calls made while a lock is held (one transitive
    closure over the class's own call graph).
RNB-C005
    A blocking call — ``queue.get/put``, bare ``.wait()``,
    ``.result()``, ``.join()``, device sync, socket IO, ``time.sleep``
    — while holding a lock. ``Condition.wait`` on the held lock itself
    is the sanctioned exception (it releases the lock), and
    ``dict.get(key)`` (positional args) is never flagged.

The static graph is exported via :func:`static_lock_order_edges` so
``parse_utils --check`` can verify the runtime witness
(rnb_tpu.lockwitness): observed acquisition-order edges must be a
subset of this graph, with zero witness violations.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from rnb_tpu.analysis.findings import Finding, package_py_files, parse_py

#: executor-side entry points (the hotpath family's reachability
#: roots) — methods reachable from these carry the ``hot`` role
HOT_ROOT_METHODS = ("__call__", "submit", "complete", "poll", "select")

#: threading constructors whose result makes an attribute a lock
_LOCK_FACTORIES = ("Lock", "RLock")
#: attribute names that make a bare ``with``-context count as a lock
#: even without a resolvable constructor (foreign chains like
#: ``arena.pager.lock``)
_LOCKISH = "lock"

#: blocking attribute calls flagged under a held lock regardless of
#: argument shape
_BLOCKING_ATTRS = {"result", "block_until_ready", "recv", "recv_into",
                   "sendall", "accept", "send_frame", "read_frame",
                   "recv_frame"}
#: blocking only with zero positional args (``q.get()`` blocks;
#: ``d.get(key)`` is a dict probe)
_BLOCKING_ATTRS_ZERO_ARG = {"get", "join", "wait"}
#: bare-name calls flagged under a held lock
_BLOCKING_NAMES = {"create_connection", "block_until_ready"}

_CONTRACT_NAMES = ("GUARDED_BY", "UNGUARDED_OK", "READ_ONLY_ROLES")


def _rel(path: str, root: Optional[str]) -> str:
    if root:
        try:
            return os.path.relpath(path, root)
        except ValueError:
            pass
    return path


def _own_walk(node):
    """Walk a function body without descending into nested function or
    class definitions (their bodies run in other scopes — often other
    threads — and are analyzed on their own)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(child))


def _attr_chain(node) -> Optional[Tuple[str, ...]]:
    """``a.b.c`` -> ("a", "b", "c"); None for non-name chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _call_chain(call: ast.Call) -> Optional[Tuple[str, ...]]:
    return _attr_chain(call.func)


class _Method:
    """Per-method facts the class-level passes consume."""

    def __init__(self, node):
        self.node = node
        self.name = node.name
        #: locks acquired anywhere in the body (chains on self)
        self.acquires: Set[Tuple[str, ...]] = set()
        #: self-methods called anywhere in the body
        self.self_calls: Set[str] = set()
        #: self attributes written outside __init__
        self.writes: Set[str] = set()
        #: (held-chain frozenset, callee-name) for call-graph edges
        self.calls_under_lock: List[Tuple[frozenset, str]] = []


class _ClassContract:
    """One class's lock inventory + declared contracts."""

    def __init__(self, node: ast.ClassDef, file: str):
        self.node = node
        self.file = file
        self.name = node.name
        self.locks: Set[str] = set()        # own lock attrs
        self.aliases: Dict[str, str] = {}   # Condition attr -> lock attr
        self.guarded: Dict[str, str] = {}
        self.unguarded_ok: Dict[str, str] = {}
        self.read_only_roles: Dict[str, str] = {}
        self.declared = False               # any contract attr present
        self.contract_errors: List[Tuple[int, str]] = []
        self.methods: Dict[str, _Method] = {}
        #: role entry points: method name -> role
        self.entry_roles: Dict[str, str] = {}

    def guard_chain(self, attr: str) -> Tuple[str, ...]:
        """The declared guard of ``attr`` as a normalized chain."""
        return self.normalize(tuple(self.guarded[attr].split(".")))

    def normalize(self, chain: Tuple[str, ...]) -> Tuple[str, ...]:
        """Resolve the Condition-on-lock alias on own-lock chains."""
        if len(chain) == 1 and chain[0] in self.aliases:
            return (self.aliases[chain[0]],)
        return chain


def _thread_role(name_literal: Optional[str]) -> str:
    """Thread role from the ``name=`` literal the trace/hostprof seams
    key on: the prefix before any per-instance numbering
    (``rnb-decode_3`` -> ``rnb-decode``)."""
    if not name_literal:
        return "worker"
    role = name_literal
    for sep in ("_", "-"):
        head, _, tail = role.rpartition(sep)
        if head and tail.isdigit():
            role = head
    return role


def _extract_contracts(cls: ast.ClassDef, file: str) -> _ClassContract:
    info = _ClassContract(cls, file)
    for stmt in cls.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id in _CONTRACT_NAMES:
            name = stmt.targets[0].id
            try:
                value = ast.literal_eval(stmt.value)
                if not isinstance(value, dict) \
                        or not all(isinstance(k, str)
                                   and isinstance(v, str)
                                   for k, v in value.items()):
                    raise ValueError("must be a {str: str} dict")
            except ValueError as exc:
                info.contract_errors.append(
                    (stmt.lineno, "%s is not a literal {str: str} dict "
                     "(%s)" % (name, exc)))
                continue
            info.declared = True
            if name == "GUARDED_BY":
                info.guarded = value
            elif name == "UNGUARDED_OK":
                info.unguarded_ok = value
            else:
                info.read_only_roles = value
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            deco = {d.id for d in stmt.decorator_list
                    if isinstance(d, ast.Name)}
            if "staticmethod" in deco or "classmethod" in deco:
                continue
            info.methods[stmt.name] = _Method(stmt)

    # lock inventory + Condition aliasing, from __init__ assignments
    init = info.methods.get("__init__")
    if init is not None:
        for node in _own_walk(init.node):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id == "self"
                    and isinstance(node.value, ast.Call)):
                continue
            attr = node.targets[0].attr
            chain = _call_chain(node.value)
            if chain is None:
                continue
            if chain[-1] in _LOCK_FACTORIES \
                    or chain[-2:] == ("lockwitness", "lock") \
                    or chain == ("lock",):
                info.locks.add(attr)
            elif chain[-1] == "Condition":
                args = node.value.args
                base = _attr_chain(args[0]) if args else None
                if base is not None and len(base) == 2 \
                        and base[0] == "self":
                    info.aliases[attr] = base[1]
                else:
                    # a Condition owns a private lock when built bare
                    info.locks.add(attr)

    # role entry points: hotpath executor roots + Thread targets
    for mname in info.methods:
        if mname in HOT_ROOT_METHODS:
            info.entry_roles[mname] = "hot"
    for m in info.methods.values():
        for node in _own_walk(m.node):
            if not (isinstance(node, ast.Call)
                    and (_call_chain(node) or ())[-1:] == ("Thread",)):
                continue
            target = None
            name_literal = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = _attr_chain(kw.value)
                elif kw.arg == "name" \
                        and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    name_literal = kw.value.value
            if target is not None and len(target) == 2 \
                    and target[0] == "self" \
                    and target[1] in info.methods:
                info.entry_roles[target[1]] = _thread_role(name_literal)
    if "run" in info.methods and "run" not in info.entry_roles:
        for base in cls.bases:
            bchain = _attr_chain(base) or ()
            if bchain[-1:] == ("Thread",):
                info.entry_roles["run"] = "worker"
    return info


def _is_lock_chain(info: _ClassContract, chain: Tuple[str, ...]) -> bool:
    """Does ``with self.<chain>`` / ``<chain>.acquire()`` take a lock?"""
    if not chain:
        return False
    if chain[0] == "self":
        rest = info.normalize(chain[1:])
        if not rest:
            return False
        if len(rest) == 1:
            return rest[0] in info.locks or rest[0] in info.aliases \
                or _LOCKISH in rest[0].lower()
        return _LOCKISH in rest[-1].lower()
    if len(chain) == 1:
        # module-level lock convention: private name containing "lock"
        return chain[0].startswith("_") and _LOCKISH in chain[0].lower()
    return _LOCKISH in chain[-1].lower()


def _held_key(info: _ClassContract,
              chain: Tuple[str, ...]) -> Tuple[str, ...]:
    """Normalize an acquisition chain to the held-set key: own locks
    become a 1-tuple attr, foreign chains keep their tail."""
    if chain and chain[0] == "self":
        return info.normalize(chain[1:])
    return chain


class _MethodScan:
    """One statement-ordered pass over a method body, tracking the set
    of held locks through ``with`` blocks and acquire/release pairs."""

    def __init__(self, info: _ClassContract, method: _Method,
                 findings: List[Finding], edges: Set[Tuple], file: str,
                 check_c001: bool):
        self.info = info
        self.m = method
        self.findings = findings
        self.edges = edges
        self.file = file
        self.check_c001 = check_c001
        self.anchor = "%s.%s" % (info.name, method.name)
        self._c001_seen: Set[str] = set()
        self._c005_seen: Set[int] = set()

    def run(self, initial_held: Set[Tuple[str, ...]]) -> None:
        self._block(self.m.node.body, set(initial_held))

    # -- statement walk ----------------------------------------------

    def _block(self, stmts, held: Set[Tuple[str, ...]]) -> None:
        for stmt in stmts:
            self._stmt(stmt, held)

    def _stmt(self, stmt, held: Set[Tuple[str, ...]]) -> None:
        if isinstance(stmt, ast.With):
            entered = []
            for item in stmt.items:
                chain = None
                if isinstance(item.context_expr, (ast.Attribute,
                                                  ast.Name)):
                    chain = _attr_chain(item.context_expr)
                if chain is not None \
                        and _is_lock_chain(self.info, chain):
                    self._acquire(chain, held)
                    entered.append(_held_key(self.info, chain))
                else:
                    self._exprs(item.context_expr, held)
            self._block(stmt.body, held)
            for key in entered:
                held.discard(key)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.Try):
            self._block(stmt.body, held)
            for handler in stmt.handlers:
                self._block(handler.body, set(held))
            self._block(stmt.orelse, set(held))
            self._block(stmt.finalbody, held)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._exprs(stmt.test, held)
            self._block(stmt.body, set(held))
            self._block(stmt.orelse, set(held))
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._exprs(stmt.iter, held)
            self._access(stmt.target, held, write=True)
            self._block(stmt.body, set(held))
            self._block(stmt.orelse, set(held))
            return
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Call):
            chain = _call_chain(stmt.value)
            if chain is not None and len(chain) > 1:
                if chain[-1] == "acquire" \
                        and _is_lock_chain(self.info, chain[:-1]):
                    self._exprs(stmt.value, held, skip_blocking=True)
                    self._acquire(chain[:-1], held)
                    return
                if chain[-1] == "release" \
                        and _is_lock_chain(self.info, chain[:-1]):
                    held.discard(_held_key(self.info, chain[:-1]))
                    return
        # generic statement: check every expression inside it
        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                self._access(target, held, write=True)
            self._exprs(stmt.value, held)
            return
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            self._access(stmt.target, held, write=True)
            if isinstance(stmt, ast.AugAssign) or stmt.value is not None:
                self._exprs(stmt.value, held)
            return
        if isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                self._access(target, held, write=True)
            return
        for child in ast.iter_child_nodes(stmt):
            self._exprs(child, held)

    # -- acquisition bookkeeping -------------------------------------

    def _acquire(self, chain: Tuple[str, ...],
                 held: Set[Tuple[str, ...]]) -> None:
        key = _held_key(self.info, chain)
        if key in held:
            return  # reentrant re-acquire: no new edge
        for prior in held:
            self.edges.add((self.info.name, prior, key,
                            self.file, self.anchor))
        held.add(key)
        self.m.acquires.add(key)

    # -- expression walk (accesses + blocking calls) ------------------

    def _exprs(self, node, held: Set[Tuple[str, ...]],
               skip_blocking: bool = False) -> None:
        if node is None:
            return
        for sub in [node] + [n for n in _own_walk(node)]:
            if isinstance(sub, ast.Attribute):
                self._access(sub, held, write=False)
            elif isinstance(sub, ast.Call) and not skip_blocking:
                self._call(sub, held)

    def _access(self, node, held: Set[Tuple[str, ...]],
                write: bool) -> None:
        if isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                self._access(elt, held, write=write)
            return
        if isinstance(node, (ast.Subscript, ast.Starred)):
            # a[k] = v reads the container binding; the element write
            # is still a mutation of the guarded structure
            self._access(node.value, held, write=write)
            if isinstance(node, ast.Subscript):
                self._exprs(node.slice, held)
            return
        if not (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            if isinstance(node, ast.Attribute):
                self._exprs(node.value, held)
            return
        attr = node.attr
        if write and self.m.name != "__init__":
            self.m.writes.add(attr)
        if not self.check_c001 or attr not in self.info.guarded:
            return
        guard = self.info.guard_chain(attr)
        if guard in held or attr in self._c001_seen:
            return
        self._c001_seen.add(attr)
        self.findings.append(Finding(
            "RNB-C001", self.file, node.lineno, self.anchor,
            "%s self.%s outside its declared lock %r "
            "(GUARDED_BY on %s)" % (
                "writes" if write else "reads", attr,
                self.info.guarded[attr], self.info.name)))

    def _call(self, call: ast.Call,
              held: Set[Tuple[str, ...]]) -> None:
        chain = _call_chain(call)
        if chain is None:
            return
        if chain[0] == "self" and len(chain) == 2 \
                and chain[1] in self.info.methods:
            self.m.self_calls.add(chain[1])
            if held:
                self.m.calls_under_lock.append(
                    (frozenset(held), chain[1]))
        if not held or call.lineno in self._c005_seen:
            return
        blocking = None
        tail = chain[-1]
        if len(chain) > 1 and tail in _BLOCKING_ATTRS:
            blocking = ".%s()" % tail
        elif len(chain) > 1 and tail in _BLOCKING_ATTRS_ZERO_ARG \
                and not call.args:
            if tail == "wait":
                # Condition.wait on the held lock releases it — the
                # sanctioned blocking shape
                key = _held_key(self.info, chain[:-1])
                if key in held:
                    return
            blocking = ".%s()" % tail
        elif len(chain) > 1 and tail == "put" \
                and "queue" in chain[-2].lower():
            blocking = ".put()"
        elif chain == ("time", "sleep"):
            blocking = "time.sleep()"
        elif len(chain) == 1 and tail in _BLOCKING_NAMES:
            blocking = "%s()" % tail
        if blocking is None:
            return
        self._c005_seen.add(call.lineno)
        self.findings.append(Finding(
            "RNB-C005", self.file, call.lineno, self.anchor,
            "blocking call %s while holding %s" % (
                blocking,
                ", ".join(sorted(".".join(h) for h in held)))))


# -- per-file analysis -------------------------------------------------

def _classes_of(tree) -> List[ast.ClassDef]:
    out = []
    stack = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.ClassDef):
            out.append(node)
            stack.extend(n for n in node.body
                         if isinstance(n, ast.ClassDef))
    return sorted(out, key=lambda c: c.lineno)


def _scan_class(info: _ClassContract,
                findings: List[Finding],
                edges: Set[Tuple]) -> None:
    relevant = bool(info.locks or info.declared or info.aliases)
    for m in info.methods.values():
        if m.name == "__init__":
            # still scanned for C005/edges (locks can nest in setup),
            # but C001 is moot: no concurrent aliases exist yet
            initial: Set[Tuple[str, ...]] = set()
            check_c001 = False
        elif m.name.endswith("_locked"):
            # the *_locked convention: the caller holds the class's
            # locks — C001-clean by contract, but blocking calls are
            # blocking calls under THOSE locks (C005 still applies)
            initial = {info.guard_chain(a) for a in info.guarded}
            initial |= {(lk,) for lk in info.locks}
            check_c001 = False
        else:
            initial = set()
            check_c001 = relevant
        scan = _MethodScan(info, m, findings, edges, info.file,
                           check_c001=check_c001)
        scan.run(initial)

    # transitive self-call edges: caller holds H, callee acquires B
    acquires = {name: set(m.acquires)
                for name, m in info.methods.items()}
    changed = True
    while changed:
        changed = False
        for name, m in info.methods.items():
            for callee in m.self_calls:
                extra = acquires.get(callee, set()) - acquires[name]
                if extra:
                    acquires[name] |= extra
                    changed = True
    for m in info.methods.values():
        for held, callee in m.calls_under_lock:
            for acquired in acquires.get(callee, set()):
                if acquired not in held:
                    for prior in held:
                        edges.add((info.name, prior, acquired,
                                   info.file,
                                   "%s.%s" % (info.name, m.name)))

    if not relevant:
        return

    for lineno, msg in info.contract_errors:
        findings.append(Finding("RNB-C003", info.file, lineno,
                                info.name, msg))

    # C003: post-init mutations must be declared (lock-owning classes)
    if info.locks:
        undeclared = set()
        for m in info.methods.values():
            undeclared |= m.writes
        undeclared -= set(info.guarded)
        undeclared -= set(info.unguarded_ok)
        undeclared -= info.locks
        undeclared -= set(info.aliases)
        if undeclared:
            findings.append(Finding(
                "RNB-C003", info.file, info.node.lineno, info.name,
                "lock-owning class mutates undeclared shared "
                "attribute(s) after __init__: %s — declare each in "
                "GUARDED_BY or UNGUARDED_OK"
                % ", ".join(sorted(undeclared))))

    # C002: read-only roles must not write shared state
    if info.read_only_roles:
        roles: Dict[str, Set[str]] = {}
        for entry, role in info.entry_roles.items():
            roles.setdefault(entry, set()).add(role)
        changed = True
        while changed:
            changed = False
            for name, m in info.methods.items():
                for callee in m.self_calls:
                    extra = roles.get(name, set()) \
                        - roles.get(callee, set())
                    if extra and callee in info.methods:
                        roles.setdefault(callee, set()).update(extra)
                        changed = True
        for name, m in info.methods.items():
            if name == "__init__":
                continue
            bad_roles = roles.get(name, set()) \
                & set(info.read_only_roles)
            shared_writes = m.writes - set(info.unguarded_ok) \
                - info.locks - set(info.aliases)
            if bad_roles and shared_writes:
                findings.append(Finding(
                    "RNB-C002", info.file, m.node.lineno,
                    "%s.%s" % (info.name, name),
                    "role %r is declared read-only but this method "
                    "writes %s" % (sorted(bad_roles)[0],
                                   ", ".join(sorted(shared_writes)))))


def _resolve_edges(edges: Set[Tuple],
                   lock_owners: Dict[str, Set[str]]
                   ) -> Tuple[Set[Tuple[str, str]],
                              Dict[Tuple[str, str],
                                   Tuple[str, str]]]:
    """(cls, held-key, acquired-key, file, anchor) tuples -> global
    edge set over "Class.attr" lock names, plus one representative
    (file, anchor) site per edge for rendering."""
    resolved: Set[Tuple[str, str]] = set()
    sites: Dict[Tuple[str, str], Tuple[str, str]] = {}

    def name_of(cls: str, key: Tuple[str, ...]) -> Optional[str]:
        attr = key[-1]
        if len(key) == 1:
            return "%s.%s" % (cls, attr)
        owners = lock_owners.get(attr, set())
        if len(owners) == 1:
            return "%s.%s" % (next(iter(owners)), attr)
        return None  # ambiguous foreign lock: never invent an edge

    for cls, held, acquired, file, anchor in edges:
        a, b = name_of(cls, held), name_of(cls, acquired)
        if a is None or b is None or a == b:
            continue
        edge = (a, b)
        resolved.add(edge)
        sites.setdefault(edge, (file, anchor))
    return resolved, sites


def _find_cycles(edges: Set[Tuple[str, str]]) -> List[List[str]]:
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles: List[List[str]] = []
    seen_cycles: Set[Tuple[str, ...]] = set()

    def dfs(node, path, on_path):
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_path:
                cyc = path[path.index(nxt):] + [nxt]
                lo = min(range(len(cyc) - 1),
                         key=lambda i: cyc[i])
                canon = tuple(cyc[lo:-1] + cyc[:lo + 1])
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    cycles.append(list(canon))
            elif nxt not in visited:
                visited.add(nxt)
                dfs(nxt, path + [nxt], on_path | {nxt})

    visited: Set[str] = set()
    for start in sorted(graph):
        if start not in visited:
            visited.add(start)
            dfs(start, [start], {start})
    return cycles


# -- public API --------------------------------------------------------

def check_files(paths: List[str],
                root: Optional[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    raw_edges: Set[Tuple] = set()
    lock_owners: Dict[str, Set[str]] = {}
    infos: List[_ClassContract] = []
    for path in paths:
        rel = _rel(path, root)
        tree = parse_py(path)
        for cls in _classes_of(tree):
            info = _extract_contracts(cls, rel)
            infos.append(info)
            for lk in info.locks:
                lock_owners.setdefault(lk, set()).add(info.name)
    for info in infos:
        _scan_class(info, findings, raw_edges)
    resolved, sites = _resolve_edges(raw_edges, lock_owners)
    for cycle in _find_cycles(resolved):
        file, _ = sites[(cycle[0], cycle[1])]
        findings.append(Finding(
            "RNB-C004", file, 0, "->".join(cycle),
            "lock-order cycle: %s — some thread can hold each lock "
            "while wanting the next" % " -> ".join(cycle)))
    findings.sort(key=lambda f: (f.file, f.line, f.rule, f.anchor))
    return findings


def check_file(path: str, root: Optional[str] = None) -> List[Finding]:
    return check_files([path], root=root)


def check_package(package_dir: str,
                  root: Optional[str] = None) -> List[Finding]:
    return check_files(package_py_files(package_dir), root=root)


def static_lock_order_edges(package_dir: Optional[str] = None
                            ) -> Set[Tuple[str, str]]:
    """The static acquisition-order graph over "Class.attr" lock names
    — the reference set ``parse_utils --check`` verifies the runtime
    witness's observed edges against."""
    if package_dir is None:
        package_dir = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
    raw_edges: Set[Tuple] = set()
    lock_owners: Dict[str, Set[str]] = {}
    infos: List[_ClassContract] = []
    for path in package_py_files(package_dir):
        tree = parse_py(path)
        rel = os.path.basename(path)
        for cls in _classes_of(tree):
            info = _extract_contracts(cls, rel)
            infos.append(info)
            for lk in info.locks:
                lock_owners.setdefault(lk, set()).add(info.name)
    findings: List[Finding] = []
    for info in infos:
        _scan_class(info, findings, raw_edges)
    resolved, _ = _resolve_edges(raw_edges, lock_owners)
    return resolved


def contract_registry(package_dir: Optional[str] = None
                      ) -> List[Tuple[str, str, Dict[str, str],
                                      Dict[str, str]]]:
    """(file, class, GUARDED_BY, UNGUARDED_OK) for every declaring
    class — the ``--stamps`` face of this family."""
    if package_dir is None:
        package_dir = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
    out = []
    for path in package_py_files(package_dir):
        tree = parse_py(path)
        for cls in _classes_of(tree):
            info = _extract_contracts(cls, os.path.basename(path))
            if info.declared:
                out.append((info.file, info.name, info.guarded,
                            info.unguarded_ok))
    return out
