"""Telemetry schema checker: stamps, log-meta lines, trailers, counters.

PRs 1-2 each extended the TimeCard/report schema by hand in three
places — the stamp/write sites, ``scripts/parse_utils.py``, and the
docs — and nothing guaranteed the three agreed. This checker extracts
what the tree *actually writes* (every ``TimeCard.record`` stamp,
every content-stamp attribute, every ``log-meta.txt`` line prefix,
every ``# <kind>`` table trailer, every ``key=value`` counter in the
Faults:/Cache: lines) and cross-checks it against the declared
registries in :mod:`rnb_tpu.telemetry` AND against what
``scripts/parse_utils.py`` parses — so a stamp can never again
silently vanish from reports.

Rules
-----
* ``RNB-T001`` unregistered-stamp: a ``.record("...")`` site writes a
  stamp pattern the ``STAMP_REGISTRY`` does not declare.
* ``RNB-T002`` unparsed-stamp: a registered stamp pattern that
  ``scripts/parse_utils.py`` never references — it would be recorded
  but invisible to every report.
* ``RNB-T003`` dead-registry-entry: a registered stamp/meta-line/
  trailer that no code path writes anymore.
* ``RNB-T004`` unregistered-meta-or-trailer: a log-meta line prefix or
  table-trailer kind written somewhere but missing from its registry.
* ``RNB-T005`` unparsed-meta-or-trailer: a registered meta-line prefix
  or trailer kind ``parse_utils`` never checks for.
* ``RNB-T006`` result-field-drift: a ``key=value`` counter written to
  the Faults:/Cache:/Staging:/Autotune:/Trace:/Ragged:/Handoff:/
  Padding:/Compute:/Memory:/Critpath:/Whatif:/Operator:/Stacks:
  log-meta lines with no matching ``BenchmarkResult`` field (or vice
  versa for those counter families; dict-valued fields — bucket
  counts, per-edge overflows, compile signatures, warmup seconds —
  ride their own JSON meta lines and are exempt).
* ``RNB-T007`` unregistered-content-stamp: an attribute stamped onto a
  TimeCard (``time_card.x = ...``) that is neither a core TimeCard
  attribute nor declared in ``CONTENT_STAMPS`` — it would silently
  fail to survive fork/merge. Attributes in ``TRANSIENT_STAMPS`` are
  also accepted: those are DECLARED single-owner carriers (live page
  pins, insert obligations) that must NOT be copied onto a fork.
* ``RNB-T008`` unregistered-trace-event: a ``trace.span`` /
  ``trace.instant`` / ``trace.counter`` / ``trace.name`` site emits an
  event name ``TRACE_EVENT_REGISTRY`` does not declare (the reverse —
  a registered event no site emits — is an RNB-T003 dead entry).
* ``RNB-T009`` unregistered-metric: a ``metrics.counter`` /
  ``metrics.gauge`` / ``metrics.observe`` / ``metrics.mark`` /
  ``metrics.name`` site emits a series name ``METRIC_REGISTRY`` does
  not declare (mirror of RNB-T008 for the live-metrics plane; the
  reverse — a ``site``-sourced registry entry with no remaining call
  site — is an RNB-T003 dead entry; ``bridge``/``poll``/``derived``
  entries have no call sites by design and are exempt).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from rnb_tpu.analysis.findings import (Finding, package_py_files,
                                       parse_py)
from rnb_tpu.telemetry import (CONTENT_STAMPS, META_LINE_REGISTRY,
                               METRIC_REGISTRY, STAMP_REGISTRY,
                               TABLE_TRAILER_REGISTRY,
                               TRACE_EVENT_REGISTRY, TRANSIENT_STAMPS)

#: core TimeCard attributes (assignments to these are state, not
#: content stamps)
TIMECARD_ATTRS = {"timings", "id", "sub_id", "num_parent_timings",
                  "devices", "status", "failure_reason"}

#: local variable names treated as TimeCard receivers at stamp sites
TIMECARD_NAMES = {"time_card", "tc", "card", "in_card", "out_card",
                  "merged", "child"}

#: bare-function stamp recorders whose SECOND argument is the stamp
#: key (card-first calling convention, e.g. the clamped
#: phase-refinement recorder in rnb_tpu/models/r2p1d/model.py)
STAMP_WRAPPERS = {"_record_clamped"}

#: modules whose span/instant/counter/name calls emit trace events
#: (rnb_tpu.trace imported as either name)
TRACE_MODULE_NAMES = {"trace", "trace_mod"}

#: rnb_tpu.trace entry points that take an event name first
TRACE_CALL_ATTRS = {"span", "instant", "counter", "name"}

#: modules whose counter/gauge/observe/mark/name calls emit live
#: metrics (rnb_tpu.metrics imported as either name)
METRIC_MODULE_NAMES = {"metrics", "metrics_mod"}

#: rnb_tpu.metrics entry points that take a series name first
METRIC_CALL_ATTRS = {"counter", "gauge", "observe", "mark", "name"}

_FMT_PLACEHOLDER = re.compile(r"%[0-9.]*[sdf]")


def _pattern_of(value: str) -> str:
    """Normalize a %-format stamp literal to a registry pattern."""
    return _FMT_PLACEHOLDER.sub("{step}", value)


_BRACE_FIELD = re.compile(r"\{[^{}]*\}")


def _fmt_string(node) -> Optional[str]:
    """The string template behind an expression, whatever formatting
    idiom wrote it: a constant, the left side of ``"..." % args``, an
    f-string (interpolations become ``{step}``), or
    ``"...".format(...)``. A site the checker cannot see is a site
    that drifts, so every literal-bearing shape must resolve."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
        return _fmt_string(node.left)
    if isinstance(node, ast.JoinedStr):
        parts = []
        for value in node.values:
            if isinstance(value, ast.Constant) \
                    and isinstance(value.value, str):
                parts.append(value.value)
            else:
                parts.append("{step}")
        return "".join(parts)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "format":
        literal = _fmt_string(node.func.value)
        if literal is not None:
            return _BRACE_FIELD.sub("{step}", literal)
    return None


_parse = parse_py


def _rel(path: str, root: str) -> str:
    return os.path.relpath(path, root).replace(os.sep, "/")


def _code_literals(src: str) -> List[str]:
    """String constants in ``src`` excluding docstrings — the 'does
    the parser reference this name' checks must not be satisfied by a
    comment or docstring mention of a stamp (deleting the parsing code
    while leaving the docstring would otherwise stay green). Snippets
    that do not parse fall back to whole-source matching."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return [src]
    doc_ids = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) \
                    and isinstance(body[0].value, ast.Constant) \
                    and isinstance(body[0].value.value, str):
                doc_ids.add(id(body[0].value))
    return [n.value for n in ast.walk(tree)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)
            and id(n) not in doc_ids]


# -- extraction -------------------------------------------------------

def extract_stamps(py_paths: Sequence[str], root: str = "."
                   ) -> List[Tuple[str, int, str]]:
    """Every literal/%-format stamp recorded anywhere:
    -> [(relpath, line, pattern)]. Non-literal keys (the TimeCardList
    fan-out re-recording a variable) are unresolvable and skipped."""
    out = []
    for path in py_paths:
        rel = _rel(path, root)
        for node in ast.walk(_parse(path)):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "record" and node.args:
                literal = _fmt_string(node.args[0])
                if literal is not None:
                    out.append((rel, node.lineno, _pattern_of(literal)))
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in STAMP_WRAPPERS \
                    and len(node.args) >= 2:
                # stamp-recording helpers take (card, key, ...): the
                # clamped phase-refinement recorder must stay visible
                # to the registry cross-check or its stamps would read
                # as dead entries
                literal = _fmt_string(node.args[1])
                if literal is not None:
                    out.append((rel, node.lineno, _pattern_of(literal)))
    return out


def extract_content_stamps(py_paths: Sequence[str], root: str = "."
                           ) -> List[Tuple[str, int, str]]:
    """Attribute assignments onto TimeCard-named receivers:
    -> [(relpath, line, attr)]."""
    out = []
    for path in py_paths:
        rel = _rel(path, root)
        for node in ast.walk(_parse(path)):
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if isinstance(target, ast.Attribute) \
                        and isinstance(target.value, ast.Name) \
                        and target.value.id in TIMECARD_NAMES:
                    out.append((rel, node.lineno, target.attr))
    return out


def extract_meta_prefixes(benchmark_path: str, root: str = "."
                          ) -> List[Tuple[str, int, str]]:
    """``<Prefix>:`` log-meta line prefixes written via ``.write()``
    in the launcher: -> [(relpath, line, prefix-with-colon)]."""
    rel = _rel(benchmark_path, root)
    out = []
    prefix_re = re.compile(r"^([A-Z][A-Za-z0-9_ ]*:)\s")
    for node in ast.walk(_parse(benchmark_path)):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "write" and node.args:
            literal = _fmt_string(node.args[0])
            if literal is None:
                continue
            m = prefix_re.match(literal)
            if m:
                out.append((rel, node.lineno, m.group(1)))
    return out


def extract_trailer_kinds(telemetry_path: str, root: str = "."
                          ) -> List[Tuple[str, int, str]]:
    """``# <kind>`` table-trailer kinds appearing as string literals in
    the telemetry module: -> [(relpath, line, kind)]."""
    rel = _rel(telemetry_path, root)
    out = []
    kind_re = re.compile(r"^# (\w+)[ \n]")
    for node in ast.walk(_parse(telemetry_path)):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            m = kind_re.match(node.value)
            if m:
                out.append((rel, getattr(node, "lineno", 0), m.group(1)))
    return out


#: counter-carrying log-meta lines and the BenchmarkResult field
#: prefix their ``key=value`` tokens map to (the same mapping
#: parse_utils applies when flattening the meta dict)
COUNTER_LINE_PREFIXES = {"Faults:": "", "Cache:": "cache_",
                         "Staging:": "staging_",
                         "Autotune:": "autotune_",
                         "Trace:": "trace_",
                         "Ragged:": "ragged_",
                         "Shard:": "shard_",
                         "Handoff:": "handoff_",
                         "Padding:": "",
                         "Health:": "health_",
                         "Deadline:": "deadline_",
                         "Hedge:": "hedges_",
                         "Metrics:": "metrics_",
                         "Slo:": "slo_",
                         "Compute:": "compute_",
                         "Memory:": "memory_",
                         "Critpath:": "critpath_",
                         "Whatif:": "whatif_",
                         "Operator:": "operator_",
                         "Stacks:": "stacks_",
                         "Net:": "net_",
                         "Net errors:": "net_err_",
                         "Locks:": "locks_"}

#: verbatim-named counter fields (prefix "") the reverse RNB-T006
#: direction holds to a meta-line counter — the Faults: trio plus the
#: Padding: line's fields
VERBATIM_COUNTER_FIELDS = ("num_failed", "num_shed", "num_retries",
                           "pad_rows", "total_rows", "pad_emissions")


def extract_meta_counter_keys(benchmark_path: str) -> Dict[str, Set[str]]:
    """``key=value`` counter names inside the Faults:/Cache:/Staging:
    log-meta format strings: -> {"Faults:": {...}, ...}."""
    keys: Dict[str, Set[str]] = {}
    key_re = re.compile(r"(\w+)=%")
    for node in ast.walk(_parse(benchmark_path)):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "write" and node.args:
            literal = _fmt_string(node.args[0])
            if literal is None:
                continue
            for prefix in COUNTER_LINE_PREFIXES:
                if literal.startswith(prefix):
                    keys.setdefault(prefix, set()).update(
                        key_re.findall(literal))
    return keys


def extract_metric_names(py_paths: Sequence[str], root: str = "."
                         ) -> List[Tuple[str, int, str]]:
    """Every literal series name passed to a live-metrics entry point
    (``metrics.counter(...)`` / ``.gauge`` / ``.observe`` / ``.mark``
    / ``.name``): -> [(relpath, line, pattern)]. Prebuilt names
    flowing through variables are covered at their ``metrics.name``
    build site, exactly like the trace extractor."""
    out = []
    for path in py_paths:
        rel = _rel(path, root)
        for node in ast.walk(_parse(path)):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in METRIC_CALL_ATTRS \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in METRIC_MODULE_NAMES \
                    and node.args:
                literal = _fmt_string(node.args[0])
                if literal is not None:
                    out.append((rel, node.lineno, _pattern_of(literal)))
    return out


def extract_trace_events(py_paths: Sequence[str], root: str = "."
                         ) -> List[Tuple[str, int, str]]:
    """Every literal event name passed to a tracing entry point
    (``trace.span(...)`` / ``.instant`` / ``.counter`` / ``.name``):
    -> [(relpath, line, pattern)]. Prebuilt names flowing through
    variables are covered at their ``trace.name`` build site."""
    out = []
    for path in py_paths:
        rel = _rel(path, root)
        for node in ast.walk(_parse(path)):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in TRACE_CALL_ATTRS \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in TRACE_MODULE_NAMES \
                    and node.args:
                literal = _fmt_string(node.args[0])
                if literal is not None:
                    out.append((rel, node.lineno, _pattern_of(literal)))
    return out


# -- checks -----------------------------------------------------------

def check_stamps(py_paths: Sequence[str], parse_utils_src: str,
                 root: str = ".", registry=STAMP_REGISTRY
                 ) -> List[Finding]:
    findings: List[Finding] = []
    sites = extract_stamps(py_paths, root)
    registered = {spec.pattern for spec in registry}
    for rel, line, pattern in sites:
        if pattern not in registered:
            findings.append(Finding(
                "RNB-T001", rel, line, pattern,
                "stamp %r is not declared in telemetry.STAMP_REGISTRY "
                "— register it (and teach parse_utils) or remove the "
                "site" % pattern))
    produced = {pattern for _, _, pattern in sites}
    literals = _code_literals(parse_utils_src)
    for spec in registry:
        if spec.pattern not in produced:
            findings.append(Finding(
                "RNB-T003", "rnb_tpu/telemetry.py", 0, spec.pattern,
                "registered stamp %r has no remaining record() site"
                % spec.pattern))
        concrete = spec.pattern.replace("{step}", "0")
        if not any(concrete in lit or spec.pattern in lit
                   for lit in literals):
            findings.append(Finding(
                "RNB-T002", "scripts/parse_utils.py", 0, spec.pattern,
                "registered stamp %r is never referenced by "
                "parse_utils code — it would vanish from every report"
                % spec.pattern))
    return findings


def check_content_stamps(py_paths: Sequence[str], root: str = ".",
                         content=CONTENT_STAMPS) -> List[Finding]:
    findings: List[Finding] = []
    allowed = TIMECARD_ATTRS | set(content) | set(TRANSIENT_STAMPS)
    for rel, line, attr in extract_content_stamps(py_paths, root):
        if attr not in allowed:
            findings.append(Finding(
                "RNB-T007", rel, line, attr,
                "attribute %r stamped onto a TimeCard is not in "
                "telemetry.CONTENT_STAMPS — it would not survive "
                "fork/merge" % attr))
    return findings


def check_meta_lines(benchmark_path: str, parse_utils_src: str,
                     root: str = ".", registry=META_LINE_REGISTRY
                     ) -> List[Finding]:
    findings: List[Finding] = []
    written = extract_meta_prefixes(benchmark_path, root)
    registered = {spec.pattern for spec in registry}
    for rel, line, prefix in written:
        if prefix not in registered:
            findings.append(Finding(
                "RNB-T004", rel, line, prefix,
                "log-meta line %r is not declared in "
                "telemetry.META_LINE_REGISTRY" % prefix))
    produced = {p for _, _, p in written}
    literals = _code_literals(parse_utils_src)
    for spec in registry:
        if spec.pattern not in produced:
            findings.append(Finding(
                "RNB-T003", "rnb_tpu/telemetry.py", 0, spec.pattern,
                "registered log-meta line %r is never written"
                % spec.pattern))
        if not any(spec.pattern in lit for lit in literals):
            findings.append(Finding(
                "RNB-T005", "scripts/parse_utils.py", 0, spec.pattern,
                "registered log-meta line %r is never parsed by "
                "parse_utils code" % spec.pattern))
    return findings


def check_trailers(telemetry_path: str, parse_utils_src: str,
                   root: str = ".", registry=TABLE_TRAILER_REGISTRY
                   ) -> List[Finding]:
    findings: List[Finding] = []
    written = extract_trailer_kinds(telemetry_path, root)
    registered = {spec.pattern for spec in registry}
    for rel, line, kind in written:
        if kind not in registered:
            findings.append(Finding(
                "RNB-T004", rel, line, kind,
                "table trailer kind %r is not declared in "
                "telemetry.TABLE_TRAILER_REGISTRY" % kind))
    produced = {k for _, _, k in written}
    literals = _code_literals(parse_utils_src)
    for spec in registry:
        if spec.pattern not in produced:
            findings.append(Finding(
                "RNB-T003", "rnb_tpu/telemetry.py", 0, spec.pattern,
                "registered trailer kind %r is never written"
                % spec.pattern))
        if spec.pattern not in literals:
            findings.append(Finding(
                "RNB-T005", "scripts/parse_utils.py", 0, spec.pattern,
                "registered trailer kind %r is never consumed by "
                "parse_utils code" % spec.pattern))
    return findings


def check_trace_events(py_paths: Sequence[str], root: str = ".",
                       registry=TRACE_EVENT_REGISTRY) -> List[Finding]:
    """RNB-T008 both ways: every emitted trace event name must be
    declared in ``telemetry.TRACE_EVENT_REGISTRY``, and every declared
    event must still have an emitting site (else RNB-T003) — so the
    trace.json vocabulary can neither drift silently nor rot."""
    findings: List[Finding] = []
    sites = extract_trace_events(py_paths, root)
    registered = {spec.pattern for spec in registry}
    for rel, line, pattern in sites:
        if pattern not in registered:
            findings.append(Finding(
                "RNB-T008", rel, line, pattern,
                "trace event %r is not declared in "
                "telemetry.TRACE_EVENT_REGISTRY — register it or "
                "remove the instrumentation site" % pattern))
    produced = {pattern for _, _, pattern in sites}
    for spec in registry:
        if spec.pattern not in produced:
            findings.append(Finding(
                "RNB-T003", "rnb_tpu/telemetry.py", 0, spec.pattern,
                "registered trace event %r has no remaining "
                "instrumentation site" % spec.pattern))
    return findings


def check_metric_names(py_paths: Sequence[str], root: str = ".",
                       registry=METRIC_REGISTRY) -> List[Finding]:
    """RNB-T009 both ways: every series name a
    ``metrics.counter/gauge/observe/mark/name`` site emits must be
    declared in ``telemetry.METRIC_REGISTRY``, and every declared
    ``site``-sourced series must still have an emitting site (else
    RNB-T003). ``bridge``/``poll``/``derived`` entries are fed from
    trace events, snapshot polls or registry internals — no call site
    exists by design, so only the forward direction applies to them
    (the runtime registry separately rejects undeclared names)."""
    findings: List[Finding] = []
    sites = extract_metric_names(py_paths, root)
    registered = {spec.pattern for spec in registry}
    for rel, line, pattern in sites:
        if pattern not in registered:
            findings.append(Finding(
                "RNB-T009", rel, line, pattern,
                "metric %r is not declared in "
                "telemetry.METRIC_REGISTRY — register it (with its "
                "kind and source) or remove the call site" % pattern))
    produced = {pattern for _, _, pattern in sites}
    for spec in registry:
        if getattr(spec, "source", "site") == "site" \
                and spec.pattern not in produced:
            findings.append(Finding(
                "RNB-T003", "rnb_tpu/telemetry.py", 0, spec.pattern,
                "registered site-sourced metric %r has no remaining "
                "call site" % spec.pattern))
    return findings


def check_benchmark_result(benchmark_path: str, root: str = "."
                           ) -> List[Finding]:
    """Every counter written to the Faults:/Cache: log-meta lines must
    be a BenchmarkResult field (Faults: verbatim; Cache: with the
    ``cache_`` prefix — the same mapping parse_utils applies)."""
    import dataclasses

    from rnb_tpu.benchmark import BenchmarkResult
    rel = _rel(benchmark_path, root)
    fields = {f.name for f in dataclasses.fields(BenchmarkResult)}
    findings: List[Finding] = []
    written = extract_meta_counter_keys(benchmark_path)
    mapped: Set[str] = set()
    for prefix, keys in sorted(written.items()):
        for key in sorted(keys):
            field = COUNTER_LINE_PREFIXES[prefix] + key
            mapped.add(field)
            if field not in fields:
                findings.append(Finding(
                    "RNB-T006", rel, 0, field,
                    "%s line writes %r but BenchmarkResult has no %r "
                    "field — programmatic callers cannot see the "
                    "counter the log records" % (prefix, key, field)))
    # reverse direction for the same counter families: a result field
    # nothing writes to the meta line is invisible to offline parsing
    # (parse_utils reads log-meta, not BenchmarkResult). Dict-valued
    # fields (bucket counts, per-edge overflows) ride their own JSON
    # meta lines, not key=value counters, so they are exempt here —
    # recognized by their shared default_factory, not by spelling of
    # the annotation (which `dict[...]`/`Mapping[...]` would break).
    dict_fields = {f.name for f in dataclasses.fields(BenchmarkResult)
                   if f.default_factory is dict}
    for field in sorted(fields - dict_fields):
        if field in VERBATIM_COUNTER_FIELDS \
                or field.startswith("cache_") \
                or field.startswith("staging_") \
                or field.startswith("autotune_") \
                or field.startswith("trace_") \
                or field.startswith("ragged_") \
                or field.startswith("shard_") \
                or field.startswith("handoff_") \
                or field.startswith("health_") \
                or field.startswith("deadline_") \
                or field.startswith("hedges_") \
                or field.startswith("metrics_") \
                or field.startswith("slo_") \
                or field.startswith("compute_") \
                or field.startswith("memory_") \
                or field.startswith("critpath_") \
                or field.startswith("whatif_") \
                or field.startswith("operator_") \
                or field.startswith("stacks_") \
                or field.startswith("net_") \
                or field.startswith("locks_"):
            if field not in mapped:
                findings.append(Finding(
                    "RNB-T006", rel, 0, field,
                    "BenchmarkResult.%s has no matching counter in "
                    "the Faults:/Cache:/Staging: log-meta lines — "
                    "offline parsing cannot recover it" % field))
    return findings


def check_repo(root: str = ".") -> List[Finding]:
    """The full schema-checker family over one repo checkout."""
    package = os.path.join(root, "rnb_tpu")
    parse_utils = os.path.join(root, "scripts", "parse_utils.py")
    benchmark = os.path.join(package, "benchmark.py")
    telemetry = os.path.join(package, "telemetry.py")
    with open(parse_utils) as f:
        parse_src = f.read()
    py_files = package_py_files(package)
    findings = []
    findings.extend(check_stamps(py_files, parse_src, root))
    findings.extend(check_content_stamps(py_files, root))
    findings.extend(check_meta_lines(benchmark, parse_src, root))
    findings.extend(check_trailers(telemetry, parse_src, root))
    findings.extend(check_trace_events(py_files, root))
    findings.extend(check_metric_names(py_files, root))
    findings.extend(check_benchmark_result(benchmark, root))
    return findings
