"""Finding records and the checked-in baseline of intentional keeps.

A finding is keyed for baselining by ``(rule, file, anchor)`` — never
by line number, which drifts with every unrelated edit. The anchor is
the enclosing function/class qualname for AST findings, the config's
step/group coordinate for graph findings, or the stamp/line name for
schema findings.

Baseline format (``rnb-lint-baseline.txt`` at the repo root): one
entry per line, ``RULE <file> <anchor>  # one-line justification``.
Blank lines and ``#``-first lines are comments. A baseline entry that
matches no current finding is *stale* and fails the lint run — the
baseline documents live exceptions, not history.
"""

from __future__ import annotations

import ast
import dataclasses
import functools
import os
from typing import Dict, List, Optional, Tuple

#: default baseline location, relative to the repo root
BASELINE_FILENAME = "rnb-lint-baseline.txt"


def package_py_files(package_dir: str) -> List[str]:
    """The one sorted walk both source-reading analyzer families
    (hotpath, schema) share — a future exclusion added here applies to
    every family at once instead of drifting per walker."""
    paths = []
    for dirpath, dirnames, filenames in sorted(os.walk(package_dir)):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        paths.extend(os.path.join(dirpath, fn)
                     for fn in sorted(filenames) if fn.endswith(".py"))
    return paths


@functools.lru_cache(maxsize=None)
def parse_py(path: str):
    """Cached AST parse: several analyzer families walk the same
    package file list in one short-lived lint run — parse each file
    once per process."""
    with open(path) as f:
        return ast.parse(f.read(), filename=path)


@dataclasses.dataclass
class Finding:
    """One static-analysis problem at a specific site."""

    rule: str       # e.g. "RNB-H002"
    file: str       # repo-relative path ("" for repo-level findings)
    line: int       # 1-based, 0 when no specific line applies
    anchor: str     # stable site key (qualname / step coord / name)
    message: str

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.file, self.anchor)

    def render(self) -> str:
        where = "%s:%d" % (self.file, self.line) if self.file else "<repo>"
        return "%s %s [%s] %s" % (where, self.rule, self.anchor,
                                  self.message)


def format_findings(findings: List[Finding]) -> str:
    return "\n".join(f.render() for f in findings)


class Baseline:
    """The parsed intentional-exception list."""

    def __init__(self, entries: Dict[Tuple[str, str, str], str],
                 path: Optional[str] = None):
        self.entries = entries  # key -> justification
        self.path = path

    @classmethod
    def load(cls, path: str) -> "Baseline":
        entries: Dict[Tuple[str, str, str], str] = {}
        if not os.path.isfile(path):
            return cls(entries, path)
        with open(path) as f:
            for lineno, raw in enumerate(f, 1):
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                body, _, justification = line.partition("#")
                tokens = body.split()
                if len(tokens) != 3:
                    raise ValueError(
                        "%s:%d: baseline entries are 'RULE file anchor  "
                        "# justification', got %r" % (path, lineno, line))
                entries[tuple(tokens)] = justification.strip()
        return cls(entries, path)

    def empty(self) -> bool:
        return not self.entries


def apply_baseline(findings: List[Finding], baseline: Baseline
                   ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """-> (active, suppressed, stale_entry_lines).

    ``active`` are findings the baseline does not cover; ``suppressed``
    are intentional keeps; ``stale_entry_lines`` render baseline
    entries that matched nothing (they must be pruned — each one is a
    fixed finding still advertised as a live exception).
    """
    active: List[Finding] = []
    suppressed: List[Finding] = []
    seen = set()
    for f in findings:
        if f.key() in baseline.entries:
            suppressed.append(f)
            seen.add(f.key())
        else:
            active.append(f)
    stale = ["%s %s %s  # %s" % (rule, file, anchor,
                                 baseline.entries[(rule, file, anchor)])
             for (rule, file, anchor) in sorted(baseline.entries)
             if (rule, file, anchor) not in seen]
    return active, suppressed, stale
