"""ctypes bindings for the native C++ decoder (native/decode.cpp).

The native library is the performance path for .y4m decode — a fused
probe/decode/convert/resize in C++ with an internal worker pool, the
TPU-native replacement for the role NVVL's GPU decoder played in the
reference (SURVEY.md §2.2 N2).  Everything degrades gracefully: if the
shared library has not been built (``make -C native``) the pure-numpy
:class:`~rnb_tpu.decode.Y4MDecoder` carries the same contract.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional

import numpy as np

from rnb_tpu.decode import (DEFAULT_HEIGHT, DEFAULT_WIDTH, VideoDecoder)
from rnb_tpu.faults import CorruptVideoError, TransientDecodeError
from rnb_tpu.ops.dct import coeffs_from_elems, dct_frame_elems

_ERR_MSGS = {
    -1: "I/O error",
    -2: "not a y4m/mjpeg file / malformed stream (the dct path also "
        "needs an MJPEG container)",
    -3: "unsupported colourspace/sampling/geometry for this pixel "
        "format",
    -4: "bad argument",
    -5: "DCT spectrum exceeds the wire coefficient budget — raise "
        "dct_coeffs_per_frame or use pixel_path yuv420",
}

#: pixel formats of the native decoder (native/decode.cpp kPix*)
PIX_RGB = 0       # fused convert+resize -> (n, F, H, W, 3) u8
PIX_YUV420 = 1    # gather-only packed planes -> (n, F, H*W*3//2) u8
PIX_DCT = 2       # dequantized coefficients -> (n, F, elems) int16

_lib = None
_lib_checked = False
_lib_lock = threading.Lock()


def _lib_path() -> str:
    override = os.environ.get("RNB_NATIVE_LIB")
    if override:
        return override
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(repo_root, "native", "build", "librnb_decode.so")


def load_native():
    """-> the loaded ctypes library, or None if unavailable/disabled."""
    global _lib, _lib_checked
    if os.environ.get("RNB_DISABLE_NATIVE"):
        return None
    with _lib_lock:
        if _lib_checked:
            return _lib
        _lib_checked = True
        path = _lib_path()
        if not os.path.exists(path):
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        # a stale prebuilt library missing newer exports must degrade
        # to the numpy backend like a missing library, not crash
        # (rnb_video_probe marks mjpeg-capable builds)
        for sym in ("rnb_y4m_probe", "rnb_y4m_decode_clips",
                    "rnb_y4m_decode_clips_fmt", "rnb_pool_create",
                    "rnb_pool_destroy", "rnb_pool_submit",
                    "rnb_pool_submit_fmt", "rnb_pool_wait",
                    "rnb_pool_peek", "rnb_video_probe",
                    "rnb_y4m_decode_clips_dct", "rnb_pool_submit_dct"):
            if not hasattr(lib, sym):
                return None
        lib.rnb_y4m_probe.restype = ctypes.c_int
        lib.rnb_y4m_probe.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_longlong)]
        lib.rnb_video_probe.restype = ctypes.c_int
        lib.rnb_video_probe.argtypes = lib.rnb_y4m_probe.argtypes
        lib.rnb_y4m_decode_clips.restype = ctypes.c_int
        lib.rnb_y4m_decode_clips.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_longlong),
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p]
        lib.rnb_pool_create.restype = ctypes.c_void_p
        lib.rnb_pool_create.argtypes = [ctypes.c_int]
        lib.rnb_pool_destroy.restype = None
        lib.rnb_pool_destroy.argtypes = [ctypes.c_void_p]
        lib.rnb_pool_submit.restype = ctypes.c_longlong
        lib.rnb_pool_submit.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_char_p]
        lib.rnb_pool_wait.restype = ctypes.c_int
        lib.rnb_pool_wait.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        lib.rnb_pool_peek.restype = ctypes.c_int
        lib.rnb_pool_peek.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        lib.rnb_y4m_decode_clips_fmt.restype = ctypes.c_int
        lib.rnb_y4m_decode_clips_fmt.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_longlong),
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_char_p]
        lib.rnb_pool_submit_fmt.restype = ctypes.c_longlong
        lib.rnb_pool_submit_fmt.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_char_p]
        lib.rnb_y4m_decode_clips_dct.restype = ctypes.c_int
        lib.rnb_y4m_decode_clips_dct.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_longlong),
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_void_p]
        lib.rnb_pool_submit_dct.restype = ctypes.c_longlong
        lib.rnb_pool_submit_dct.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return load_native() is not None


def default_decode_threads() -> int:
    """The one decode-pool sizing rule: ``RNB_DECODE_THREADS`` env
    override, else min(8, cores). Shared by the native
    :class:`DecodePool` and the loaders' non-native Python fallback
    pool (rnb_tpu/models/r2p1d/model.py ``fallback_decode_threads``),
    so the two backends degrade with identical parallelism."""
    return int(os.environ.get("RNB_DECODE_THREADS",
                              min(8, os.cpu_count() or 1)))


def _check(rc: int, path: str) -> None:
    """Raise the native error code as a *classified* exception
    (rnb_tpu.faults): -1 (read failed; may succeed on retry) is
    transient, -2/-3 (malformed/unsupported stream; retrying cannot
    help) are permanent. Both subclass ValueError, so pre-containment
    callers are unaffected. -4 (bad argument) stays a plain ValueError
    — a caller bug should abort, not dead-letter a request."""
    if rc == 0:
        return
    msg = ("native y4m decode of %r failed: %s"
           % (path, _ERR_MSGS.get(rc, "error %d" % rc)))
    if rc == -1:
        raise TransientDecodeError(msg)
    if rc in (-2, -3, -5):
        # -5 (over-budget spectrum) is permanent: re-decoding cannot
        # shrink a frame's nonzero coefficient count
        raise CorruptVideoError(msg)
    raise ValueError(msg)


class DecodePool:
    """Worker pool over the native library; submit/wait across videos.

    One pool is shared per process (``DecodePool.shared()``); the
    loader stage uses it to overlap decode of queued videos the way the
    reference's NVVL loader overlapped NVDEC work with inference
    (reference README.md:46-110).
    """

    GUARDED_BY = {"_pending": "_pending_lock"}

    UNGUARDED_OK = {
        "_pool": "set in __init__, cleared only by close() at "
                 "teardown after in-flight tickets drain",
    }

    def __init__(self, num_threads: Optional[int] = None):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native decode library not built; run "
                               "`make -C native`")
        if num_threads is None:
            num_threads = default_decode_threads()
        self._lib = lib
        self._pool = lib.rnb_pool_create(int(num_threads))
        self.num_threads = int(num_threads)
        # ticket -> (out, starts): keeps the buffers a worker thread
        # writes into alive until wait() retires the job, even if the
        # caller drops its references mid-flight
        self._pending = {}
        self._pending_lock = threading.Lock()

    _shared = None
    _shared_lock = threading.Lock()

    @classmethod
    def shared(cls) -> "DecodePool":
        with cls._shared_lock:
            if cls._shared is None:
                cls._shared = cls()
            return cls._shared

    def submit(self, path: str, clip_starts: List[int],
               consecutive_frames: int, width: int, height: int):
        """-> (ticket, out_array); pass ticket to :meth:`wait`."""
        out = np.empty((len(clip_starts), consecutive_frames, height,
                        width, 3), dtype=np.uint8)
        ticket = self.submit_into(path, clip_starts, consecutive_frames,
                                  out)
        return ticket, out

    def submit_into(self, path: str, clip_starts: List[int],
                    consecutive_frames: int, out: np.ndarray,
                    pixfmt: int = PIX_RGB,
                    width: int = DEFAULT_WIDTH,
                    height: int = DEFAULT_HEIGHT) -> int:
        """Decode into a caller-provided C-contiguous view — uint8
        (clips, frames, H, W, 3) for PIX_RGB, uint8 (clips, frames,
        H*W*3//2) packed planes for PIX_YUV420, int16 (clips, frames,
        num_blocks + 2*C) coefficient rows for PIX_DCT (geometry comes
        from width/height; a packed length alone is ambiguous, and the
        dct coefficient budget C is recovered from the trailing axis).
        Lets one logical decode fan out over the pool by submitting
        chunks that target disjoint slices of a single batch buffer."""
        want_dtype = np.int16 if pixfmt == PIX_DCT else np.uint8
        if out.dtype != want_dtype or not out.flags["C_CONTIGUOUS"] \
                or out.shape[:2] != (len(clip_starts),
                                     consecutive_frames):
            raise ValueError("bad output buffer %r/%s for %d clips x %d "
                             "frames" % (out.shape, out.dtype,
                                         len(clip_starts),
                                         consecutive_frames))
        dct_coeffs = 0
        if pixfmt == PIX_RGB:
            if out.ndim != 5 or out.shape[4] != 3:
                raise ValueError("PIX_RGB wants (clips, frames, H, W, 3)"
                                 ", got %r" % (out.shape,))
            out_w, out_h = out.shape[3], out.shape[2]
        elif pixfmt == PIX_YUV420:
            if out.ndim != 3 or out.shape[2] != height * width * 3 // 2:
                raise ValueError(
                    "PIX_YUV420 wants (clips, frames, %d) for %dx%d, "
                    "got %r" % (height * width * 3 // 2, height, width,
                                out.shape))
            out_w, out_h = width, height
        elif pixfmt == PIX_DCT:
            if out.ndim != 3:
                raise ValueError("PIX_DCT wants (clips, frames, elems) "
                                 "int16, got %r" % (out.shape,))
            dct_coeffs = coeffs_from_elems(height, width, out.shape[2])
            out_w, out_h = width, height
        else:
            raise ValueError("unknown pixfmt %r" % (pixfmt,))
        starts = (ctypes.c_longlong * len(clip_starts))(*clip_starts)
        if pixfmt == PIX_DCT:
            ticket = self._lib.rnb_pool_submit_dct(
                self._pool, path.encode(), starts, len(clip_starts),
                consecutive_frames, out_w, out_h, dct_coeffs,
                out.ctypes.data_as(ctypes.c_void_p))
        else:
            ticket = self._lib.rnb_pool_submit_fmt(
                self._pool, path.encode(), starts, len(clip_starts),
                consecutive_frames, out_w, out_h, pixfmt,
                out.ctypes.data_as(ctypes.c_char_p))
        if ticket <= 0:
            raise RuntimeError("native pool rejected submit for %r" % path)
        with self._pending_lock:
            self._pending[ticket] = (out, starts)
        return ticket

    def peek(self, ticket: int) -> bool:
        """Non-blocking: True when the ticket's decode has finished.
        Does not retire the ticket — pair with :meth:`wait`."""
        with self._pending_lock:
            if ticket not in self._pending:
                raise ValueError("unknown or already-waited ticket %r"
                                 % (ticket,))
        return bool(self._lib.rnb_pool_peek(self._pool, ticket))

    def wait(self, ticket: int, path: str = "<submitted>") -> None:
        # claim the ticket atomically before touching the native side:
        # rnb_pool_wait blocks forever on unknown/retired tickets, and a
        # check-then-act race between two waiters would send the loser
        # into exactly that hang — the loser must fail fast here instead
        with self._pending_lock:
            buffers = self._pending.pop(ticket, None)
            if buffers is None:
                raise ValueError("unknown or already-waited ticket %r"
                                 % (ticket,))
        # `buffers` pins (out, starts) until the native workers finish
        _check(self._lib.rnb_pool_wait(self._pool, ticket), path)
        del buffers

    def close(self) -> None:
        if self._pool:
            self._lib.rnb_pool_destroy(self._pool)
            self._pool = None


#: one logical decode fans out over the shared pool only past this many
#: clips — tiny requests aren't worth the submit/wait round trip
POOL_SPLIT_MIN_CLIPS = 4


class NativeY4MDecoder(VideoDecoder):
    """VideoDecoder backed by the C++ library.

    Despite the historical name this handles BOTH containers — the
    library sniffs y4m vs MJPEG from the magic bytes, so .mjpg files
    (self-contained baseline-JPEG decode, native/decode.cpp) ride the
    same entry points, pool and pixel formats.

    Single-clip requests decode synchronously on the calling thread;
    larger requests split their clip list into chunks fanned out over
    the process-shared :class:`DecodePool`, each chunk writing a
    disjoint slice of the one output batch — the intra-video
    parallelism NVVL got from async NVDEC (reference README.md:46-110).
    """

    def __init__(self, use_pool: bool = True):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native decode library not built; run "
                               "`make -C native`")
        self._lib = lib
        self._use_pool = use_pool and not os.environ.get(
            "RNB_DECODE_NO_POOL")
        self._count_cache = {}

    def num_frames(self, video: str) -> int:
        if video not in self._count_cache:
            n = ctypes.c_longlong()
            _check(self._lib.rnb_video_probe(video.encode(), None, None,
                                             ctypes.byref(n)), video)
            self._count_cache[video] = int(n.value)
        return self._count_cache[video]

    def _pool_fanout(self, video: str, clip_starts: List[int],
                     consecutive_frames: int, out: np.ndarray,
                     pixfmt: int, width: int, height: int) -> np.ndarray:
        """Split one logical decode into per-chunk pool tickets writing
        disjoint slices of ``out``; retire EVERY submitted ticket even
        if one fails — un-waited tickets would pin the batch buffer in
        _pending and leak done-map entries in the native pool."""
        pool = DecodePool.shared()
        chunk = max(1, -(-len(clip_starts) // pool.num_threads))
        tickets = []
        first_error = None
        try:
            for lo in range(0, len(clip_starts), chunk):
                hi = min(lo + chunk, len(clip_starts))
                tickets.append(pool.submit_into(
                    video, clip_starts[lo:hi], consecutive_frames,
                    out[lo:hi], pixfmt=pixfmt, width=width,
                    height=height))
        finally:
            for ticket in tickets:
                try:
                    pool.wait(ticket, video)
                except ValueError as e:
                    first_error = first_error or e
        if first_error is not None:
            raise first_error
        return out

    def decode_clips(self, video: str, clip_starts: List[int],
                     consecutive_frames: int = 8,
                     width: int = DEFAULT_WIDTH,
                     height: int = DEFAULT_HEIGHT) -> np.ndarray:
        out = np.empty((len(clip_starts), consecutive_frames, height,
                        width, 3), dtype=np.uint8)
        if self._use_pool and len(clip_starts) >= POOL_SPLIT_MIN_CLIPS:
            return self._pool_fanout(video, clip_starts,
                                     consecutive_frames, out, PIX_RGB,
                                     width, height)
        starts = (ctypes.c_longlong * len(clip_starts))(*clip_starts)
        _check(self._lib.rnb_y4m_decode_clips(
            video.encode(), starts, len(clip_starts), consecutive_frames,
            width, height, out.ctypes.data_as(ctypes.c_char_p)), video)
        return out

    def decode_clips_yuv(self, video: str, clip_starts: List[int],
                         consecutive_frames: int = 8,
                         width: int = DEFAULT_WIDTH,
                         height: int = DEFAULT_HEIGHT) -> np.ndarray:
        if width % 2 or height % 2:
            raise ValueError("packed 4:2:0 needs even geometry")
        out = np.empty((len(clip_starts), consecutive_frames,
                        height * width * 3 // 2), dtype=np.uint8)
        if self._use_pool and len(clip_starts) >= POOL_SPLIT_MIN_CLIPS:
            return self._pool_fanout(video, clip_starts,
                                     consecutive_frames, out,
                                     PIX_YUV420, width, height)
        starts = (ctypes.c_longlong * len(clip_starts))(*clip_starts)
        _check(self._lib.rnb_y4m_decode_clips_fmt(
            video.encode(), starts, len(clip_starts), consecutive_frames,
            width, height, PIX_YUV420,
            out.ctypes.data_as(ctypes.c_char_p)), video)
        return out

    def decode_clips_dct(self, video: str, clip_starts: List[int],
                         consecutive_frames: int = 8,
                         width: int = DEFAULT_WIDTH,
                         height: int = DEFAULT_HEIGHT,
                         coeffs=None) -> np.ndarray:
        """Packed dequantized-coefficient rows (rnb_tpu/ops/dct.py
        wire format) straight from the C++ entropy decoder — the
        per-pixel IDCT/convert work never runs on the host."""
        elems = dct_frame_elems(height, width, coeffs)
        out = np.empty((len(clip_starts), consecutive_frames, elems),
                       dtype=np.int16)
        if self._use_pool and len(clip_starts) >= POOL_SPLIT_MIN_CLIPS:
            return self._pool_fanout(video, clip_starts,
                                     consecutive_frames, out, PIX_DCT,
                                     width, height)
        starts = (ctypes.c_longlong * len(clip_starts))(*clip_starts)
        _check(self._lib.rnb_y4m_decode_clips_dct(
            video.encode(), starts, len(clip_starts),
            consecutive_frames, width, height,
            coeffs_from_elems(height, width, elems),
            out.ctypes.data_as(ctypes.c_void_p)), video)
        return out
