"""Host-side video decode: files (or synthetic ids) -> uint8 clip tensors.

TPUs have no hardware video decoder, so unlike the reference — whose
NVVL fork demuxed+NVDEC-decoded straight into GPU memory (SURVEY.md §2.2
N2, reference models/r2p1d/model.py:123-145) — decode is a host-CPU
stage here whose output feeds ``jax.device_put`` onto the stage's TPU
core. The contract mirrors RnBLoader's: give a decoder a video and a
list of clip start indices, get back a uint8 array of shape
``(num_clips, consecutive_frames, H, W, 3)``.

Backends:
  * :class:`SyntheticDecoder` — deterministic procedural frames keyed by
    video id; zero-dependency default for benchmarks/tests in
    environments with no video files or codecs.
  * :class:`Y4MDecoder` — real file decode of uncompressed YUV4MPEG2
    (.y4m) files: header parse, frame extraction, BT.601 YUV->RGB, box
    resize. Pure numpy here; the C++ worker-pool decoder in native/
    accelerates the same format.
  * ffmpeg CLI piping is intentionally absent — the binary does not
    exist in this image; the native decoder is the performance path.
"""

from __future__ import annotations

import os
import zlib
from typing import List, Optional

import numpy as np

from rnb_tpu.faults import CorruptVideoError

DEFAULT_WIDTH = 112
DEFAULT_HEIGHT = 112
SYNTH_PREFIX = "synth://"


class VideoDecoder:
    """Contract shared by all decode backends."""

    def num_frames(self, video: str) -> int:
        raise NotImplementedError

    def decode_clips(self, video: str, clip_starts: List[int],
                     consecutive_frames: int = 8,
                     width: int = DEFAULT_WIDTH,
                     height: int = DEFAULT_HEIGHT) -> np.ndarray:
        """-> uint8 (num_clips, consecutive_frames, height, width, 3)."""
        raise NotImplementedError

    def decode_clips_yuv(self, video: str, clip_starts: List[int],
                         consecutive_frames: int = 8,
                         width: int = DEFAULT_WIDTH,
                         height: int = DEFAULT_HEIGHT) -> np.ndarray:
        """-> uint8 (num_clips, consecutive_frames, H*W*3//2): packed
        output-resolution 4:2:0 planes (Y then U then V per frame) for
        the on-device colourspace path (rnb_tpu/ops/yuv.py). Geometry
        must be even."""
        raise NotImplementedError

    def decode_clips_dct(self, video: str, clip_starts: List[int],
                         consecutive_frames: int = 8,
                         width: int = DEFAULT_WIDTH,
                         height: int = DEFAULT_HEIGHT,
                         coeffs: Optional[int] = None) -> np.ndarray:
        """-> int16 (num_clips, consecutive_frames, elems): packed
        dequantized DCT coefficient rows (rnb_tpu/ops/dct.py wire
        format) for the DCT-domain ingest — the decode stops at
        entropy-decoded coefficients, IDCT/upsample/convert run
        on-device. MJPEG only; geometry must equal the source frame
        geometry and be divisible by 16. ``coeffs`` is the per-frame
        coefficient budget (None = the default half-of-yuv420 rule);
        a frame whose spectrum exceeds it raises a classified
        permanent error."""
        raise NotImplementedError


class SyntheticDecoder(VideoDecoder):
    """Procedural frames, deterministic per (video id, clip start).

    Frame count is derived from the id's CRC32 so the same id always
    yields the same "video". Frame pixels are PRNG noise — statistically
    as incompressible as real decoded video for downstream compute.
    """

    def __init__(self, min_frames: int = 128, max_frames: int = 360):
        self.min_frames = min_frames
        self.max_frames = max_frames

    def num_frames(self, video: str) -> int:
        h = zlib.crc32(("len:" + video).encode())
        return self.min_frames + h % (self.max_frames - self.min_frames + 1)

    def decode_clips(self, video, clip_starts, consecutive_frames=8,
                     width=DEFAULT_WIDTH, height=DEFAULT_HEIGHT):
        out = np.empty((len(clip_starts), consecutive_frames, height, width,
                        3), dtype=np.uint8)
        for i, start in enumerate(clip_starts):
            seed = zlib.crc32(("%s@%d" % (video, start)).encode())
            rng = np.random.default_rng(seed)
            out[i] = rng.integers(0, 256,
                                  (consecutive_frames, height, width, 3),
                                  dtype=np.uint8)
        return out

    def decode_clips_yuv(self, video, clip_starts, consecutive_frames=8,
                         width=DEFAULT_WIDTH, height=DEFAULT_HEIGHT):
        if width % 2 or height % 2:
            raise ValueError("packed 4:2:0 needs even geometry")
        packed = height * width * 3 // 2
        out = np.empty((len(clip_starts), consecutive_frames, packed),
                       dtype=np.uint8)
        for i, start in enumerate(clip_starts):
            # distinct PRNG stream from the rgb path (different label)
            seed = zlib.crc32(("yuv:%s@%d" % (video, start)).encode())
            rng = np.random.default_rng(seed)
            out[i] = rng.integers(0, 256, (consecutive_frames, packed),
                                  dtype=np.uint8)
        return out

    def decode_clips_dct(self, video, clip_starts, consecutive_frames=8,
                         width=DEFAULT_WIDTH, height=DEFAULT_HEIGHT,
                         coeffs=None):
        """Procedural sparse coefficient rows: a small per-block
        zigzag-prefix spectrum — statistically like real quantized
        video (energy in the first few frequencies) and always within
        the wire budget, so synthetic benchmark arms exercise the real
        unpack/IDCT compute path."""
        from rnb_tpu.ops.dct import (dct_frame_elems, num_dct_blocks)
        nb = num_dct_blocks(height, width)
        elems = dct_frame_elems(height, width, coeffs)
        budget = (elems - nb) // 2
        if budget < nb:
            raise ValueError(
                "dct coefficient budget %d below one coefficient per "
                "block (%d)" % (budget, nb))
        kmax = min(6, budget // nb)
        out = np.zeros((len(clip_starts), consecutive_frames, elems),
                       dtype=np.int16)
        for i, start in enumerate(clip_starts):
            seed = zlib.crc32(("dct:%s@%d" % (video, start)).encode())
            rng = np.random.default_rng(seed)
            for fi in range(consecutive_frames):
                counts = rng.integers(1, kmax + 1, nb)
                total = int(counts.sum())
                mags = rng.integers(1, 480, total)
                signs = rng.integers(0, 2, total) * 2 - 1
                # zigzag-prefix positions: 0..counts[b]-1 per block
                cum = np.concatenate(([0], np.cumsum(counts)[:-1]))
                poss = np.arange(total) - np.repeat(cum, counts)
                row = out[i, fi]
                row[:nb] = counts.astype(np.int16)
                row[nb:nb + total] = (mags * signs).astype(np.int16)
                row[nb + budget:nb + budget + total] = \
                    poss.astype(np.int16)
        return out


class Y4MDecoder(VideoDecoder):
    """Uncompressed YUV4MPEG2 (.y4m) file decode, 4:2:0 or 4:4:4.

    Parses the stream header (W/H/colourspace), seeks frame payloads,
    upsamples chroma, converts BT.601 full-range YUV->RGB, and
    box-resizes to the requested geometry.
    """

    def __init__(self):
        self._meta = {}

    def _parse_header(self, video: str):
        if video in self._meta:
            return self._meta[video]
        with open(video, "rb") as f:
            header = f.readline()
        if not header.startswith(b"YUV4MPEG2"):
            raise CorruptVideoError("%s is not a y4m file" % video)
        width = height = None
        cs = "420"
        for token in header.split()[1:]:
            tag, val = token[:1], token[1:]
            if tag == b"W":
                width = int(val)
            elif tag == b"H":
                height = int(val)
            elif tag == b"C":
                cs = val.decode()
        if not width or not height:
            raise CorruptVideoError(
                "y4m header of %s lacks geometry" % video)
        if cs.startswith("420"):
            frame_bytes = width * height * 3 // 2
            subsample = 2
        elif cs.startswith("444"):
            frame_bytes = width * height * 3
            subsample = 1
        else:
            raise CorruptVideoError(
                "unsupported y4m colourspace %s" % cs)
        data_start = len(header)
        size = os.path.getsize(video)
        # each frame: b"FRAME...\n" marker + payload
        with open(video, "rb") as f:
            f.seek(data_start)
            marker = f.readline()
        if not marker.startswith(b"FRAME"):
            raise CorruptVideoError("missing FRAME marker in %s" % video)
        stride = len(marker) + frame_bytes
        count = (size - data_start) // stride
        meta = dict(width=width, height=height, subsample=subsample,
                    frame_bytes=frame_bytes, data_start=data_start,
                    marker_len=len(marker), stride=stride, count=count)
        self._meta[video] = meta
        return meta

    def num_frames(self, video: str) -> int:
        return self._parse_header(video)["count"]

    def _read_frame(self, f, meta) -> np.ndarray:
        w, h, sub = meta["width"], meta["height"], meta["subsample"]
        payload = f.read(meta["frame_bytes"])
        if len(payload) < meta["frame_bytes"]:
            # a file truncated mid-frame must surface as a classified
            # per-request error, not numpy's bare buffer ValueError
            raise CorruptVideoError(
                "truncated y4m frame payload (%d of %d bytes)"
                % (len(payload), meta["frame_bytes"]))
        y = np.frombuffer(payload, np.uint8, w * h).reshape(h, w)
        cw, ch = w // sub, h // sub
        u = np.frombuffer(payload, np.uint8, cw * ch,
                          offset=w * h).reshape(ch, cw)
        v = np.frombuffer(payload, np.uint8, cw * ch,
                          offset=w * h + cw * ch).reshape(ch, cw)
        if sub > 1:
            u = u.repeat(sub, axis=0).repeat(sub, axis=1)
            v = v.repeat(sub, axis=0).repeat(sub, axis=1)
        yf = y.astype(np.float32)
        uf = u.astype(np.float32) - 128.0
        vf = v.astype(np.float32) - 128.0
        rgb = np.stack([
            yf + 1.402 * vf,
            yf - 0.344136 * uf - 0.714136 * vf,
            yf + 1.772 * uf,
        ], axis=-1)
        return np.clip(rgb, 0.0, 255.0).astype(np.uint8)

    @staticmethod
    def _box_resize(frame: np.ndarray, width: int, height: int
                    ) -> np.ndarray:
        h, w = frame.shape[:2]
        if (h, w) == (height, width):
            return frame
        rows = (np.arange(height) * h // height)
        cols = (np.arange(width) * w // width)
        return frame[rows][:, cols]

    def decode_clips(self, video, clip_starts, consecutive_frames=8,
                     width=DEFAULT_WIDTH, height=DEFAULT_HEIGHT):
        meta = self._parse_header(video)
        if any(s < 0 for s in clip_starts):
            raise ValueError("negative clip start in %r" % (clip_starts,))
        out = np.empty((len(clip_starts), consecutive_frames, height, width,
                        3), dtype=np.uint8)
        with open(video, "rb") as f:
            for ci, start in enumerate(clip_starts):
                for fi in range(consecutive_frames):
                    idx = min(start + fi, meta["count"] - 1)
                    f.seek(meta["data_start"] + idx * meta["stride"]
                           + meta["marker_len"])
                    frame = self._read_frame(f, meta)
                    out[ci, fi] = self._box_resize(frame, width, height)
        return out

    @staticmethod
    def _gather_frame_yuv(payload, meta, maps) -> np.ndarray:
        """One frame payload -> packed output-res 4:2:0 planes.

        Pure gathers, no float math: luma uses the rgb path's exact
        nearest index map; chroma keeps its own nearest map at half
        output resolution (rnb_tpu/ops/yuv.py docstring). Mirrors the
        native GatherFrameYUV bit-exactly (native/decode.cpp).
        """
        w, h, sub = meta["width"], meta["height"], meta["subsample"]
        cw, ch = w // sub, h // sub
        rows, cols, crows, ccols = maps
        if len(payload) < meta["frame_bytes"]:
            raise CorruptVideoError(
                "truncated y4m frame payload (%d of %d bytes)"
                % (len(payload), meta["frame_bytes"]))
        y = np.frombuffer(payload, np.uint8, w * h).reshape(h, w)
        u = np.frombuffer(payload, np.uint8, cw * ch,
                          offset=w * h).reshape(ch, cw)
        v = np.frombuffer(payload, np.uint8, cw * ch,
                          offset=w * h + cw * ch).reshape(ch, cw)
        return np.concatenate([
            y[rows][:, cols].ravel(),
            u[crows][:, ccols].ravel(),
            v[crows][:, ccols].ravel(),
        ])

    def decode_clips_yuv(self, video, clip_starts, consecutive_frames=8,
                         width=DEFAULT_WIDTH, height=DEFAULT_HEIGHT):
        if width % 2 or height % 2:
            raise ValueError("packed 4:2:0 needs even geometry")
        meta = self._parse_header(video)
        if any(s < 0 for s in clip_starts):
            raise ValueError("negative clip start in %r" % (clip_starts,))
        packed = height * width * 3 // 2
        out = np.empty((len(clip_starts), consecutive_frames, packed),
                       dtype=np.uint8)
        # the index maps are invariant per (geometry) — hoisted out of
        # the frame loop, as in the native decoder
        w, h, sub = meta["width"], meta["height"], meta["subsample"]
        maps = (np.arange(height) * h // height,
                np.arange(width) * w // width,
                np.arange(height // 2) * (h // sub) // (height // 2),
                np.arange(width // 2) * (w // sub) // (width // 2))
        with open(video, "rb") as f:
            for ci, start in enumerate(clip_starts):
                for fi in range(consecutive_frames):
                    idx = min(start + fi, meta["count"] - 1)
                    f.seek(meta["data_start"] + idx * meta["stride"]
                           + meta["marker_len"])
                    out[ci, fi] = self._gather_frame_yuv(
                        f.read(meta["frame_bytes"]), meta, maps)
        return out

    def decode_clips_dct(self, video, clip_starts, consecutive_frames=8,
                         width=DEFAULT_WIDTH, height=DEFAULT_HEIGHT,
                         coeffs=None):
        # classified permanent: an uncompressed container carries no
        # DCT coefficients to stop at — the request dead-letters under
        # containment instead of taking the run down
        raise CorruptVideoError(
            "the dct pixel path needs an MJPEG container; %s is "
            "uncompressed y4m (no DCT coefficients to ship)" % video)


def write_y4m(path: str, frames: np.ndarray,
              colorspace: str = "444") -> None:
    """Write (N, H, W, 3) uint8 RGB frames as a y4m file (RGB stored
    via inverse BT.601) — used by tests and data generators.

    ``colorspace="420"`` downsamples chroma with a 2x2 box mean
    (geometry must be even) — the colourspace virtually all real video
    ships in, and half the bytes per frame of 4:4:4, which matters
    because uncompressed-read bandwidth stands in for the codec here.
    """
    n, h, w, _ = frames.shape
    rgb = frames.astype(np.float32)
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    y = 0.299 * r + 0.587 * g + 0.114 * b
    u = (b - y) / 1.772 + 128.0
    v = (r - y) / 1.402 + 128.0
    if colorspace == "420":
        if h % 2 or w % 2:
            raise ValueError("4:2:0 needs even geometry, got %dx%d"
                             % (h, w))
        u = u.reshape(n, h // 2, 2, w // 2, 2).mean(axis=(2, 4))
        v = v.reshape(n, h // 2, 2, w // 2, 2).mean(axis=(2, 4))
    elif colorspace != "444":
        raise ValueError("colorspace must be '444' or '420', got %r"
                         % (colorspace,))
    with open(path, "wb") as f:
        f.write(b"YUV4MPEG2 W%d H%d F25:1 Ip A1:1 C%s\n"
                % (w, h, colorspace.encode()))
        for i in range(n):
            f.write(b"FRAME\n")
            for plane in (y[i], u[i], v[i]):
                f.write(np.clip(plane, 0, 255).astype(np.uint8).tobytes())


def _jpeg_frame_end(data: bytes, p: int) -> int:
    """-> offset one past the frame's EOI, or 0 on corrupt/truncated
    structure. ``data[p:]`` must start at an SOI."""
    n = len(data)
    p += 2  # SOI
    while p + 1 < n:
        if data[p] != 0xFF:
            return 0
        while p < n and data[p] == 0xFF:
            p += 1  # fill bytes
        if p >= n:
            return 0
        m = data[p]
        p += 1
        if m == 0xD9:
            return p  # EOI
        if m == 0x01 or 0xD0 <= m <= 0xD7:
            continue  # TEM / RSTn: no length field
        if p + 2 > n:
            return 0
        length = (data[p] << 8) | data[p + 1]
        if length < 2 or p + length > n:
            return 0
        is_sos = m == 0xDA
        p += length
        if is_sos:
            # entropy-coded data: only here is FFD9 unambiguous
            while True:
                q = data.find(b"\xff", p)
                if q < 0 or q + 1 >= n:
                    return 0
                nm = data[q + 1]
                if nm == 0x00 or 0xD0 <= nm <= 0xD7:
                    p = q + 2  # stuffing / restart
                elif nm == 0xFF:
                    p = q + 1  # fill byte
                else:
                    p = q
                    break  # real marker: handled by the loop top
    return 0


def scan_mjpeg_frames(data: bytes):
    """-> [(offset, length)] of the JPEG frames in an MJPEG byte
    stream. Walks the marker structure: length-prefixed segments are
    skipped whole (an APPn/EXIF payload may legally embed a
    thumbnail's FFD9, so a raw byte scan would split mid-frame).
    Shared logic with the native scanner (native/decode.cpp
    JpegFrameEnd/ScanMjpeg)."""
    frames = []
    p = 0
    n = len(data)
    while p + 2 < n:
        if data[p] == 0xFF and data[p + 1] == 0xD8 and data[p + 2] == 0xFF:
            end = _jpeg_frame_end(data, p)
            if not end:
                break  # truncated trailing frame: drop it
            frames.append((p, end - p))
            p = end
        else:
            p += 1
    return frames


class MjpegPILDecoder(VideoDecoder):
    """Fallback MJPEG backend on PIL/libjpeg (no native library).

    The performance path is the self-contained baseline-JPEG decoder in
    native/decode.cpp; this fallback keeps the contract alive without
    the build, and doubles as the *independent decode oracle* the
    parity tests compare the native decoder against. Numerics caveat:
    libjpeg upsamples chroma with a triangle filter ("fancy
    upsampling") while the native path keeps nearest semantics, so RGB
    output matches the native backend only within a few LSB on smooth
    content — the tests bound this, they do not assert bit-equality.
    """

    def __init__(self):
        # frame index only — caching raw bytes per video would grow
        # without bound over a many-video run (the native cache keeps
        # offsets only for the same reason); bytes are re-read per
        # decode call
        self._index = {}

    def _frames(self, video: str):
        """-> (file bytes, [(offset, length)]); only the index is
        cached."""
        with open(video, "rb") as f:
            data = f.read()
        if video not in self._index:
            frames = scan_mjpeg_frames(data)
            if not frames:
                raise CorruptVideoError(
                    "%s contains no JPEG frames" % video)
            self._index[video] = frames
        return data, self._index[video]

    def num_frames(self, video: str) -> int:
        return len(self._frames(video)[1])

    def decode_clips(self, video, clip_starts, consecutive_frames=8,
                     width=DEFAULT_WIDTH, height=DEFAULT_HEIGHT):
        import io

        from PIL import Image
        data, frames = self._frames(video)
        count = len(frames)
        if any(s < 0 for s in clip_starts):
            raise ValueError("negative clip start in %r" % (clip_starts,))
        out = np.empty((len(clip_starts), consecutive_frames, height,
                        width, 3), dtype=np.uint8)
        for ci, start in enumerate(clip_starts):
            for fi in range(consecutive_frames):
                off, length = frames[min(start + fi, count - 1)]
                try:
                    with Image.open(io.BytesIO(
                            data[off:off + length])) as im:
                        frame = np.asarray(im.convert("RGB"))
                except (OSError, SyntaxError, ValueError) as e:
                    # libjpeg's truncation/corruption errors, classified
                    raise CorruptVideoError(
                        "%s frame %d: %s" % (video, start + fi, e)) from e
                out[ci, fi] = Y4MDecoder._box_resize(frame, width, height)
        return out

    def decode_clips_yuv(self, video, clip_starts, consecutive_frames=8,
                         width=DEFAULT_WIDTH, height=DEFAULT_HEIGHT):
        """Packed 4:2:0 via PIL's YCbCr draft decode. libjpeg hands
        back chroma already upsampled to full resolution, so the half
        resolution planes are re-sampled from it (phase-aligned with
        the native gather's nearest map) — approximate by a few LSB
        where the native path reads the stored chroma sample."""
        import io

        from PIL import Image
        if width % 2 or height % 2:
            raise ValueError("packed 4:2:0 needs even geometry")
        data, frames = self._frames(video)
        count = len(frames)
        if any(s < 0 for s in clip_starts):
            raise ValueError("negative clip start in %r" % (clip_starts,))
        packed = height * width * 3 // 2
        out = np.empty((len(clip_starts), consecutive_frames, packed),
                       dtype=np.uint8)
        maps = None  # index maps are geometry-invariant: built once
        for ci, start in enumerate(clip_starts):
            for fi in range(consecutive_frames):
                off, length = frames[min(start + fi, count - 1)]
                try:
                    with Image.open(io.BytesIO(
                            data[off:off + length])) as im:
                        im.draft("YCbCr", im.size)
                        ycc = np.asarray(im.convert("YCbCr"))
                except (OSError, SyntaxError, ValueError) as e:
                    raise CorruptVideoError(
                        "%s frame %d: %s" % (video, start + fi, e)) from e
                if maps is None or maps[0] != ycc.shape[:2]:
                    # maps are per-geometry; frames from external
                    # encoders may legally vary in size mid-file
                    h, w = ycc.shape[:2]
                    maps = ((h, w),
                            np.arange(height) * h // height,
                            np.arange(width) * w // width,
                            np.arange(height // 2) * (h // 2)
                            // (height // 2) * 2,
                            np.arange(width // 2) * (w // 2)
                            // (width // 2) * 2)
                _geom, rows, cols, crows, ccols = maps
                y = ycc[rows][:, cols, 0]
                u = ycc[crows][:, ccols, 1]
                v = ycc[crows][:, ccols, 2]
                out[ci, fi] = np.concatenate(
                    [y.ravel(), u.ravel(), v.ravel()])
        return out

    def decode_clips_dct(self, video, clip_starts, consecutive_frames=8,
                         width=DEFAULT_WIDTH, height=DEFAULT_HEIGHT,
                         coeffs=None):
        """Packed dequantized coefficients via the pure-Python
        entropy decoder (rnb_tpu/decode/jpeg_dct.py) — PIL/libjpeg
        never exposes coefficients, so this backend IS the
        independent oracle the native decoder is parity-tested
        against. Clamp-past-end and repeat-frame semantics match the
        pixel paths."""
        from rnb_tpu.decode.jpeg_dct import jpeg_frame_dct
        from rnb_tpu.ops.dct import dct_frame_elems, pack_frame_dct
        elems = dct_frame_elems(height, width, coeffs)
        data, frames = self._frames(video)
        count = len(frames)
        if any(s < 0 for s in clip_starts):
            raise ValueError("negative clip start in %r" % (clip_starts,))
        out = np.zeros((len(clip_starts), consecutive_frames, elems),
                       dtype=np.int16)
        last_idx = None
        last_row = None
        for ci, start in enumerate(clip_starts):
            for fi in range(consecutive_frames):
                idx = min(start + fi, count - 1)
                if idx != last_idx:
                    off, length = frames[idx]
                    zz, w, h = jpeg_frame_dct(data[off:off + length])
                    if (w, h) != (width, height):
                        # no resize exists in the coefficient domain:
                        # the source geometry must BE the requested one
                        raise CorruptVideoError(
                            "%s is %dx%d but the dct path was asked "
                            "for %dx%d — coefficients cannot be "
                            "resized on the host" % (video, w, h,
                                                     width, height))
                    try:
                        last_row = pack_frame_dct(zz, height, width,
                                                  coeffs)
                    except ValueError as e:
                        # over-budget spectrum: re-decoding cannot
                        # shrink it — classified permanent
                        raise CorruptVideoError(
                            "%s frame %d: %s" % (video, idx, e)) from e
                    last_idx = idx
                out[ci, fi] = last_row
        return out


def write_mjpeg(path: str, frames: np.ndarray, quality: int = 90) -> None:
    """Write (N, H, W, 3) uint8 RGB frames as an MJPEG file: baseline
    JPEG frames (4:2:0, via PIL/libjpeg) concatenated back to back —
    the compressed counterpart of :func:`write_y4m`, giving the decode
    stage real entropy-decode + IDCT work per frame (the reference's
    NVVL decoded real compressed video, README.md:42-110)."""
    import io

    from PIL import Image
    n, h, w, _ = frames.shape
    if h % 2 or w % 2:
        raise ValueError("4:2:0 JPEG needs even geometry, got %dx%d"
                         % (h, w))
    with open(path, "wb") as f:
        for i in range(n):
            buf = io.BytesIO()
            Image.fromarray(frames[i], "RGB").save(
                buf, "JPEG", quality=quality, subsampling=2)  # 4:2:0
            f.write(buf.getvalue())


#: backend instances are shared per process: get_decoder runs once per
#: request, and a fresh instance each time would defeat every decoder's
#: per-video metadata cache (header/frame-index parses would repeat on
#: each request). The caches inside are per-video metadata only.
_DECODER_CACHE: dict = {}


def get_decoder(video: str) -> VideoDecoder:
    """Pick a backend for one video path/id (instances shared
    per-process).

    .y4m and .mjpg/.mjpeg files prefer the native C++ worker-pool
    decoder when built (``make -C native``; disable with
    RNB_DISABLE_NATIVE=1), falling back to the numpy y4m backend with
    identical numerics / the PIL-based MJPEG backend.
    """
    if video.startswith(SYNTH_PREFIX) or not os.path.exists(video):
        key = "synth"
    elif video.endswith((".y4m", ".mjpg", ".mjpeg")):
        from rnb_tpu.decode.native import native_available
        if native_available():
            key = "native"
        else:
            key = "y4m" if video.endswith(".y4m") else "mjpeg-pil"
    else:
        # classified permanent: the request can never decode, but it
        # must not take the whole run down under containment
        raise CorruptVideoError(
            "no decode backend for %r: only synth:// ids, .y4m and "
            ".mjpg/.mjpeg files are supported" % video)
    dec = _DECODER_CACHE.get(key)
    if dec is None:
        if key == "synth":
            dec = SyntheticDecoder()
        elif key == "native":
            from rnb_tpu.decode.native import NativeY4MDecoder
            dec = NativeY4MDecoder()
        elif key == "y4m":
            dec = Y4MDecoder()
        else:
            dec = MjpegPILDecoder()
        _DECODER_CACHE[key] = dec
    return dec
