"""Pure-Python baseline-JPEG coefficient decoder (the dct-path
fallback oracle).

The performance path for ``pixel_path: "dct"`` is the native C++
decoder (native/decode.cpp), which stops the MJPEG decode at
entropy-decoded, dequantized 8x8 DCT coefficients. This module is its
*independent* Python twin: a from-the-spec (ITU T.81 sequential DCT,
8-bit, Huffman) entropy decoder that produces the SAME dequantized
coefficients — it keeps the contract alive where the native library is
not built (PIL cannot help here: libjpeg never exposes coefficients
through PIL), and doubles as the parity oracle the native decoder is
tested against bit-for-bit (tests/test_dct.py).

Scope matches the dct wire format (rnb_tpu/ops/dct.py): 3-component
4:2:0 (2x2, 1x1, 1x1) sampling, geometry divisible by 16 (whole MCUs),
restart markers supported. Anything else — progressive, 4:4:4, 12-bit,
partial-MCU geometry — raises a *classified permanent*
:class:`~rnb_tpu.faults.CorruptVideoError`: re-decoding cannot change
the stream, and under containment the request dead-letters instead of
killing the run.

Output block order is plane-major (Y blocks in raster order, then U,
then V), zigzag scan order within each block — exactly what
``rnb_tpu.ops.dct.pack_frame_dct`` packs and the native decoder emits.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from rnb_tpu.faults import CorruptVideoError


class _Huff:
    """Canonical Huffman decode tables per ITU T.81 F.2.2.3."""

    __slots__ = ("mincode", "maxcode", "valptr", "values")

    def __init__(self, counts, values):
        self.mincode = [0] * 17
        self.maxcode = [-1] * 17
        self.valptr = [0] * 17
        self.values = values
        code = 0
        k = 0
        for length in range(1, 17):
            self.valptr[length] = k
            self.mincode[length] = code
            n = counts[length - 1]
            code += n
            k += n
            self.maxcode[length] = code - 1 if n else -1
            code <<= 1


class _BitReader:
    """MSB-first bit reader over entropy-coded data with 0xFF00
    stuffing; a real marker ends the stream (zero bits synthesize past
    it, matching the native BitReader's starved behavior)."""

    __slots__ = ("d", "n", "pos", "acc", "count")

    def __init__(self, data: bytes, pos: int):
        self.d = data
        self.n = len(data)
        self.pos = pos
        self.acc = 0
        self.count = 0

    def _fill(self) -> None:
        while self.count <= 24:
            b = 0
            if self.pos < self.n:
                b = self.d[self.pos]
                if b == 0xFF:
                    if self.pos + 1 < self.n \
                            and self.d[self.pos + 1] == 0x00:
                        self.pos += 2
                    else:
                        b = 0  # real marker: stop consuming
                else:
                    self.pos += 1
            self.acc = ((self.acc << 8) | b) & 0xFFFFFFFFFF
            self.count += 8

    def get(self, nbits: int) -> int:
        if nbits == 0:
            return 0
        if self.count < nbits:
            self._fill()
        self.count -= nbits
        return (self.acc >> self.count) & ((1 << nbits) - 1)

    def consume_restart(self) -> bool:
        self.count = 0
        self.acc = 0
        if self.pos + 1 >= self.n or self.d[self.pos] != 0xFF:
            return False
        m = self.d[self.pos + 1]
        if m < 0xD0 or m > 0xD7:
            return False
        self.pos += 2
        return True

    def decode(self, table: _Huff) -> int:
        code = self.get(1)
        for length in range(1, 17):
            if table.maxcode[length] >= 0 \
                    and table.mincode[length] <= code \
                    <= table.maxcode[length]:
                return table.values[table.valptr[length]
                                    + code - table.mincode[length]]
            code = (code << 1) | self.get(1)
        raise CorruptVideoError("invalid Huffman code in scan data")


def _extend(v: int, s: int) -> int:
    return v - (1 << s) + 1 if s and v < (1 << (s - 1)) else v


def jpeg_frame_dct(data: bytes) -> Tuple[np.ndarray, int, int]:
    """One baseline JPEG -> ``(zz, width, height)`` where ``zz`` is
    ``(num_blocks, 64)`` int16 dequantized coefficients, plane-major
    block order, zigzag within a block (see module docstring for the
    supported stream shape)."""
    n = len(data)
    if n < 4 or data[0] != 0xFF or data[1] != 0xD8:
        raise CorruptVideoError("not a JPEG stream (no SOI)")
    qt: Dict[int, np.ndarray] = {}
    hdc: Dict[int, _Huff] = {}
    hac: Dict[int, _Huff] = {}
    comps = []  # (id, h, v, tq); td/ta filled at SOS
    w = h = 0
    restart_interval = 0
    p = 2
    scan_start = None
    while scan_start is None:
        while p < n and data[p] != 0xFF:
            p += 1
        while p < n and data[p] == 0xFF:
            p += 1
        if p >= n:
            raise CorruptVideoError("truncated JPEG (no SOS)")
        m = data[p]
        p += 1
        if m == 0xD9:
            raise CorruptVideoError("EOI before SOS")
        if 0xD0 <= m <= 0xD7 or m == 0x01:
            continue
        if p + 2 > n:
            raise CorruptVideoError("truncated JPEG segment")
        seg_len = (data[p] << 8) | data[p + 1]
        if seg_len < 2 or p + seg_len > n:
            raise CorruptVideoError("bad JPEG segment length")
        seg = data[p + 2:p + seg_len]
        if m == 0xDB:  # DQT
            q = 0
            while q < len(seg):
                pq, tq = seg[q] >> 4, seg[q] & 15
                q += 1
                need = 128 if pq else 64
                if q + need > len(seg):
                    raise CorruptVideoError("truncated DQT")
                if pq:
                    table = np.frombuffer(
                        seg[q:q + 128], ">u2").astype(np.int32)
                else:
                    table = np.frombuffer(
                        seg[q:q + 64], np.uint8).astype(np.int32)
                qt[tq] = table
                q += need
        elif m == 0xC4:  # DHT
            q = 0
            while q + 17 <= len(seg):
                tc, th = seg[q] >> 4, seg[q] & 15
                counts = list(seg[q + 1:q + 17])
                nvals = sum(counts)
                if q + 17 + nvals > len(seg):
                    raise CorruptVideoError("truncated DHT")
                values = list(seg[q + 17:q + 17 + nvals])
                (hac if tc else hdc)[th] = _Huff(counts, values)
                q += 17 + nvals
        elif m in (0xC0, 0xC1):  # baseline / extended sequential SOF
            if len(seg) < 6 or seg[0] != 8:
                raise CorruptVideoError("only 8-bit baseline JPEG is "
                                        "supported on the dct path")
            h = (seg[1] << 8) | seg[2]
            w = (seg[3] << 8) | seg[4]
            ncomp = seg[5]
            if ncomp != 3 or len(seg) < 6 + 3 * ncomp:
                raise CorruptVideoError("dct path needs 3-component "
                                        "YCbCr JPEG")
            for c in range(ncomp):
                comps.append({
                    "id": seg[6 + c * 3],
                    "h": seg[7 + c * 3] >> 4,
                    "v": seg[7 + c * 3] & 15,
                    "tq": seg[8 + c * 3],
                })
        elif m == 0xC2:
            raise CorruptVideoError("progressive JPEG unsupported on "
                                    "the dct path")
        elif m == 0xDD:  # DRI
            if len(seg) < 2:
                raise CorruptVideoError("truncated DRI")
            restart_interval = (seg[0] << 8) | seg[1]
        elif m == 0xDA:  # SOS
            if not comps:
                raise CorruptVideoError("SOS before SOF")
            ns = seg[0] if seg else 0
            if ns != len(comps) or len(seg) < 1 + 2 * ns + 3:
                raise CorruptVideoError("bad SOS header")
            for s in range(ns):
                cs = seg[1 + s * 2]
                for comp in comps:
                    if comp["id"] == cs:
                        comp["td"] = seg[2 + s * 2] >> 4
                        comp["ta"] = seg[2 + s * 2] & 15
            scan_start = p + seg_len
        p += seg_len
    if (comps[0]["h"], comps[0]["v"]) != (2, 2) or any(
            (c["h"], c["v"]) != (1, 1) for c in comps[1:]):
        raise CorruptVideoError(
            "dct path supports 4:2:0 (2x2,1x1,1x1) sampling only")
    if w % 16 or h % 16:
        raise CorruptVideoError(
            "dct path needs geometry divisible by 16 (whole MCUs), "
            "got %dx%d" % (w, h))
    for comp in comps:
        if comp["tq"] not in qt or comp.get("td") not in hdc \
                or comp.get("ta") not in hac:
            raise CorruptVideoError("missing quant/Huffman table")

    mcus_x, mcus_y = w // 16, h // 16
    yw = w // 8
    ny = (h // 8) * yw
    nc = mcus_x * mcus_y
    zz = np.zeros((ny + 2 * nc, 64), dtype=np.int16)
    plane_base = [0, ny, ny + nc]

    br = _BitReader(data, scan_start)
    dc_pred = [0, 0, 0]
    mcus_until_restart = restart_interval
    for my in range(mcus_y):
        for mx in range(mcus_x):
            if restart_interval and mcus_until_restart == 0:
                if not br.consume_restart():
                    raise CorruptVideoError("missing restart marker")
                dc_pred = [0, 0, 0]
                mcus_until_restart = restart_interval
            if restart_interval:
                mcus_until_restart -= 1
            for ci, comp in enumerate(comps):
                q = qt[comp["tq"]]
                dc_t = hdc[comp["td"]]
                ac_t = hac[comp["ta"]]
                for by in range(comp["v"]):
                    for bx in range(comp["h"]):
                        if ci == 0:
                            bidx = (my * 2 + by) * yw + mx * 2 + bx
                        else:
                            bidx = plane_base[ci] + my * mcus_x + mx
                        t = br.decode(dc_t)
                        if t > 11:
                            raise CorruptVideoError("bad DC category")
                        dc_pred[ci] += _extend(br.get(t), t)
                        row = zz[bidx]
                        row[0] = np.clip(dc_pred[ci] * int(q[0]),
                                         -32768, 32767)
                        k = 1
                        while k < 64:
                            rs = br.decode(ac_t)
                            s = rs & 15
                            if s:
                                k += rs >> 4
                                if k > 63:
                                    raise CorruptVideoError(
                                        "AC index overrun")
                                row[k] = np.clip(
                                    _extend(br.get(s), s) * int(q[k]),
                                    -32768, 32767)
                                k += 1
                            elif (rs >> 4) == 15:
                                k += 16  # ZRL
                            else:
                                break  # EOB
    return zz, w, h
