"""Device-resident inter-stage handoff: the edge contract.

Until PR 9 every inter-stage tensor edge had ONE implicit shape: the
producer synced its device output (``sync_outputs``), parked the
arrays in a ring slot, and the consumer's stage model re-homed them
with its own ``jax.device_put`` — correct, but invisible: nothing
said whether a given edge actually moved bytes device-to-device or
bounced them through host memory, and nothing *enforced* either. This
module makes the edge an explicit, accounted contract the executor
applies when the config's root ``handoff`` key is present:

* ``mode: "device"`` — **device-resident**: the queue/ring carries
  committed on-device ``jax.Array`` values by reference. A payload
  already homed on the consumer's device is adopted as-is (zero-copy
  take, no transfer, no host bounce); a payload on a *different*
  device of the host's mesh is re-homed with an on-device resharding
  (``jax.device_put`` onto the consumer's device or — for stages that
  declare a :meth:`StageModel` ``input_sharding()`` — its
  ``NamedSharding``), with a Pallas ``make_async_remote_copy`` fast
  path gated to real TPU hardware and a ``shard_map``/``ppermute``
  CPU-testable twin (:mod:`rnb_tpu.ops.handoff_dma`). The host is
  never materialized; rnb-lint RNB-H008 rejects any
  ``device_get``/``np.asarray`` creeping into this path statically.
* ``mode: "host"`` — the explicit host round trip (device →
  ``np.asarray`` → ``device_put``), kept as the measurable A/B
  baseline arm and for backends whose D2D path is broken. Every byte
  it moves is counted, so "the device-resident edge moved zero host
  bytes" is a provable log statement, not an assertion.
* no ``handoff`` key — exactly the pre-PR behavior: the stage model's
  own ``device_put`` re-homes, no accounting, logs stay byte-stable.

Ownership (donation safety, mirroring the staging-slot lifecycle in
:mod:`rnb_tpu.staging`): the producer *commits* a payload by writing
it to the ring slot — from that instant it must neither mutate nor
donate the arrays (``jax.Array`` immutability gives the former; the
publish path never passes arrays to a donating jit, which gives the
latter). The consumer's take is the ownership transfer: an adopted
same-device array is owned jointly (both sides may read, neither may
donate it to a jit — exactly like a cached ClipCache value), while a
resharded take produces a fresh consumer-owned array and the
producer's copy dies with the ring-slot release. A stage that wants
to donate its input into its jit must therefore run under
``mode: "host"`` or make its own defensive copy — the contract trades
that freedom for the removed transfer.

Accounting (the ``Handoff:`` log-meta line, ``handoff_*``
BenchmarkResult fields, ``parse_utils --check`` invariants): every
consumer-side take of a tensor payload is one *edge event*, classified
``d2d`` (adopted or device-to-device resharded) or ``host`` (bounced
through numpy), with the payload bytes attributed to the class that
moved them — adopted same-device takes move zero bytes and count 0.
``d2d_edges + host_edges == edges`` always; a device-resident config
must report ``host_bytes == 0``.

Precisely: ``host`` counts takes where the edge *materialized a
device payload on the host* — the avoidable bounce this contract
exists to delete. A payload a producer publishes as host memory in
the first place (a numpy-emitting stage) has no host hop for the
edge to add or avoid; its one unavoidable upload counts under
``d2d_bytes`` (bytes the edge moved onto the device), so the
``host_bytes == 0`` promise reads "this edge added zero host
round-trips", not "no producer ever touched host memory".
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from rnb_tpu.ops.handoff_dma import reshard
from rnb_tpu.utils.lazy_jax import jax_numpy as _jax_numpy

#: modes the root ``handoff`` config key accepts
HANDOFF_MODES = ("device", "host")


class HandoffSettings:
    """Validated, defaulted view of the ``handoff`` root config key."""

    def __init__(self, mode: str):
        if mode not in HANDOFF_MODES:
            raise ValueError("handoff mode must be one of %s, got %r"
                             % (list(HANDOFF_MODES), mode))
        self.mode = mode

    @staticmethod
    def from_config(raw: Optional[dict]) -> Optional["HandoffSettings"]:
        """Settings from the (schema-validated) config dict, or None
        when the key is absent or ``enabled`` is false — absent means
        the pre-handoff edge semantics, byte-stable logs included."""
        if not raw or not raw.get("enabled", True):
            return None
        return HandoffSettings(raw.get("mode", "device"))


class EdgeHandoff:
    """One consumer stage instance's side of the edge contract.

    Built by the stage executor (rnb_tpu.runner) after stage
    construction — the stage may refine the re-home target via an
    ``input_sharding()`` method returning a ``NamedSharding`` (the
    mesh runner's clip-axis sharding) — and consulted once per ring
    payload take. Single-threaded like the stage itself; the snapshot
    is read after the stage drained.
    """

    def __init__(self, settings: HandoffSettings, device,
                 edge: str, model=None, external_owner=None):
        self.mode = settings.mode
        self.edge = str(edge)
        #: predicate over committed arrays whose bytes another ledger
        #: owner already foots (the pager's shared zero/stub pools,
        #: rnb_tpu.pager.Pager.owns): the SAME persistent array rides
        #: every feature-hit take, so counting it into this edge's
        #: residency would double-claim bytes the `page_pool` owner
        #: holds and break the Memory owners reconciliation
        self._external_owner = external_owner
        self._device = (device.resolve() if hasattr(device, "resolve")
                        else device)
        # stages homed on a mesh declare the sharding their inputs
        # should land on; everything else re-homes to the home device
        self._target = self._device
        sharding_fn = getattr(model, "input_sharding", None)
        if sharding_fn is not None:
            target = sharding_fn()
            if target is not None:
                self._target = target
        # -- accounting (snapshot/log-meta schema) --------------------
        self.d2d_edges = 0
        self.host_edges = 0
        self.d2d_bytes = 0
        self.host_bytes = 0
        #: payload bytes resident from the most recent take — what
        #: this edge's adoptions currently pin on the consumer side
        #: (the HBM-ledger "handoff" owner, rnb_tpu.memledger; a
        #: single-threaded int the ledger probe reads without a lock)
        self.resident_bytes = 0

    # -- the take -----------------------------------------------------

    def take(self, payload: Tuple) -> Tuple:
        """Apply the edge contract to one ring payload (a tuple of
        PaddedBatch/RaggedBatch): returns the consumer-resident
        payload and records the edge event. The batch wrappers are
        re-built around the re-homed arrays with their valid counts
        (and segment tables) intact."""
        if self.mode == "host":
            return self._take_host(payload)
        return self._take_device(payload)

    def _rewrap(self, pb, data):
        """A new batch wrapper of pb's kind around re-homed data."""
        offsets = getattr(pb, "segment_offsets", None)
        if offsets is not None:
            return type(pb)(data, pb.valid, offsets)
        return type(pb)(data, pb.valid)

    def _take_device(self, payload: Tuple) -> Tuple:
        """Device-resident take: adopt same-device arrays by
        reference; reshard cross-device arrays on-device (DMA fast
        path on real TPU, plain device_put otherwise). No host
        materialization on this path — rnb-lint RNB-H008 enforces it
        statically."""
        jax, _ = _jax_numpy()
        out: List[Any] = []
        moved = 0
        for pb in payload:
            data = pb.data
            if isinstance(data, jax.Array) \
                    and self._is_resident(data):
                out.append(pb)  # committed array adopted by reference
                continue
            rehomed = reshard(data, self._target)
            moved += int(getattr(data, "nbytes", 0))
            out.append(self._rewrap(pb, rehomed))
        self.d2d_edges += 1
        self.d2d_bytes += moved
        self.resident_bytes = self._residency(out)
        return tuple(out)

    def _residency(self, out) -> int:
        """Bytes this take pins on the consumer side, excluding arrays
        an external ledger owner (the pager) already foots."""
        total = 0
        for pb in out:
            data = pb.data
            if self._external_owner is not None \
                    and self._external_owner(data):
                continue
            total += int(getattr(data, "nbytes", 0))
        return total

    def _is_resident(self, data) -> bool:
        """Is this committed array already where the consumer wants
        it? (Single-device home: exactly this device. Sharding home:
        identical sharding.)"""
        try:
            if hasattr(self._target, "device_set"):  # a Sharding
                return data.sharding == self._target
            devices = data.devices()
        except Exception:
            return False
        return devices == {self._target}

    def _take_host(self, payload: Tuple) -> Tuple:
        """The explicit host round trip (the A/B baseline arm): every
        payload byte bounces through a numpy buffer before the
        consumer-side upload — the cost the device-resident mode
        exists to delete, here so it stays measurable."""
        jax, _ = _jax_numpy()
        out: List[Any] = []
        moved = 0
        for pb in payload:
            host = np.asarray(pb.data)
            moved += int(host.nbytes)
            out.append(self._rewrap(
                pb, jax.device_put(host, self._device)))
        self.host_edges += 1
        self.host_bytes += moved
        self.resident_bytes = self._residency(out)
        return tuple(out)

    # -- reporting ----------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        """Final per-edge counters for the job-wide aggregation
        (BenchmarkResult ``handoff_*`` fields / log-meta ``Handoff:``
        + ``Handoff edges:`` lines)."""
        return {
            "edge": self.edge,
            "mode": self.mode,
            "d2d_edges": self.d2d_edges,
            "host_edges": self.host_edges,
            "d2d_bytes": self.d2d_bytes,
            "host_bytes": self.host_bytes,
        }


def aggregate_snapshots(snapshots: List[Dict[str, object]]
                        ) -> Dict[str, object]:
    """Sum per-instance edge snapshots into the job-wide view plus the
    per-edge detail dict (edge label -> summed counters) the
    ``Handoff edges:`` JSON line carries."""
    out: Dict[str, object] = {"edges": 0, "d2d_edges": 0,
                              "host_edges": 0, "d2d_bytes": 0,
                              "host_bytes": 0}
    detail: Dict[str, Dict[str, int]] = {}
    for snap in snapshots:
        per = detail.setdefault(str(snap.get("edge", "?")),
                                {"d2d_edges": 0, "host_edges": 0,
                                 "d2d_bytes": 0, "host_bytes": 0})
        for key in ("d2d_edges", "host_edges", "d2d_bytes",
                    "host_bytes"):
            n = int(snap.get(key, 0))
            out[key] += n
            per[key] += n
    out["edges"] = out["d2d_edges"] + out["host_edges"]
    out["edge_detail"] = detail
    return out


class InflightDepths:
    """Per-replica in-flight depth counters for least-loaded routing.

    One instance per replica-expanded step, shared by the upstream
    producers' :class:`rnb_tpu.selector.ReplicaSelector` (reads +
    increments at enqueue) and the replica executors (decrement once
    the popped item's processing completes). Depth therefore counts
    queued *plus* in-service dispatches — a replica wedged on a slow
    batch keeps its depth high and stops receiving work, which a bare
    ``queue.qsize()`` poll would miss.
    """

    GUARDED_BY = {"_depths": "_lock"}

    def __init__(self, queue_indices):
        self._lock = threading.Lock()
        self._depths: Dict[int, int] = {int(q): 0
                                        for q in queue_indices}

    def inc(self, queue_idx: int, n: int = 1) -> None:
        with self._lock:
            if queue_idx in self._depths:
                self._depths[queue_idx] += n

    def dec(self, queue_idx: int, n: int = 1) -> None:
        with self._lock:
            if queue_idx in self._depths:
                self._depths[queue_idx] -= n

    def depth(self, queue_idx: int) -> int:
        with self._lock:
            return self._depths.get(queue_idx, 0)

    def snapshot(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._depths)
