"""Device observability plane: capture windows, live MFU, HBM ledger.

PR 6 made the host observable (trace.json) and PR 11 made it live
(metrics.jsonl), but the device stayed a black box at run time:
``profiler.py`` xplane captures, ``flops.py`` analytic FLOPs and
bench.py's end-of-run MFU line were disconnected one-shot tools. This
module stitches them into one plane behind the root ``devobs`` config
key, three legs:

* **Unified timeline** — bounded ``jax.profiler`` capture windows
  (config ``capture_window_ms``, the ``RNB_DEVOBS_FORCE`` env, or the
  PR 11 flight-recorder triggers via the metrics registry's trigger
  hooks). Captured op intervals are written as bounded
  ``devobs-capture-<n>.txt`` artifacts (the xprof-ops.txt 4-column
  format ``scripts/device_busy.py`` reads) AND merged into the PR 6
  Chrome-trace export as ``device:<plane>`` tracks, time-aligned by
  anchoring each plane's last timestamp to the capture's flush epoch
  (the same rule ``--xprof`` documents) and flow-correlated to the
  enclosing ``exec{i}.model_call`` spans via their request ids — one
  Perfetto file shows host hold/queue/transfer AND the XLA ops they
  paid for.
* **Live MFU / roofline** — per-dispatch achieved FLOPs: the stage's
  declared per-row count (``compute_profile()``, backed by
  rnb_tpu/models/r2p1d/flops.py) x the ``num_clips`` /
  ``rows_valid`` rows the dispatch actually carried, over the measured
  ``inference{i}`` span. Per stage: achieved TFLOP/s over busy time,
  MFU vs ``peak_tflops_for``, and an arithmetic-intensity figure from
  XLA ``cost_analysis()`` bytes — streamed as ``compute.*`` series
  through the PR 11 metrics plane and summarized in a ``Compute:``
  log-meta line whose job-level tflops/mfu use bench.py's exact
  arithmetic (same expression order, same rounding), so the two
  cross-foot to the digit on a clean run.
* **HBM footprint ledger** — :mod:`rnb_tpu.memledger`: cache, staging
  pools, ragged pools, stage params and handoff adoptions as declared
  owners, live ``memory.*`` gauges with peak high-water tracking, a
  watermark that warns and arms the flight recorder, and a
  live-buffer reconciliation pass — the ``Memory:`` line's owner rows
  sum to the total by construction.

House style (PR 6/11): names are declared (telemetry.METRIC_REGISTRY,
memledger.MEM_OWNER_REGISTRY), everything is checked rather than
trusted (``parse_utils --check`` cross-foots every line), and with the
``devobs`` key absent nothing is installed and every artifact stays
byte-identical to the pre-devobs schema.
"""

from __future__ import annotations

import bisect
import os
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

#: the active per-job plane, installed/cleared by rnb_tpu.benchmark
ACTIVE: Optional["DevObsPlane"] = None

#: env var forcing one capture window at run start (the ``make
#: devobs`` gate uses it to assert a bounded artifact without a
#: configured window)
FORCE_ENV = "RNB_DEVOBS_FORCE"

DEFAULT_CAPTURE_WINDOW_MS = 0.0     # no configured window
DEFAULT_FORCED_WINDOW_MS = 250.0    # window for env/trigger captures
DEFAULT_MAX_CAPTURES = 4
DEFAULT_CAPTURE_MAX_OPS = 20000
DEFAULT_SAMPLE_HZ = 20.0

#: merged-trace track prefix; the acceptance gate counts tracks with
#: this prefix as device tracks
DEVICE_TRACK_PREFIX = "device:"

_MODEL_CALL_RE = re.compile(r"^exec\d+\.model_call$")


def note_dispatch(step_idx: int, rows: int, busy_s: float) -> None:
    """Per-dispatch compute feed (rnb_tpu.runner). Disabled path: one
    module-global ``None`` test. Prefer resolving :func:`meter_for`
    once ahead of the hot loop and calling ``meter.note`` directly."""
    plane = ACTIVE
    if plane is None:
        return
    meter = plane.meters.get(step_idx)
    if meter is not None:
        meter.note(rows, busy_s)


def meter_for(step_idx: int) -> Optional["StageComputeMeter"]:
    """The step's compute meter, or None when devobs is off or the
    stage declared no compute profile — resolved once ahead of the
    executor hot loop so the per-dispatch cost is one ``None`` test."""
    plane = ACTIVE
    if plane is None:
        return None
    return plane.meters.get(step_idx)


def register_stage(model, step_idx: int, device, handoff=None) -> None:
    """One-stop stage-side registration (called by the executor after
    stage construction, before the start barrier): the stage's compute
    profile becomes a meter, and its byte-owning subsystems become
    ledger sources. No-op when devobs is off."""
    plane = ACTIVE
    if plane is None:
        return
    plane.add_stage(model, step_idx, device, handoff)


# -- config-derived helpers (shared with bench.py) ---------------------

def config_stage_views(config: dict):
    """Yield (step, [merged kwargs per queue_group]) with group keys
    overriding step keys — mirroring the runtime's kwargs_for_group,
    so evidence extractors see the same semantics the stage
    constructors do."""
    for step in config.get("pipeline", []):
        groups = step.get("queue_groups") or [{}]
        views = []
        for group in groups:
            merged = dict(step)
            merged.update(group)
            views.append(merged)
        yield step, views


def flops_per_clip_for_config(config: dict) -> float:
    """Analytic conv+dense FLOPs one clip costs across every network
    stage of the pipeline (a layer-split pipeline sums its ranges back
    to the full net). The config-walk twin of the runtime
    ``compute_profile()`` seam — the ``make devobs`` gate asserts the
    two agree, so the published evidence can never drift from the
    network that actually ran."""
    from rnb_tpu.models.r2p1d.flops import range_flops_per_clip
    total = 0
    for step, views in config_stage_views(config):
        model = step.get("model", "")
        if not model.endswith((".R2P1DSingleStep", ".R2P1DMeshRunner",
                               ".R2P1DRunner")):
            continue
        # one clip flows through ONE replica of the step, so count the
        # step once — from the first group's merged view
        view = views[0]
        kwargs = dict(
            consecutive_frames=view.get("consecutive_frames", 8),
            num_classes=view.get("num_classes", 400),
            factored_shortcut=view.get("factored_shortcut", False))
        if view.get("layer_sizes") is not None:
            kwargs["layer_sizes"] = tuple(view["layer_sizes"])
        if model.endswith(".R2P1DRunner"):
            start = view.get("start_index", 1)
            end = view.get("end_index", 5)
        else:
            start, end = 1, 5
        total += range_flops_per_clip(start, end, **kwargs)
    return float(total)


def devices_used(config: dict) -> int:
    """Distinct accelerator devices the topology touches (host -1
    excluded; a mesh stage counts its whole sub-mesh). Shared MFU
    denominator rule for bench.py's evidence line and the ``Compute:``
    log-meta line — one definition, so the two can cross-foot."""
    used = set()
    for _step, views in config_stage_views(config):
        for view in views:
            for dev in view.get("mesh_devices", []):
                used.add(int(dev))
            for dev in view.get("devices", []):
                if int(dev) >= 0:
                    used.add(int(dev))
    return max(1, len(used))


class DevObsSettings:
    """Validated per-job knobs (root config key ``devobs``)."""

    __slots__ = ("enabled", "capture_window_ms", "capture_on_trigger",
                 "max_captures", "capture_max_ops", "watermark_mb",
                 "sample_hz")

    def __init__(self, enabled: bool = True,
                 capture_window_ms: float = DEFAULT_CAPTURE_WINDOW_MS,
                 capture_on_trigger: bool = True,
                 max_captures: int = DEFAULT_MAX_CAPTURES,
                 capture_max_ops: int = DEFAULT_CAPTURE_MAX_OPS,
                 watermark_mb: Optional[float] = None,
                 sample_hz: float = DEFAULT_SAMPLE_HZ):
        self.enabled = bool(enabled)
        self.capture_window_ms = float(capture_window_ms)
        self.capture_on_trigger = bool(capture_on_trigger)
        self.max_captures = int(max_captures)
        self.capture_max_ops = int(capture_max_ops)
        self.watermark_mb = (float(watermark_mb)
                             if watermark_mb is not None else None)
        self.sample_hz = float(sample_hz)

    @staticmethod
    def from_config(raw: Optional[dict]) -> Optional["DevObsSettings"]:
        """Settings from the validated config dict, or None when the
        key is absent or ``enabled`` is false (devobs fully off: no
        plane, no ledger, no new meta lines, byte-stable logs)."""
        if raw is None:
            return None
        settings = DevObsSettings(
            enabled=raw.get("enabled", True),
            capture_window_ms=raw.get("capture_window_ms",
                                      DEFAULT_CAPTURE_WINDOW_MS),
            capture_on_trigger=raw.get("capture_on_trigger", True),
            max_captures=raw.get("max_captures", DEFAULT_MAX_CAPTURES),
            capture_max_ops=raw.get("capture_max_ops",
                                    DEFAULT_CAPTURE_MAX_OPS),
            watermark_mb=raw.get("watermark_mb"),
            sample_hz=raw.get("sample_hz", DEFAULT_SAMPLE_HZ))
        return settings if settings.enabled else None


class StageComputeMeter:
    """Per-step dispatch accounting: valid rows, dispatch count, busy
    seconds — multiplied by the stage's declared per-row FLOPs into
    achieved TFLOP/s and MFU. Shared by a step's replica instances
    (one lock)."""

    __slots__ = ("step_idx", "flops_per_row", "devices",
                 "bytes_per_row", "_lock", "rows", "dispatches",
                 "busy_s")

    GUARDED_BY = {
        "rows": "_lock",
        "dispatches": "_lock",
        "busy_s": "_lock",
    }

    def __init__(self, step_idx: int, flops_per_row: int,
                 devices: int = 1,
                 bytes_per_row: Optional[float] = None):
        self.step_idx = int(step_idx)
        self.flops_per_row = int(flops_per_row)
        self.devices = max(1, int(devices))
        self.bytes_per_row = (float(bytes_per_row)
                              if bytes_per_row else None)
        self._lock = threading.Lock()
        self.rows = 0
        self.dispatches = 0
        self.busy_s = 0.0

    def note(self, rows: int, busy_s: float) -> None:
        with self._lock:
            self.rows += int(rows)
            self.dispatches += 1
            self.busy_s += max(0.0, float(busy_s))

    def snapshot(self) -> dict:
        with self._lock:
            return {"rows": self.rows, "dispatches": self.dispatches,
                    "busy_s": self.busy_s}

    def achieved_tflops(self) -> float:
        """Achieved TFLOP/s over this stage's busy time (the roofline
        x-axis companion; 0 with no busy time yet)."""
        snap = self.snapshot()
        if snap["busy_s"] <= 0.0:
            return 0.0
        return snap["rows"] * self.flops_per_row / snap["busy_s"] / 1e12


class _Capture:
    """One bounded profiler capture: host epoch bounds + per-plane op
    intervals (ns on each plane's own clock)."""

    __slots__ = ("index", "trigger", "t0_epoch", "t1_epoch",
                 "intervals", "total_ops", "path", "plane_anchors")

    def __init__(self, index: int, trigger: str, t0_epoch: float,
                 t1_epoch: float, intervals: List[Tuple],
                 total_ops: int, path: Optional[str],
                 plane_anchors: Optional[Dict[str, int]] = None):
        self.index = index
        self.trigger = trigger
        self.t0_epoch = t0_epoch
        self.t1_epoch = t1_epoch
        self.intervals = intervals  # [(name, t0_ns, t1_ns, plane)]
        self.total_ops = total_ops
        self.path = path
        #: plane -> max end-timestamp (ns) over the FULL capture,
        #: recorded BEFORE the op bound truncates to the earliest
        #: ops — the epoch-alignment anchor (t1_epoch maps here)
        self.plane_anchors = plane_anchors or {}


def model_call_spans(events: List[Tuple]) -> List[Tuple]:
    """Extract rid-correlated ``exec{i}.model_call`` spans from a
    Tracer event snapshot: sorted ``[(t0_s, t1_s, rid)]`` — the flow
    anchors device ops correlate against."""
    spans = []
    for event_name, ph, t0, dur, _thread, rid, _args in events:
        if ph == "X" and rid is not None \
                and _MODEL_CALL_RE.match(event_name):
            spans.append((t0, t0 + max(0.0, dur), rid))
    spans.sort()
    return spans


class DevObsPlane:
    """Per-job device observability: capture worker + compute meters +
    the memory ledger. Built by rnb_tpu.benchmark when the ``devobs``
    root config key is enabled; one instance per job."""

    GUARDED_BY = {
        "meters": "_lock",
        "captures": "_lock",
        "captures_skipped": "_lock",
        "_capture_requests": "_lock",
        "_captures_inflight": "_lock",
    }

    UNGUARDED_OK = {
        "_worker": "controller-thread lifecycle (start/stop)",
        "_peak_tflops": "idempotent memo — a racing duplicate probe "
                        "computes the same value",
        "_peak_resolved": "guards only the memo above; same "
                          "idempotence argument",
    }

    def __init__(self, settings: DevObsSettings,
                 job_dir: Optional[str] = None, job_id: str = ""):
        from rnb_tpu.memledger import MemLedger
        self.settings = settings
        self.job_dir = job_dir
        self.job_id = job_id
        watermark_bytes = None
        if settings.watermark_mb is not None:
            watermark_bytes = int(settings.watermark_mb * (1 << 20))
        self.ledger = MemLedger(watermark_bytes=watermark_bytes)
        # metrics-less runs still get the watermark capture: the
        # ledger's direct observer arms it, deduped against the
        # metrics trigger-hook path (which delivers the same event
        # when a registry is live)
        self.ledger.on_watermark = self._watermark_capture
        self.meters: Dict[int, StageComputeMeter] = {}
        self._lock = threading.Lock()
        self.captures: List[_Capture] = []
        self.captures_skipped = 0
        self._capture_requests: List[str] = []
        #: requests popped but not yet landed in ``captures`` — part
        #: of the budget check, or a trigger firing mid-capture could
        #: overrun max_captures
        self._captures_inflight = 0
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._run_started = threading.Event()
        self._peak_tflops: Optional[float] = None
        self._peak_resolved = False

    # -- stage registration -------------------------------------------

    def add_stage(self, model, step_idx: int, device,
                  handoff=None) -> None:
        device_label = getattr(device, "label", str(device))
        profile_fn = getattr(model, "compute_profile", None)
        profile = None
        if profile_fn is not None:
            try:
                profile = profile_fn()
            except Exception:
                profile = None
        if profile and int(profile.get("flops_per_row", 0)) > 0:
            with self._lock:
                if step_idx not in self.meters:
                    # replicas of one step share one meter (their
                    # dispatch rows/busy sum into the step's roofline)
                    self.meters[step_idx] = StageComputeMeter(
                        step_idx, profile["flops_per_row"],
                        devices=profile.get("devices", 1),
                        bytes_per_row=profile.get("bytes_per_row"))
            params_key = profile.get("params_key")
            params_bytes = int(profile.get("params_bytes", 0) or 0)
            if params_key is not None and params_bytes > 0:
                # deduped across replicas: shared parameter copies
                # register under one key and count once — and they are
                # provably backed by live device arrays (live=True
                # enters the reconcile pass)
                self.ledger.register("params", device_label,
                                     params_key, params_bytes,
                                     live=True)
            pool_bytes = int(profile.get("pool_bytes", 0) or 0)
            if pool_bytes > 0:
                self.ledger.register("ragged_pool", device_label,
                                     ("pool", step_idx, id(model)),
                                     pool_bytes)
        cache = getattr(model, "cache", None)
        if cache is not None and hasattr(cache, "resident_bytes"):
            self.ledger.register(
                "cache", device_label, ("cache", id(cache)),
                lambda c=cache: c.resident_bytes)
        staging = getattr(model, "staging", None)
        if staging is not None and hasattr(staging, "snapshot"):
            self.ledger.register(
                "staging", device_label, ("staging", id(staging)),
                lambda s=staging: s.snapshot().get("slot_bytes", 0))
        if handoff is not None \
                and hasattr(handoff, "resident_bytes"):
            self.ledger.register(
                "handoff", device_label, ("handoff", id(handoff)),
                lambda h=handoff: h.resident_bytes)

    # -- capture windows ----------------------------------------------

    def request_capture(self, trigger: str) -> None:
        """Arm one bounded capture window (serviced by the worker —
        never profiler work on the caller's thread)."""
        with self._lock:
            if len(self.captures) + len(self._capture_requests) \
                    + self._captures_inflight \
                    >= self.settings.max_captures:
                self.captures_skipped += 1
                return
            self._capture_requests.append(str(trigger))

    def on_trigger(self, reason: str, detail: Optional[dict]) -> None:
        """Metrics-plane trigger hook (PR 11 flight-recorder
        machinery): every anomaly trigger also arms a device capture,
        so the black box records what the device was doing."""
        if self.settings.capture_on_trigger:
            self.request_capture(reason)

    def _watermark_capture(self, total_bytes: int) -> None:
        """The ledger's direct watermark observer: arms the capture on
        metrics-less runs. With a live metrics registry the SAME
        crossing arrives through the trigger-hook path above, so this
        side defers to it (one crossing, one capture)."""
        from rnb_tpu import metrics
        if metrics.ACTIVE is not None:
            return
        if self.settings.capture_on_trigger:
            self.request_capture(metrics.TRIGGER_MEMORY_WATERMARK)

    def _capture_once(self, trigger: str) -> None:
        from rnb_tpu import profiler
        window_ms = self.settings.capture_window_ms \
            or DEFAULT_FORCED_WINDOW_MS
        t0 = time.time()
        try:
            profiler.initialize()
        except RuntimeError:
            # another capture owns the profiler (an --xprof run, or a
            # stale session): skip, never break the run
            with self._lock:
                self.captures_skipped += 1
            return
        try:
            # interruptible window: teardown must not wait a full
            # window out
            self._stop.wait(timeout=window_ms / 1000.0)
        finally:
            # anchor BEFORE flush/parse: stopping a large capture and
            # walking its xplane can take seconds, and the alignment
            # rule maps the last captured op to THIS instant (the
            # --xprof anchor-before-stop rule) — an after-the-parse
            # stamp would shift every merged device event late by the
            # parse time, off the model_call spans they belong under
            t1 = time.time()
            profiler.flush()
        intervals = profiler.report(include_plane=True)
        total_ops = len(intervals)
        intervals = sorted(intervals, key=lambda iv: iv[1])
        # per-plane anchors over the FULL set: the bound below keeps
        # the EARLIEST ops, so anchoring on the kept maximum would
        # misplace a truncated capture by the dropped tail's extent
        plane_anchors: Dict[str, int] = {}
        for _name, _s, e, plane in intervals:
            if e > plane_anchors.get(plane, 0):
                plane_anchors[plane] = e
        # bounded artifact: the cap is part of the contract (a runaway
        # capture must not OOM the host or bloat the job dir)
        kept = [(name, s, e, plane)
                for name, s, e, plane in intervals[
                    :self.settings.capture_max_ops]]
        with self._lock:
            index = len(self.captures)
        path = None
        if self.job_dir is not None:
            path = os.path.join(self.job_dir,
                                "devobs-capture-%d.txt" % index)
            with open(path, "w") as f:
                f.write("# t0_ns t1_ns plane op_name\n")
                f.write("# window_epoch %f %f flush_epoch %f\n"
                        % (t0, t1, t1))
                f.write("# trigger %s ops_total %d ops_written %d\n"
                        % (trigger.replace(" ", "_"), total_ops,
                           len(kept)))
                for name, s, e, plane in kept:
                    f.write("%d %d %s %s\n"
                            % (s, e, plane.replace(" ", "_") or "-",
                               name))
        with self._lock:
            self.captures.append(_Capture(index, trigger, t0, t1,
                                          kept, total_ops, path,
                                          plane_anchors))

    # -- worker --------------------------------------------------------

    def start(self) -> None:
        if self._worker is None:
            self._worker = threading.Thread(target=self._run,
                                            name="devobs-worker",
                                            daemon=True)
            self._worker.start()

    def note_run_started(self) -> None:
        """The measured window opened (start barrier released): the
        configured/forced capture windows begin now, so warmup compile
        never lands in a capture."""
        self._run_started.set()

    def _run(self) -> None:
        period = 1.0 / max(1e-3, self.settings.sample_hz)
        self._run_started.wait(timeout=1800.0)
        if os.environ.get(FORCE_ENV):
            self.request_capture("forced")
        if self.settings.capture_window_ms > 0:
            self.request_capture("window")
        while not self._stop.wait(timeout=period):
            try:
                self.ledger.sample()
                self._service_captures()
            except Exception:
                continue  # the worker must outlive any bad probe
        # drain any still-armed capture with the stop flag set: the
        # window wait returns immediately, so this is cheap and the
        # forced-capture contract (env set => artifact exists) holds
        # even for very short runs
        try:
            self._service_captures()
        except Exception:
            pass

    def _service_captures(self) -> None:
        while True:
            with self._lock:
                if not self._capture_requests:
                    return
                trigger = self._capture_requests.pop(0)
                self._captures_inflight += 1
            try:
                self._capture_once(trigger)
            finally:
                with self._lock:
                    self._captures_inflight -= 1

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        self._run_started.set()
        if self._worker is not None:
            self._worker.join(timeout=timeout)
            self._worker = None

    # -- metrics bridge -----------------------------------------------

    def _peak(self) -> Optional[float]:
        if not self._peak_resolved:
            self._peak_resolved = True
            try:
                import jax

                from rnb_tpu.models.r2p1d.flops import peak_tflops_for
                self._peak_tflops = peak_tflops_for(
                    jax.devices()[0].device_kind)
            except Exception:
                self._peak_tflops = None
        return self._peak_tflops

    def metrics_poll(self) -> List[Tuple[str, str, float]]:
        """Registry poll source (rnb_tpu.metrics): ``compute.*``
        per-stage series + ``memory.*`` ledger gauges, read each
        flusher tick. Doubles as a ledger sampling site, so the peak
        tracking is at least as fine as the metrics interval."""
        from rnb_tpu import metrics
        out: List[Tuple[str, str, float]] = []
        peak = self._peak()
        with self._lock:
            meters = list(self.meters.values())
        for meter in meters:
            snap = meter.snapshot()
            step = meter.step_idx
            out.append(("counter",
                        metrics.name("compute.s%d.rows", step),
                        snap["rows"]))
            out.append(("counter",
                        metrics.name("compute.s%d.dispatches", step),
                        snap["dispatches"]))
            tflops = meter.achieved_tflops()
            out.append(("gauge",
                        metrics.name("compute.s%d.tflops", step),
                        tflops))
            if peak:
                out.append(("gauge",
                            metrics.name("compute.s%d.mfu", step),
                            tflops / (peak * meter.devices)))
        record = self.ledger.sample()
        out.append(("gauge", metrics.name("memory.total_bytes"),
                    record["total"]))
        out.append(("gauge", metrics.name("memory.peak_bytes"),
                    self.ledger.peak_total))
        owner_gauges = {
            "params": metrics.name("memory.params_bytes"),
            "cache": metrics.name("memory.cache_bytes"),
            "staging": metrics.name("memory.staging_bytes"),
            "ragged_pool": metrics.name("memory.ragged_pool_bytes"),
            "handoff": metrics.name("memory.handoff_bytes"),
            "page_pool": metrics.name("memory.page_pool_bytes"),
        }
        for owner, nbytes in sorted(record["owners"].items()):
            gauge_name = owner_gauges.get(owner)
            if gauge_name is not None:
                out.append(("gauge", gauge_name, nbytes))
        return out

    # -- trace merge ---------------------------------------------------

    def device_events(self, spans: List[Tuple]) -> List[Tuple]:
        """Captured op intervals as Tracer event tuples on synthetic
        ``device:<plane>`` tracks, epoch-aligned per plane (anchor:
        the plane's last timestamp coincides with the capture's flush
        epoch — the ``--xprof`` mapping rule) and rid-correlated to
        the enclosing ``model_call`` span so the exporter's flow
        chains draw host->device arrows. ``spans`` comes from
        :func:`model_call_spans` over the tracer's event snapshot."""
        starts = [s[0] for s in spans]
        # running max-end prefix (the exporter's enclosure trick):
        # model_call spans overlap across replica lanes and pipeline
        # steps, so the latest-started span is not the only enclosure
        # candidate — walk back while an earlier span could still
        # reach t, preferring the latest-started (innermost) one
        maxend: List[float] = []
        running = float("-inf")
        for _t0, t1, _rid in spans:
            running = max(running, t1)
            maxend.append(running)

        def rid_at(t: float) -> Optional[int]:
            idx = bisect.bisect_right(starts, t) - 1
            while idx >= 0 and maxend[idx] >= t:
                if spans[idx][1] >= t:
                    return spans[idx][2]
                idx -= 1
            return None

        events: List[Tuple] = []
        with self._lock:
            captures = list(self.captures)
        for cap in captures:
            by_plane: Dict[str, List[Tuple]] = {}
            for name, t0_ns, t1_ns, plane in cap.intervals:
                by_plane.setdefault(plane, []).append(
                    (name, t0_ns, t1_ns))
            for plane, ivals in sorted(by_plane.items()):
                # per-plane anchoring: XLine clock bases differ across
                # planes, so each plane maps into epoch independently —
                # using the FULL capture's anchor when recorded (the
                # kept set may be a truncated prefix)
                max_end = cap.plane_anchors.get(
                    plane, max(t1 for _n, _t0, t1 in ivals))
                offset = cap.t1_epoch - max_end / 1e9
                track = DEVICE_TRACK_PREFIX + plane
                for name, t0_ns, t1_ns, in ivals:
                    t0 = t0_ns / 1e9 + offset
                    dur = max(0.0, (t1_ns - t0_ns) / 1e9)
                    rid = rid_at(t0 + dur / 2.0)
                    events.append((name, "X", t0, dur, track, rid,
                                   {"devobs_capture": cap.index}))
        return events

    # -- summaries -----------------------------------------------------

    def compute_summary(self, total_time_s: float,
                        devices_used_count: int) -> Optional[dict]:
        """The ``Compute:`` / ``Compute stages:`` record. Job-level
        tflops/mfu use bench.py's exact arithmetic — same expression
        order (``rows/s * flops_per_clip / 1e12``), same denominator
        (``peak * devices_used``), same rounding (3 digits tflops, 4
        digits mfu) — so a clean run cross-foots the bench evidence
        line to the digit; per-stage figures use each stage's busy
        time (the roofline view). With NO flops-declaring stage the
        record still carries the capture counter (stages=0, zero
        flops) — the Compute: line rides every devobs run so the
        captures-vs-artifacts invariant never goes unchecked."""
        with self._lock:
            meters = sorted(self.meters.values(),
                            key=lambda m: m.step_idx)
        peak = self._peak()
        stage_detail: Dict[str, dict] = {}
        flops_total = 0
        dispatches_total = 0
        for meter in meters:
            snap = meter.snapshot()
            stage_flops = snap["rows"] * meter.flops_per_row
            flops_total += stage_flops
            dispatches_total += snap["dispatches"]
            busy_s = snap["busy_s"]
            tflops_busy = (stage_flops / busy_s / 1e12
                           if busy_s > 0 else 0.0)
            entry = {
                "rows": snap["rows"],
                "dispatches": snap["dispatches"],
                "flops_per_row": meter.flops_per_row,
                "flops": stage_flops,
                "busy_us": int(round(busy_s * 1e6)),
                "devices": meter.devices,
                "tflops_busy": round(tflops_busy, 6),
                "mfu_busy": (round(tflops_busy
                                   / (peak * meter.devices), 6)
                             if peak else None),
                "ai_flops_per_byte": (
                    round(meter.flops_per_row / meter.bytes_per_row, 3)
                    if meter.bytes_per_row else None),
            }
            stage_detail["step%d" % meter.step_idx] = entry
        # job-level cross-foot against bench.py: rows at the LAST
        # flops-bearing stage are the completed clips, and the
        # per-clip cost is the sum over stages — the same quantities
        # bench derives from clips_completed and the config walk
        rows_job = meters[-1].snapshot()["rows"] if meters else 0
        flops_per_clip = float(sum(m.flops_per_row for m in meters))
        clips_per_sec = (rows_job / total_time_s
                         if total_time_s > 0 else 0.0)
        tflops = clips_per_sec * flops_per_clip / 1e12
        mfu = (tflops / (peak * devices_used_count)
               if peak else None)
        with self._lock:
            num_captures = len(self.captures)
        return {
            "stages": len(meters),
            "dispatches": dispatches_total,
            "rows": rows_job,
            "flops_total": flops_total,
            "window_us": int(round(total_time_s * 1e6)),
            # derived from the SAME rounded values bench.py publishes,
            # so the demo's to-the-digit comparison is deterministic
            "tflops_milli": int(round(round(tflops, 3) * 1000)),
            "mfu_e4": (int(round(round(mfu, 4) * 10000))
                       if mfu is not None else -1),
            "captures": num_captures,
            "stage_detail": stage_detail,
        }

    def memory_summary(self) -> dict:
        """The ``Memory:`` / ``Memory owners:`` record: the ledger's
        settled snapshot plus the live-buffer reconciliation pass."""
        snap = self.ledger.snapshot()
        live_bytes, ok = self.ledger.reconcile()
        snap["live_bytes"] = live_bytes
        snap["reconciled"] = 1 if (live_bytes > 0 and ok) else 0
        return snap
