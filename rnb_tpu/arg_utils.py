"""argparse value validators for the benchmark CLI.

Reference parity: arg_utils.py:2-16.
"""

from __future__ import annotations

import argparse


def positive_int(value) -> int:
    ivalue = int(value)
    if ivalue <= 0:
        raise argparse.ArgumentTypeError(
            "%s is not a positive integer" % value)
    return ivalue


def nonnegative_int(value) -> int:
    ivalue = int(value)
    if ivalue < 0:
        raise argparse.ArgumentTypeError(
            "%s is not a non-negative integer" % value)
    return ivalue
