"""The pipeline-stage plugin contract.

A *stage model* is one step of the inference pipeline — decode, a
(possibly partial) neural network, a batcher, an aggregator. Stage
classes are named by string in JSON configs and loaded dynamically;
the executor instantiates one per (step, group, device instance).

Capability parity with the reference's RunnerModel (runner_model.py:1-81)
with one deliberate TPU-first change: tensors move through the pipeline
as fixed max-shape arrays with an explicit valid-row count
(:class:`PaddedBatch`), never as dynamically-sized slices. XLA compiles
a jitted stage exactly once per static shape; the reference instead
sliced shared CUDA tensors to the valid batch size before each call
(reference runner.py:109-114), which on TPU would trigger a
recompilation per distinct clip count.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class PadCounter:
    """Padding-waste accounting for one batching stage instance.

    Every emission notes its valid rows and the rows it actually
    shipped; the difference is pad work the downstream stage burns
    FLOPs (and the wire burns bytes) on. Surfaced end-to-end —
    BenchmarkResult ``pad_rows``/``total_rows``, the log-meta
    ``Padding:`` line, the ``# padding`` table trailer — so the
    bucketed path quantifies the waste the ragged path removes (and
    the ragged path proves its computed-pad count is ~0).
    """

    pad_rows: int = 0
    total_rows: int = 0
    emissions: int = 0

    def note(self, valid: int, shipped: int) -> int:
        """Record one emission; returns its pad-row count."""
        pad = max(0, int(shipped) - int(valid))
        self.pad_rows += pad
        self.total_rows += int(shipped)
        self.emissions += 1
        return pad

    def snapshot(self) -> dict:
        return {"pad_rows": self.pad_rows, "total_rows": self.total_rows,
                "emissions": self.emissions}


def note_emission_accounting(padding: "PadCounter", ragged_stats,
                             cards, valid: int, shipped: int,
                             counterfactual_rows: int) -> None:
    """The ONE padding/ragged accounting rule every batching stage
    (loaders, Batcher) applies per emission — parse_utils --check
    asserts invariants over these counters, so two hand-maintained
    copies would be exactly the drift the checker exists to stop.

    Bucketed (``ragged_stats is None``): count ``shipped - valid`` pad
    rows. Ragged: the consumer's kernel computes no pad rows, so the
    counted shipped rows ARE the valid rows and ``counterfactual_rows
    - valid`` — what the bucketed pad rule would have shipped — lands
    in ``pad_rows_eliminated`` (equal to a same-seed bucketed arm's
    ``pad_rows`` by construction). Either way the emission's pad count
    is stamped on the FIRST constituent card (0 on the rest) so table
    sums stay exact.
    """
    if ragged_stats is not None:
        pad = padding.note(valid, valid)
        ragged_stats["emissions"] += 1
        ragged_stats["rows"] += valid
        ragged_stats["pad_rows_eliminated"] += \
            int(counterfactual_rows) - int(valid)
    else:
        pad = padding.note(valid, shipped)
    for idx, tc in enumerate(cards):
        tc.pad_rows = (getattr(tc, "pad_rows", 0) + pad if idx == 0
                       else getattr(tc, "pad_rows", 0))


@dataclasses.dataclass
class PaddedBatch:
    """A static-shape array plus the number of leading valid rows.

    ``data``'s row axis (axis 0, the batch/clip axis) is the stage's
    declared max shape — or, under opt-in row bucketing, a smaller
    bucket from a fixed per-config set (still static per bucket, one jit
    executable each). Consumers must use ``valid``/``max_rows``, never
    assume axis 0 equals the declared maximum. Rows ``valid:`` are
    padding and must be ignored. This is the TPU-idiomatic encoding of
    the reference's max-shape shared tensors + ``valid_batch_sizes``
    side array (reference control.py:34-39).
    """

    data: Any          # numpy or jax.Array, shape = (max_rows, ...)
    valid: int         # number of meaningful leading rows

    @property
    def max_rows(self) -> int:
        return int(self.data.shape[0])

    def valid_data(self):
        """Host-side view of the meaningful rows (do not use inside jit)."""
        return self.data[: self.valid]

    @staticmethod
    def from_rows(rows, max_rows: int, dtype=None) -> "PaddedBatch":
        """Pad a (n, ...) host array up to (max_rows, ...) with zeros."""
        rows = np.asarray(rows, dtype=dtype)
        n = rows.shape[0]
        if n > max_rows:
            raise ValueError("batch of %d rows exceeds max_rows=%d"
                             % (n, max_rows))
        if n == max_rows:
            return PaddedBatch(rows, n)
        pad = np.zeros((max_rows - n,) + rows.shape[1:], dtype=rows.dtype)
        return PaddedBatch(np.concatenate([rows, pad], axis=0), n)


@dataclasses.dataclass
class RaggedBatch(PaddedBatch):
    """A :class:`PaddedBatch` whose row axis is a **flat row pool** at
    the stage's one compiled shape, plus the per-request segment table
    (rnb_tpu.ops.ragged).

    ``data`` always has exactly the pool shape — never a bucket —
    so every dispatch hits the same XLA executable; ``valid`` is the
    scalar ``rows_valid`` the ragged forward primitive masks against;
    ``segment_offsets`` partitions ``[0, valid)`` per constituent
    request (request i owns rows ``[offsets[i], offsets[i+1])``),
    validated by the executor on every publish.
    """

    segment_offsets: Tuple[int, ...] = (0, 0)

    def __post_init__(self):
        self.segment_offsets = tuple(int(o)
                                     for o in self.segment_offsets)

    @property
    def num_segments(self) -> int:
        """Constituent requests packed into the pool."""
        return len(self.segment_offsets) - 1


def normalize_row_buckets(row_buckets, max_rows: int, what: str
                          ) -> Tuple[int, ...]:
    """Sorted, validated bucket tuple; ``(max_rows,)`` when disabled.

    The one validation every bucketing stage (loader, batcher) shares:
    buckets are distinct positive row counts ending exactly at the
    stage's max shape — a typo'd set must fail fast, not silently pad
    to an un-warmed shape.
    """
    if not row_buckets:
        return (int(max_rows),)
    buckets = sorted(int(b) for b in row_buckets)
    if buckets[0] < 1 or len(set(buckets)) != len(buckets):
        raise ValueError("row_buckets %r must be distinct positive row "
                         "counts" % (row_buckets,))
    if buckets[-1] != max_rows:
        raise ValueError("row_buckets %r must end at %s=%d"
                         % (row_buckets, what, max_rows))
    return tuple(buckets)


class StageModel:
    """Abstract contract every pipeline stage implements.

    Besides the instance lifecycle below, stages expose a *static*
    face — ``output_shape_for`` / ``input_shape_for`` and the dtype
    variants — classmethods that derive wire metadata from the step's
    JSON kwargs without constructing the stage (no device, no
    checkpoint, no warm-up). The runtime sizes buffer rings from the
    output side; the static pipeline checker (rnb_tpu.analysis.graph)
    walks both sides step-to-step to reject shape/dtype-incompatible
    wiring before any device is touched.

    Lifecycle (all in the executor thread that owns the stage's devices):

    * ``__init__(device, **kwargs)`` — build the stage, load weights, and
      *warm up* (jit-compile with dummy inputs) so steady-state requests
      never pay compilation latency. Extra JSON config keys arrive as
      kwargs (reference runner_model.py:3-14, benchmark.py:241-246).
    * ``input_shape()`` — nested tuple of expected per-tensor shapes, or
      None if the stage takes no tensor inputs (reference
      runner_model.py:16-29).
    * ``output_shape()`` — static; tuple of max shapes of the produced
      tensors, or None meaning "this stage emits no tensors", in which
      case the runtime allocates no device ring for it (reference
      runner_model.py:31-46 — note None differs from ``()``).
    * ``output_shape_for(**model_kwargs)`` — classmethod refinement of
      ``output_shape()``: receives the step's model kwargs (the same
      dict the constructor gets) so config-dependent stages — a partial
      layer range, a non-default row count — can declare their *exact*
      output shapes. The runtime sizes buffer rings with this and
      validates every produced payload against it, so shape metadata
      can never silently rot (the reference's hardcoded (10, 400) was
      wrong for partial ranges — its TODO #69,
      models/r2p1d/model.py:76-80). Default: the static shape.
    * ``__call__(tensors, non_tensors, time_card)`` — run one request.
      ``tensors`` is a tuple of :class:`PaddedBatch` (or None for the
      first stage); returns ``(tensors, non_tensors, time_card)`` where a
      None time_card means the stage swallowed the item (e.g. a batcher
      still accumulating) and nothing propagates downstream (reference
      runner_model.py:48-81, runner.py:130-134).
    """

    #: True for stages that re-pack incoming rows into their own
    #: batches (Batcher): any upstream row-bucket set is acceptable on
    #: their input, so bucket-compatibility checks skip them. Stages
    #: that jit-compile per incoming bucket shape (network runners)
    #: leave this False — their warmed bucket set must cover every
    #: bucket the producer can emit.
    REPACKS_ROWS = False

    #: Classes this stage forwards its open config kwargs to (composed
    #: stages, e.g. R2P1DSingleStep embedding a loader + runner). The
    #: static unconsumed-config-key check unions their named
    #: constructor parameters with this class's own.
    FORWARDS_CONFIG_TO: Tuple[type, ...] = ()

    #: True for stages whose batching knobs the load-adaptive
    #: controller (rnb_tpu.autotune, root 'autotune' config key) can
    #: drive — they implement ``enable_autotune(settings)`` and route
    #: their accumulate/emit decisions through the controller. The
    #: executor and the static graph checker both key off this.
    SUPPORTS_AUTOTUNE = False

    #: True for stages that implement the ragged row-pool dispatch
    #: contract (root 'ragged' config key, rnb_tpu.ops.ragged): they
    #: accept ``ragged``/``ragged_pool_rows`` constructor kwargs, warm
    #: exactly ONE shape (the pool), and move RaggedBatch payloads.
    #: The launcher injects the kwargs only for supporting classes.
    SUPPORTS_RAGGED = False

    #: True for stages that implement the page-allocator contract
    #: (root 'pager' config key, rnb_tpu.pager): they implement
    #: ``enable_pager(pager)`` — loaders switch the clip cache to
    #: paged entries and gather hits on device; consumers attach a
    #: feature-page arena and serve repeat requests from cached
    #: post-stage rows. The executor wires the shared Pager only for
    #: supporting classes.
    SUPPORTS_PAGER = False

    def __init__(self, device, **kwargs):
        self.device = device

    def input_shape(self) -> Optional[Sequence]:
        return None

    def input_sharding(self):
        """The ``jax.sharding.Sharding`` this stage wants its input
        payloads homed on, or None for the instance's home device.

        Consulted by the device-resident edge contract
        (rnb_tpu.handoff.EdgeHandoff) under the root ``handoff``
        config key: a mesh-resident stage (R2P1DMeshRunner) declares
        its mesh placement here so the inter-stage edge re-homes
        payloads as ONE on-device resharding — ICI on real hardware,
        with the remote-DMA fast path when the move matches the ring
        pattern (rnb_tpu.ops.handoff_dma) — instead of the stage
        re-placing them inside its dispatch path."""
        return None

    @staticmethod
    def output_shape() -> Optional[Tuple[Tuple[int, ...], ...]]:
        return None

    @classmethod
    def input_shape_for(cls, **model_kwargs) -> Optional[
            Tuple[Tuple[int, ...], ...]]:
        """Config-aware *expected input* max shapes, or None when the
        stage takes no tensor inputs (first-stage loaders) or accepts
        anything. The static counterpart of ``input_shape()`` —
        derivable from the step's JSON kwargs alone, so the pipeline
        checker can match it against the upstream step's declared
        output shapes without constructing the stage."""
        del model_kwargs
        return None

    @classmethod
    def input_dtype_for(cls, **model_kwargs) -> Optional[str]:
        """Expected input dtype name ("uint8", "bfloat16", "float32"),
        or None when any dtype is acceptable / unknown."""
        del model_kwargs
        return None

    @classmethod
    def output_dtype_for(cls, **model_kwargs) -> Optional[str]:
        """Produced output dtype name, or None when unknown (e.g. a
        pass-through stage that emits whatever it receives)."""
        del model_kwargs
        return None

    @classmethod
    def output_shape_for(cls, **model_kwargs) -> Optional[
            Tuple[Tuple[int, ...], ...]]:
        """Config-aware output shapes; defaults to ``output_shape()``.

        Overrides must accept (and ignore) arbitrary kwargs — the
        runtime passes the step's full model-kwargs dict.
        """
        del model_kwargs
        return cls.output_shape()

    def __call__(self, tensors, non_tensors, time_card):
        raise NotImplementedError
