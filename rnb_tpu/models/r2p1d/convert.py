"""Torch -> Flax checkpoint conversion for R(2+1)D-18.

The reference loaded a pretrained Kinetics-400 torch checkpoint
(``model_data.pth.tar`` with a ``state_dict`` payload, reference
models/r2p1d/model.py:18,50-63) whose module tree comes from the
R2Plus1D-PyTorch submodule (``res2plus1d.conv{1..5}`` +
``linear``). This module converts that state dict into this
framework's Flax variable tree so the same pretrained weights drive
the TPU pipeline:

* torch ``Conv3d`` weights ``(out, in, T, H, W)`` transpose to Flax
  ``(T, H, W, in, out)`` kernels;
* torch ``BatchNorm3d`` splits into Flax params (weight->scale,
  bias->bias) and batch_stats (running_mean->mean, running_var->var);
* torch ``Linear`` ``(out, in)`` transposes to Dense ``(in, out)``;
* module paths remap: ``convL.block1`` -> ``convL/block0``,
  ``convL.blocks.{i}`` -> ``convL/block{i+1}``,
  ``downsampleconv/downsamplebn`` -> ``shortcut/shortcut_bn``,
  ``spatial_conv/temporal_conv`` -> ``spatial/temporal``;
* the stem BN this network adds after conv1 (the torch stem conv is
  bare) has no torch source and is initialized to identity, which is a
  no-op in inference mode;
* the torch downsampling shortcut is a *factored* 1x1x1 (2+1)D pair,
  so converted trees target ``factored_shortcut=True`` models
  (rnb_tpu.models.r2p1d.network.SpatioTemporalResBlock).

Conversion is pure numpy — torch is only needed by :func:`convert_file`
to unpickle a real ``.pth.tar``. Every converted tree is validated
module-by-module against the target architecture's abstract init
(structure AND shapes), so a truncated or mismatched state dict fails
loudly instead of producing a silently wrong model.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional, Sequence, Tuple

import numpy as np

from rnb_tpu.models.r2p1d.network import (KINETICS_CLASSES, NUM_LAYERS,
                                          R18_LAYER_SIZES)


class ConversionError(ValueError):
    """State dict does not match the expected reference format."""


def _conv_kernel(w: np.ndarray) -> np.ndarray:
    """torch Conv3d (out, in, T, H, W) -> Flax (T, H, W, in, out)."""
    if w.ndim != 5:
        raise ConversionError("conv weight must be 5-D, got %r"
                              % (w.shape,))
    return np.ascontiguousarray(np.transpose(w, (2, 3, 4, 1, 0)))


def _set(tree: Dict[str, Any], path: Sequence[str],
         value: np.ndarray) -> None:
    node = tree
    for key in path[:-1]:
        node = node.setdefault(key, {})
    if path[-1] in node:
        raise ConversionError("duplicate assignment at %s"
                              % "/".join(path))
    node[path[-1]] = value


def _bn(params: Dict[str, Any], stats: Dict[str, Any],
        flax_path: Tuple[str, ...], torch_name: str,
        sd: Mapping[str, np.ndarray], prefix: str) -> None:
    """Map one BatchNorm3d: affine params + running statistics."""
    for torch_key, target, leaf in (
            ("weight", params, "scale"), ("bias", params, "bias")):
        _set(target, flax_path + (leaf,),
             _np(sd, "%s%s.%s" % (prefix, torch_name, torch_key), 1))
    for torch_key, leaf in (("running_mean", "mean"),
                            ("running_var", "var")):
        _set(stats, flax_path + (leaf,),
             _np(sd, "%s%s.%s" % (prefix, torch_name, torch_key), 1))


def _np(sd: Mapping[str, Any], key: str, ndim: Optional[int] = None
        ) -> np.ndarray:
    if key not in sd:
        raise ConversionError("state dict is missing %r" % key)
    arr = np.asarray(sd[key], dtype=np.float32)
    if ndim is not None and arr.ndim != ndim:
        raise ConversionError("%r has %d dims, expected %d"
                              % (key, arr.ndim, ndim))
    return arr


def _st_conv(params: Dict[str, Any], stats: Dict[str, Any],
             flax_path: Tuple[str, ...], sd: Mapping[str, Any],
             prefix: str) -> None:
    """Map one SpatioTemporalConv (spatial conv + mid BN + temporal)."""
    _set(params, flax_path + ("spatial", "kernel"),
         _conv_kernel(_np(sd, prefix + "spatial_conv.weight")))
    _bn(params, stats, flax_path + ("bn",), "bn", sd, prefix)
    _set(params, flax_path + ("temporal", "kernel"),
         _conv_kernel(_np(sd, prefix + "temporal_conv.weight")))


def _identity_bn(params: Dict[str, Any], stats: Dict[str, Any],
                 flax_path: Tuple[str, ...], features: int) -> None:
    _set(params, flax_path + ("scale",), np.ones(features, np.float32))
    _set(params, flax_path + ("bias",), np.zeros(features, np.float32))
    _set(stats, flax_path + ("mean",), np.zeros(features, np.float32))
    _set(stats, flax_path + ("var",), np.ones(features, np.float32))


def convert_state_dict(state_dict: Mapping[str, Any],
                       num_classes: int = KINETICS_CLASSES,
                       layer_sizes: Sequence[int] = R18_LAYER_SIZES,
                       validate: bool = True) -> Dict[str, Any]:
    """Reference torch state dict -> full-model Flax variable tree.

    The result loads into ``R2Plus1DClassifier(factored_shortcut=True)``
    (and, range-filtered via checkpoint.filter_layer_range, into every
    partitioned stage). With ``validate`` the tree is checked leaf by
    leaf against the architecture's abstract init shapes.
    """
    sd = state_dict
    params: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}

    # stem: torch applies the factored conv bare; our trailing stem BN
    # has no source weights and starts as the identity
    _st_conv(params, stats, ("net", "conv1"), sd, "res2plus1d.conv1.")
    _identity_bn(params, stats, ("net", "stem_bn"), 64)

    for layer in range(2, NUM_LAYERS + 1):
        blocks = int(layer_sizes[layer - 2])
        downsample = layer >= 3
        lname = "conv%d" % layer
        for block in range(blocks):
            # torch names the first block `block1` and the rest
            # `blocks.{i}`; we name them block0..block{n-1}
            tprefix = ("res2plus1d.%s.block1." % lname if block == 0
                       else "res2plus1d.%s.blocks.%d." % (lname, block - 1))
            fpath = ("net", lname, "block%d" % block)
            _st_conv(params, stats, fpath + ("conv1",), sd,
                     tprefix + "conv1.")
            _bn(params, stats, fpath + ("bn1",), "bn1", sd, tprefix)
            _st_conv(params, stats, fpath + ("conv2",), sd,
                     tprefix + "conv2.")
            _bn(params, stats, fpath + ("bn2",), "bn2", sd, tprefix)
            if block == 0 and downsample:
                _st_conv(params, stats, fpath + ("shortcut",), sd,
                         tprefix + "downsampleconv.")
                _bn(params, stats, fpath + ("shortcut_bn",),
                    "downsamplebn", sd, tprefix)

    _set(params, ("linear", "kernel"),
         np.ascontiguousarray(_np(sd, "linear.weight", 2).T))
    _set(params, ("linear", "bias"), _np(sd, "linear.bias", 1))

    variables = {"params": params, "batch_stats": stats}
    if validate:
        validate_variables(variables, num_classes=num_classes,
                           layer_sizes=layer_sizes)
    return variables


def validate_variables(variables: Dict[str, Any],
                       num_classes: int = KINETICS_CLASSES,
                       layer_sizes: Sequence[int] = R18_LAYER_SIZES
                       ) -> None:
    """Check a converted tree against the target architecture: same
    leaf paths, same shapes (abstract init — no real compute)."""
    import jax

    from rnb_tpu.models.r2p1d.network import R2Plus1DClassifier

    model = R2Plus1DClassifier(num_classes=num_classes,
                               layer_sizes=tuple(layer_sizes),
                               factored_shortcut=True)
    x = jax.ShapeDtypeStruct((1, 2, 14, 14, 3), np.float32)
    want = jax.eval_shape(
        lambda k, x: model.init(k, x, train=False), jax.random.key(0), x)

    want_leaves = {
        "/".join(str(k.key) for k in path): leaf.shape
        for path, leaf in jax.tree_util.tree_flatten_with_path(want)[0]}
    got_leaves = {
        "/".join(str(k.key) for k in path): np.shape(leaf)
        for path, leaf in
        jax.tree_util.tree_flatten_with_path(variables)[0]}

    missing = sorted(set(want_leaves) - set(got_leaves))
    extra = sorted(set(got_leaves) - set(want_leaves))
    if missing or extra:
        raise ConversionError(
            "converted tree structure mismatch: missing %s, unexpected %s"
            % (missing[:5], extra[:5]))
    for key, want_shape in want_leaves.items():
        if tuple(got_leaves[key]) != tuple(want_shape):
            raise ConversionError(
                "converted %s has shape %r, architecture wants %r"
                % (key, tuple(got_leaves[key]), tuple(want_shape)))


def convert_file(pth_path: str, out_path: str,
                 num_classes: int = KINETICS_CLASSES,
                 layer_sizes: Sequence[int] = R18_LAYER_SIZES) -> str:
    """Unpickle a reference ``.pth.tar`` (torch required), convert and
    save as this framework's msgpack checkpoint. Returns ``out_path``."""
    import torch

    from rnb_tpu.models.r2p1d import checkpoint as ckpt

    payload = torch.load(pth_path, map_location="cpu",
                         weights_only=False)
    state_dict = payload.get("state_dict", payload)
    state_dict = {k: v.detach().cpu().numpy() if hasattr(v, "detach")
                  else v for k, v in state_dict.items()}
    variables = convert_state_dict(state_dict, num_classes=num_classes,
                                   layer_sizes=layer_sizes)
    ckpt.save_checkpoint(out_path, variables)
    return out_path


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser(
        description="Convert a reference R(2+1)D torch checkpoint to "
                    "the rnb_tpu msgpack format")
    parser.add_argument("pth_path")
    parser.add_argument("out_path")
    args = parser.parse_args()
    print(convert_file(args.pth_path, args.out_path))
