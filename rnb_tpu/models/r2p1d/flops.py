"""Analytic FLOP counter for R(2+1)D layer ranges.

Walks exactly the convolution schedule of :mod:`.network` (stem
(2+1)D conv, residual stages with factored pairs, projection shortcuts,
classification head) and counts multiply-accumulates as 2 FLOPs, the
MFU convention. Elementwise work (BatchNorm, ReLU, residual adds,
pooling) is excluded — on any matmul-class accelerator it is bandwidth,
not FLOPs, and XLA fuses it into the convs anyway.

The numbers feed the benchmark's ``tflops``/``mfu`` line (bench.py) and
are cross-checked in tests against XLA's own ``cost_analysis()`` of the
compiled program, so the analytic walk cannot silently drift from the
network it claims to describe.

Reference context: the reference never measured device utilization — its
methodology stopped at videos/sec (reference README.md:176-185). MFU is
the evidence this framework adds on top.
"""

from __future__ import annotations

from typing import Sequence, Tuple

from rnb_tpu.models.r2p1d.network import (KINETICS_CLASSES, LAYER_FEATURES,
                                          LAYER_INPUT_SHAPES, NUM_LAYERS,
                                          R18_LAYER_SIZES,
                                          factored_channels)

#: Dense bf16 peak TFLOP/s per *jax.Device* by device_kind, for the MFU
#: denominator. v2/v3 report one device per core (chip peak halved);
#: v4 onward one device per chip (megacore). Public spec-sheet numbers.
TPU_PEAK_TFLOPS = {
    "TPU v2": 22.5,
    "TPU v3": 61.5,
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5": 459.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
    "TPU7x": 2307.0,
}


def peak_tflops_for(device_kind: str):
    """Peak lookup for a ``jax.Device.device_kind`` string; None when
    the platform is unknown (mfu is then unreported rather than wrong).
    Exact match only — a prefix fallback would hand e.g. a 'TPU v4
    lite' variant the full v4 peak and silently corrupt the published
    MFU; unknown kinds belong in the table, not guessed."""
    return TPU_PEAK_TFLOPS.get(device_kind.strip())


def _conv_out(extent: int, kernel: int, stride: int, pad: int) -> int:
    return (extent + 2 * pad - kernel) // stride + 1


def _st_conv_flops(t_in: int, h: int, w: int, c_in: int, c_out: int,
                   kernel: Tuple[int, int], stride: Tuple[int, int]
                   ) -> Tuple[int, Tuple[int, int, int]]:
    """FLOPs + output dims of one factored SpatioTemporalConv
    (network.py SpatioTemporalConv: spatial (1,d,d) conv to the
    parameter-matched mid width, then temporal (t,1,1) conv)."""
    kt, kd = kernel
    st, sd = stride
    mid = factored_channels(c_in, c_out, kt, kd)
    h_out = _conv_out(h, kd, sd, kd // 2)
    w_out = _conv_out(w, kd, sd, kd // 2)
    spatial = 2 * t_in * h_out * w_out * mid * (kd * kd * c_in)
    t_out = _conv_out(t_in, kt, st, kt // 2)
    temporal = 2 * t_out * h_out * w_out * c_out * (kt * mid)
    return spatial + temporal, (t_out, h_out, w_out)


def range_flops_per_clip(start: int = 1, end: int = NUM_LAYERS,
                         consecutive_frames: int = 8,
                         num_classes: int = KINETICS_CLASSES,
                         layer_sizes: Sequence[int] = R18_LAYER_SIZES,
                         frame_hw: int = None,
                         factored_shortcut: bool = False) -> int:
    """Conv+dense FLOPs for ONE clip row through layers [start..end].

    ``frame_hw``/``consecutive_frames`` describe the *layer-1* input
    geometry; for ``start > 1`` the walk derives the range's input dims
    from the downsampling schedule (same rule as
    network.range_output_shape), so partial ranges stay consistent with
    whatever geometry the pipeline actually flows.
    """
    if not (1 <= start <= end <= NUM_LAYERS):
        raise ValueError("invalid layer range [%s..%s]" % (start, end))
    t = int(consecutive_frames)
    h = w = int(frame_hw) if frame_hw is not None else \
        LAYER_INPUT_SHAPES[1][1]
    c = 3
    for layer in range(1, start):  # walk dims up to the range's input
        if layer == 1:
            h, w, c = -(-h // 2), -(-w // 2), 64
        else:
            c = LAYER_FEATURES[layer]
            if layer >= 3:
                t, h, w = -(-t // 2), -(-h // 2), -(-w // 2)
    total = 0
    for layer in range(start, end + 1):
        if layer == 1:
            flops, (t, h, w) = _st_conv_flops(t, h, w, c, 64,
                                              kernel=(3, 7), stride=(1, 2))
            total += flops
            c = 64
            continue
        c_out = LAYER_FEATURES[layer]
        downsample = layer >= 3
        for block in range(layer_sizes[layer - 2]):
            block_down = downsample and block == 0
            stride = 2 if block_down else 1
            if block_down:
                if factored_shortcut:
                    flops, _ = _st_conv_flops(t, h, w, c, c_out,
                                              kernel=(1, 1),
                                              stride=(2, 2))
                    total += flops
                else:
                    t_s = _conv_out(t, 1, 2, 0)
                    h_s = _conv_out(h, 1, 2, 0)
                    w_s = _conv_out(w, 1, 2, 0)
                    total += 2 * t_s * h_s * w_s * c_out * c
            flops, (t2, h2, w2) = _st_conv_flops(
                t, h, w, c, c_out, kernel=(3, 3), stride=(stride, stride))
            total += flops
            flops, _ = _st_conv_flops(t2, h2, w2, c_out, c_out,
                                      kernel=(3, 3), stride=(1, 1))
            total += flops
            t, h, w, c = t2, h2, w2, c_out
    if end == NUM_LAYERS:
        total += 2 * c * num_classes  # classification head
    return int(total)
