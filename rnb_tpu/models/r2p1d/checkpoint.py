"""Checkpoint save/load with per-layer-range filtering.

The reference loaded a pretrained Kinetics-400 torch checkpoint from a
hardcoded path and filtered the state dict so a partial-network stage
only received its own layers' weights (reference
models/r2p1d/model.py:18,50-63). This module provides the same
capability on Flax variable trees (msgpack on disk): a full-model
checkpoint is filtered down to exactly the modules a [start..end]
range instantiates, so every stage of a partitioned pipeline shares one
set of weights.

No pretrained weights are available in this environment, so
:func:`ensure_checkpoint` materializes a deterministic seeded
initialization once and reuses it — every stage and every process
loads identical weights, which is what the parity benchmarks need.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

import numpy as np

from rnb_tpu.models.r2p1d.network import (KINETICS_CLASSES,
                                          LAYER_INPUT_SHAPES, NUM_LAYERS,
                                          R18_LAYER_SIZES,
                                          R2Plus1DClassifier)

DEFAULT_CKPT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))), "checkpoints")
DEFAULT_CKPT_PATH = os.path.join(DEFAULT_CKPT_DIR,
                                 "r2p1d18_kinetics400.msgpack")

_ensure_lock = threading.Lock()


def init_variables(seed: int = 0, start: int = 1, end: int = NUM_LAYERS,
                   num_classes: int = KINETICS_CLASSES,
                   layer_sizes=None,
                   factored_shortcut: bool = False) -> Dict[str, Any]:
    """Seeded init of the [start..end] classifier's variables
    (params + batch_stats).

    Conv/BN/Dense parameter shapes are independent of the spatial and
    temporal extent, so init traces a tiny dummy under jit — orders of
    magnitude cheaper than tracing the real 112x112x8 shape.
    """
    import jax
    kwargs = {} if layer_sizes is None else {"layer_sizes": layer_sizes}
    model = R2Plus1DClassifier(start=start, end=end,
                               num_classes=num_classes,
                               factored_shortcut=factored_shortcut,
                               **kwargs)
    channels = LAYER_INPUT_SHAPES[start][-1]
    dummy = np.zeros((1, 2, 14, 14, channels), dtype=np.float32)
    init = jax.jit(lambda key: model.init(key, dummy, train=False))
    return jax.tree.map(np.asarray, init(jax.random.key(seed)))


def save_checkpoint(path: str, variables: Dict[str, Any]) -> None:
    from flax import serialization
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(serialization.msgpack_serialize(
            serialization.to_state_dict(variables)))
    os.replace(tmp, path)


def load_checkpoint(path: str) -> Dict[str, Any]:
    from flax import serialization
    with open(path, "rb") as f:
        return serialization.msgpack_restore(f.read())


def ensure_checkpoint(path: Optional[str] = None, seed: int = 0) -> str:
    """Create the shared full-model checkpoint if absent; return path."""
    path = path or DEFAULT_CKPT_PATH
    with _ensure_lock:
        if not os.path.exists(path):
            save_checkpoint(path, init_variables(seed=seed))
    return path


def _range_module_names(start: int, end: int) -> set:
    names = set()
    for layer in range(start, end + 1):
        names.add("conv%d" % layer)
        if layer == 1:
            names.add("stem_bn")
    return names


def filter_layer_range(variables: Dict[str, Any], start: int,
                       end: int) -> Dict[str, Any]:
    """Restrict a full-model variable tree to a layer range.

    Keeps ``net/conv{i}`` (plus the stem BN with layer 1) for i in
    [start..end] and the ``linear`` head only when the range reaches the
    final layer — the same per-range weight filtering the reference
    applied to torch state dicts (models/r2p1d/model.py:52-63).
    """
    if not (1 <= start <= end <= NUM_LAYERS):
        raise ValueError("invalid layer range [%s..%s]" % (start, end))
    keep = _range_module_names(start, end)
    out: Dict[str, Any] = {}
    for collection, tree in variables.items():
        new_tree: Dict[str, Any] = {}
        net = tree.get("net", {})
        kept_net = {name: sub for name, sub in net.items() if name in keep}
        if kept_net:
            new_tree["net"] = kept_net
        if end == NUM_LAYERS and "linear" in tree:
            new_tree["linear"] = tree["linear"]
        out[collection] = new_tree
    return out


def load_for_range(start: int, end: int,
                   path: Optional[str] = None) -> Dict[str, Any]:
    """Load the shared checkpoint filtered to [start..end]."""
    return filter_layer_range(load_checkpoint(ensure_checkpoint(path)),
                              start, end)


def load_or_init(start: int, end: int,
                 num_classes: int = KINETICS_CLASSES,
                 layer_sizes=R18_LAYER_SIZES,
                 path: Optional[str] = None,
                 factored_shortcut: bool = False) -> Dict[str, Any]:
    """The one checkpoint policy every execution path shares:

    * an explicit existing ``path`` wins for any architecture — that is
      how partitioned stages of a non-default (tiny/test) model share
      one set of weights, and how converted external checkpoints
      (checkpoint_convert) are loaded;
    * otherwise the default architecture loads the shared
      (range-filtered, materialized-once) checkpoint;
    * any other architecture gets a fresh seeded init.
    """
    if path is not None:
        # an explicit path must exist: silently materializing a fresh
        # seeded init at a mistyped path would run the benchmark on
        # random weights while the user believes they loaded pretrained
        # ones
        if not os.path.exists(path):
            raise FileNotFoundError(
                "explicit ckpt_path %r does not exist; convert or save "
                "a checkpoint there first (models/r2p1d/convert.py)"
                % (path,))
        return filter_layer_range(load_checkpoint(path), start, end)
    # the shared materialized checkpoint is the default (plain-shortcut)
    # architecture; a factored-shortcut model without an explicit
    # converted checkpoint gets a fresh matching init instead
    if not factored_shortcut and (
            num_classes, tuple(layer_sizes)) == (KINETICS_CLASSES,
                                                 tuple(R18_LAYER_SIZES)):
        return load_for_range(start, end)
    return init_variables(start=start, end=end, num_classes=num_classes,
                          layer_sizes=tuple(layer_sizes),
                          factored_shortcut=factored_shortcut)
