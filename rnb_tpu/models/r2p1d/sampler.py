"""Clip sampling: which 8-frame windows of a video get inferred.

A video becomes 1..15 clips of ``consecutive_frames`` frames. The clip
count is drawn from a skewed two-point distribution (~91% small 1-clip
videos, ~9% large 15-clip videos) — the workload skew that motivates
content-aware Large/Small routing. Clips are spread evenly across the
video with a random global offset, recursively falling back to fewer
clips when the video is too short.

Capability parity with the reference sampler
(models/r2p1d/sampler.py:21-62), re-implemented standalone: no NVVL
``Sampler`` base class exists here — decoders consume the start-index
list directly. Sampling is deterministic per video id (seeded by a
CRC32 of the id) so runs are reproducible; pass an explicit ``rng`` to
restore global randomness.
"""

from __future__ import annotations

import zlib
from typing import List, Optional, Sequence

import numpy as np

DEFAULT_NUM_CLIPS_POPULATION = (1, 15)
DEFAULT_NUM_CLIPS_WEIGHTS = (10, 1)


class ClipSampler:
    """Contract: map a video's frame count to clip start indices."""

    consecutive_frames: int = 8

    def sample(self, num_frames: int, video_id: Optional[str] = None
               ) -> List[int]:
        raise NotImplementedError


class R2P1DSampler(ClipSampler):
    def __init__(self,
                 consecutive_frames: int = 8,
                 num_clips_population: Sequence[int] =
                 DEFAULT_NUM_CLIPS_POPULATION,
                 weights: Sequence[float] = DEFAULT_NUM_CLIPS_WEIGHTS,
                 rng: Optional[np.random.Generator] = None):
        if len(num_clips_population) != len(weights):
            raise ValueError("population and weights length mismatch")
        self.consecutive_frames = int(consecutive_frames)
        self.num_clips_population = list(num_clips_population)
        w = np.asarray(weights, dtype=np.float64)
        self.probabilities = w / w.sum()
        self._rng = rng

    @property
    def max_clips(self) -> int:
        return max(self.num_clips_population)

    def _rng_for(self, video_id: Optional[str]) -> np.random.Generator:
        if self._rng is not None:
            return self._rng
        seed = zlib.crc32(str(video_id).encode()) if video_id is not None \
            else None
        return np.random.default_rng(seed)

    def choose_num_clips(self, video_id: Optional[str] = None) -> int:
        rng = self._rng_for(video_id)
        return int(rng.choice(self.num_clips_population,
                              p=self.probabilities))

    def sample(self, num_frames: int, video_id: Optional[str] = None,
               num_clips: Optional[int] = None) -> List[int]:
        """Evenly-spread clip start indices with a random global offset.

        With stride ``num_frames // num_clips``, clip i starts at
        ``i * stride + offset`` where the offset is drawn from the slack
        within one stride. When the video cannot hold ``num_clips``
        non-overlapping windows, retry with fewer clips (reference
        recursion, models/r2p1d/sampler.py:37-53).
        """
        f = self.consecutive_frames
        if num_frames < f:
            raise ValueError(
                "video of %d frames is shorter than one clip (%d frames)"
                % (num_frames, f))
        rng = self._rng_for(video_id)
        if num_clips is None:
            num_clips = int(rng.choice(self.num_clips_population,
                                       p=self.probabilities))
        while num_clips > 1 and num_clips * f > num_frames:
            num_clips -= 1
        stride = num_frames // num_clips
        slack = stride - f
        offset = int(rng.integers(0, slack + 1)) if slack > 0 else 0
        return [i * stride + offset for i in range(num_clips)]
