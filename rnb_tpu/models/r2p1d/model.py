"""R(2+1)D pipeline stages: loader, partial-net runner, fused
single-step, logit aggregator, path iterator, Large/Small router.

Capability parity with the reference stage library
(models/r2p1d/model.py:1-296), re-designed for the TPU runtime:

* the loader decodes on the host (no NVDEC on TPU; see rnb_tpu.decode)
  and immediately re-homes padded uint8 clips onto its TPU core where a
  jitted preprocess casts/normalizes to bfloat16 NDHWC — decode cost on
  host threads, math on device;
* every stage computes on static-shape batches with valid-row counts —
  one max shape per topology, or a small fixed set of row buckets when
  ``row_buckets`` is configured — so XLA compiles a bounded number of
  executables, never per-request shapes;
* jitted appliers and device-resident weights are cached per
  (layer-range, device) so N replicas on one device share one
  executable and one parameter copy.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Any, Dict, Optional, Tuple

import numpy as np

from rnb_tpu import hostprof, trace
from rnb_tpu.autotune import BatchController
from rnb_tpu.cache import content_key
from rnb_tpu.compilestats import SignatureTracker
from rnb_tpu.decode import get_decoder
from rnb_tpu.devices import DeviceSpec
from rnb_tpu.decode.native import (DecodePool, NativeY4MDecoder, PIX_DCT,
                                   PIX_RGB, PIX_YUV420,
                                   default_decode_threads,
                                   native_available)
from rnb_tpu.faults import (FATAL, TRANSIENT, TransientDecodeError,
                            classify_error, fault_reason)
from rnb_tpu.health import expired as _deadline_expired
from rnb_tpu.models.r2p1d import checkpoint as ckpt
from rnb_tpu.models.r2p1d.network import (KINETICS_CLASSES,
                                          LAYER_INPUT_SHAPES, NUM_LAYERS,
                                          R2Plus1DClassifier,
                                          R18_LAYER_SIZES)
from rnb_tpu.models.r2p1d.sampler import R2P1DSampler
from rnb_tpu.ops.dct import dct_frame_elems, default_dct_coeffs
from rnb_tpu.ops.ragged import resolve_pool_rows, segment_offsets_of
from rnb_tpu.ops.yuv import packed_frame_bytes
from rnb_tpu.selector import QueueSelector
from rnb_tpu.stage import (PadCounter, PaddedBatch, RaggedBatch,
                           StageModel, normalize_row_buckets,
                           note_emission_accounting)
from rnb_tpu.staging import StagingPool, TransferWorker
from rnb_tpu.telemetry import TimeCard, TimeCardList
from rnb_tpu.utils.lazy_jax import jax_numpy as _jax_numpy
from rnb_tpu import video_path_provider
from rnb_tpu.video_path_provider import VideoPathIterator

MAX_CLIPS = 15
CONSECUTIVE_FRAMES = 8
FRAME_HW = 112
NUM_WARMUPS = 3  # reference warm-up convention (models/r2p1d/model.py:65-71)

_cache_lock = threading.Lock()
_apply_cache: Dict[tuple, Any] = {}
_params_cache: Dict[tuple, Any] = {}
_preprocess_cache: Dict[tuple, Any] = {}


def _record_clamped(card, key: str, at: float) -> None:
    """Record a phase-refinement stamp (rnb_tpu.trace) no earlier
    than the card's latest stamp: each card's stamps must stay
    time-ordered or attribution gaps go negative — e.g. a coalesced
    follower can be swallowed AFTER its leader's decode completed, so
    its decode phase legitimately clamps to zero."""
    if card.timings:
        last = next(reversed(card.timings.values()))
        if at < last:
            at = last
    card.record(key, at=at)


def _resolve(device):
    """Accept a DeviceSpec or a raw jax.Device."""
    return device.resolve() if hasattr(device, "resolve") else device


#: shared bucket validation (rnb_tpu.stage) — loader and Batcher must
#: reject a typo'd bucket set identically
_normalize_row_buckets = normalize_row_buckets


def default_ragged_chunk(pool_rows: int) -> int:
    """Auto row-chunk for the ragged applier's dynamic grid: the
    largest divisor of the pool capacity no bigger than a third of it
    (so a typical partial pool skips real work), floored at 1. 15 ->
    5, 12 -> 4, 2 -> 1."""
    pool_rows = int(pool_rows)
    cap = max(1, pool_rows // 3)
    for d in range(cap, 0, -1):
        if pool_rows % d == 0:
            return d
    return 1


def _shared_apply(start: int, end: int, num_classes: int,
                  layer_sizes: tuple, factored_shortcut: bool = False,
                  pixel_path: str = "rgb", ragged: bool = False,
                  ragged_chunk: int = 0):
    """One jitted inference applier shared by every replica of a range.

    ``pixel_path="yuv420"`` (layer-1 stages only) prepends the fused
    ingest — packed 4:2:0 planes -> chroma upsample -> BT.601 ->
    normalize (rnb_tpu/ops/yuv.py) — inside the same jit, so XLA fuses
    the colourspace math with the first convolution's input pipeline.

    ``ragged`` swaps the contract for the ragged row-pool one
    (rnb_tpu/ops/ragged.py): the applier takes the flat pool plus a
    *traced* ``rows_valid`` scalar and compiles exactly ONCE for any
    batch composition — for yuv420 the fused ingest masks the pool
    tail at the u8 level first. With ``ragged_chunk`` > 0 (must
    divide the pool capacity) the network body runs as a dynamic grid
    over fixed ``ragged_chunk``-row tiles: a ``fori_loop`` whose trip
    count is ``ceil(rows_valid / chunk)``, so network FLOPs scale
    with the valid rows (rounded up to one tile) instead of the pool
    capacity — the CPU/compile-once analog of the TPU kernel's
    ``pl.when`` grid skip, and bit-identical per row (tiles of any
    size produce the same per-row outputs; asserted in
    tests/test_ragged.py). ``ragged_chunk=0`` applies the whole pool
    in one call (preferable on real TPUs, where the MXU wants the
    large batch and the Pallas ingest already skips pad arithmetic).
    """
    key = (start, end, num_classes, layer_sizes, factored_shortcut,
           pixel_path, bool(ragged), int(ragged_chunk))
    with _cache_lock:
        fn = _apply_cache.get(key)
        if fn is None:
            import jax
            model = R2Plus1DClassifier(start=start, end=end,
                                       num_classes=num_classes,
                                       layer_sizes=layer_sizes,
                                       factored_shortcut=factored_shortcut)

            if ragged:
                if pixel_path == "yuv420":
                    from rnb_tpu.ops.ragged import ragged_normalize_yuv420

                    def ingest(x, rows_valid):
                        return ragged_normalize_yuv420(
                            x, rows_valid, FRAME_HW, FRAME_HW)
                elif pixel_path == "dct":
                    from rnb_tpu.ops.dct import ragged_normalize_dct

                    def ingest(x, rows_valid):
                        return ragged_normalize_dct(
                            x, rows_valid, FRAME_HW, FRAME_HW)
                else:
                    # rgb/mid-pipeline pools arrive already normalized
                    # and masked by the producing loader's ragged
                    # preprocess
                    def ingest(x, rows_valid):
                        del rows_valid
                        return x
                chunk = int(ragged_chunk)

                def apply(variables, x, rows_valid):
                    import jax.numpy as jnp
                    from jax import lax
                    xin = ingest(x, rows_valid)
                    if chunk <= 0 or chunk >= xin.shape[0]:
                        return model.apply(variables, xin, train=False)

                    def tile(i):
                        part = lax.dynamic_slice_in_dim(
                            xin, i * chunk, chunk, axis=0)
                        return model.apply(variables, part, train=False)

                    # tile 0 is computed unconditionally (every real
                    # emission carries >= 1 valid row) — it also fixes
                    # the output row shape/dtype without re-tracing
                    first = tile(0)
                    out = lax.dynamic_update_slice_in_dim(
                        jnp.zeros((xin.shape[0],) + first.shape[1:],
                                  first.dtype), first, 0, axis=0)
                    num_tiles = jnp.minimum(
                        (rows_valid + chunk - 1) // chunk,
                        xin.shape[0] // chunk)

                    def body(i, acc):
                        return lax.dynamic_update_slice_in_dim(
                            acc, tile(i), i * chunk, axis=0)

                    return lax.fori_loop(1, num_tiles, body, out)
            elif pixel_path == "yuv420":
                from rnb_tpu.ops.yuv import normalize_yuv420

                def apply(variables, x):
                    return model.apply(variables, normalize_yuv420(
                        x, FRAME_HW, FRAME_HW), train=False)
            elif pixel_path == "dct":
                from rnb_tpu.ops.dct import normalize_dct

                def apply(variables, x):
                    return model.apply(variables, normalize_dct(
                        x, FRAME_HW, FRAME_HW), train=False)
            else:
                def apply(variables, x):
                    return model.apply(variables, x, train=False)

            fn = jax.jit(apply)
            _apply_cache[key] = fn
        return fn


def _shared_params(start: int, end: int, num_classes: int,
                   layer_sizes: tuple, ckpt_path: Optional[str], device,
                   factored_shortcut: bool = False):
    """Device-resident filtered weights, one copy per (range, device)."""
    import jax
    key = (start, end, num_classes, layer_sizes, ckpt_path, id(device),
           factored_shortcut)
    with _cache_lock:
        params = _params_cache.get(key)
        if params is None:
            variables = ckpt.load_or_init(
                start, end, num_classes, layer_sizes, ckpt_path,
                factored_shortcut=factored_shortcut)
            params = jax.device_put(variables, device)
            _params_cache[key] = params
        return params


def _shared_preprocess(device):
    """Jitted uint8 -> normalized bfloat16 cast, one per device."""
    key = id(device)
    with _cache_lock:
        fn = _preprocess_cache.get(key)
        if fn is None:
            import jax
            from rnb_tpu.models.r2p1d.network import normalize_u8
            fn = jax.jit(normalize_u8)
            _preprocess_cache[key] = fn
        return fn


def _shared_ragged_preprocess(device):
    """Jitted ragged uint8 pool -> normalized bfloat16, one per
    device: the ragged forward primitive (rnb_tpu/ops/ragged.py) with
    a *traced* rows_valid scalar — one executable serves every batch
    composition, and rows past rows_valid cost no arithmetic on the
    TPU grid-skip path."""
    key = ("ragged", id(device))
    with _cache_lock:
        fn = _preprocess_cache.get(key)
        if fn is None:
            import jax
            from rnb_tpu.ops.ragged import ragged_normalize_u8

            def preprocess(pool, rows_valid):
                return ragged_normalize_u8(pool, rows_valid)

            fn = jax.jit(preprocess)
            _preprocess_cache[key] = fn
        return fn


#: ceiling on one fallback-pool decode's wait: far above any real
#: decode (tiny y4m/MJPEG clips decode in milliseconds), so hitting it
#: is a liveness verdict on the worker thread, not a slow file
FALLBACK_DECODE_TIMEOUT_S = 120.0


class _DecodeHandle:
    """In-flight decode work submitted ahead of its turn.

    Mirrors what NVVL's async ``loadfile`` represented (reference
    README.md:46-110): decode has been kicked off, ``wait()`` blocks
    until the clip batch is materialized in ``out``.

    Cache/coalescing variants (rnb_tpu.cache): a ``cached`` handle
    carries a device-resident hit and owns no decode work at all; a
    ``leader`` handle is a coalesced follower that shares another
    in-flight request's decode. A failed ``wait()`` remembers its
    error and re-raises it on every later wait, so a follower parked
    on a failed leader observes the same classified failure instead
    of silently reading a garbage buffer.
    """

    __slots__ = ("out", "n", "pool", "tickets", "future", "cached",
                 "leader", "key", "error", "slot", "row0",
                 "gather_plan", "feature_plan")

    def __init__(self, out, n, pool=None, tickets=None, future=None,
                 cached=None, leader=None, key=None, slot=None,
                 row0=0):
        self.out = out          # uint8 (n, F, H, W, 3), filled async
        self.n = n              # valid clip count
        self.pool = pool        # the DecodePool the tickets belong to
        self.tickets = tickets  # native DecodePool tickets, or None
        self.future = future    # fallback executor future, or None
        self.cached = cached    # CacheEntry on a cache hit, or None
        self.leader = leader    # coalesced: the leader's handle, or None
        self.key = key          # cache key of this decode, or None
        self.error = None       # sticky decode failure (see class doc)
        self.slot = slot        # StagingSlot the decode targets, or None
        self.row0 = row0        # first row of this decode in the slot
        self.gather_plan = None  # pinned paged-cache hit (rnb_tpu.pager)
        self.feature_plan = None  # pinned feature-page hit, or None

    def wait(self, video: str = "<video>") -> None:
        if self.leader is not None:
            self.leader.wait(video)
            self.out = self.leader.out
            return
        if self.error is not None:
            raise self.error
        try:
            if self.tickets:
                first_error = None
                for ticket in self.tickets:
                    try:
                        self.pool.wait(ticket, video)
                    except ValueError as e:
                        first_error = first_error or e
                self.tickets = None
                if first_error is not None:
                    raise first_error
            if self.future is not None:
                # bounded wait + liveness verdict (the RNB-H009
                # discipline): a wedged fallback-pool decode thread
                # dead-letters ONE request as a classified transient
                # instead of hanging the stage — and, behind it, the
                # whole replica lane — forever
                try:
                    self.future.result(
                        timeout=FALLBACK_DECODE_TIMEOUT_S)
                except FuturesTimeout:
                    raise TransientDecodeError(
                        "fallback decode of %s unresponsive for %.0fs"
                        % (video, FALLBACK_DECODE_TIMEOUT_S)) from None
                self.future = None
        except Exception as e:
            self.error = e
            raise

    @property
    def ready(self) -> bool:
        """Non-blocking: has the decode finished? (wait() still
        required to retire tickets / surface errors.)"""
        if self.leader is not None:
            return self.leader.ready
        if self.tickets:
            return all(self.pool.peek(t) for t in self.tickets)
        if self.future is not None:
            return self.future.done()
        return True


class R2P1DLoader(StageModel):
    """Decode stage: video path/id -> padded bf16 clip batch on device.

    Reference equivalent: R2P1DLoader over NVVL
    (models/r2p1d/model.py:116-158). Samples 1..max_clips clips, decodes
    them on the host, pads to the static max shape, transfers once to
    the stage device and normalizes there. Stamps ``num_clips`` on the
    TimeCard for content-aware routing.

    **Prefetch** (NVVL parity, reference README.md:46-110): with a
    ``prefetch`` depth configured, the stage exposes ``submit()`` /
    ``complete()`` and the executor kicks off decode of request N+1..N+k
    while request N's device work runs — native-pool tickets for .y4m
    files, a small thread pool for the numpy/synthetic backends. The
    TimeCard decode span (``inference{i}``) then measures only the
    *residual* wait, which is exactly the overlap being bought.
    """

    #: transfer_async moves ``device_put`` to a dedicated worker thread
    #: between emissions — only meaningful for a stage that emits
    #: asynchronously of its model call (the fusing loader); the plain
    #: loader's complete() contract is synchronous
    SUPPORTS_TRANSFER_ASYNC = False

    #: emissions can ship as a flat row pool at ONE compiled shape
    #: with a rows_valid count + per-request segment offsets instead
    #: of padding to buckets (root 'ragged' config key; the launcher
    #: injects the kwargs — rnb_tpu.ops.ragged)
    SUPPORTS_RAGGED = True

    #: with the root 'pager' config key the clip cache's blob storage
    #: becomes page-table entries in a pager arena and hits gather on
    #: device with zero host bytes (rnb_tpu.pager; enable_pager below)
    SUPPORTS_PAGER = True

    def __init__(self, device, max_clips: int = MAX_CLIPS,
                 consecutive_frames: int = CONSECUTIVE_FRAMES,
                 num_clips_population=None, weights=None,
                 num_warmups: int = NUM_WARMUPS,
                 raw_output: bool = False,
                 row_buckets=None, prefetch: int = 0,
                 pixel_path: str = "rgb", cache_mb: float = 0,
                 staging_slots=None, transfer_async: bool = False,
                 fallback_decode_threads=None,
                 ragged: bool = False, ragged_pool_rows=None,
                 dct_coeffs_per_frame=None,
                 **kwargs):
        super().__init__(device)
        import jax
        self._jax_device = _resolve(device)
        #: raw mode emits the padded uint8 batch itself (half the bytes
        #: of bf16 on the wire) for consumers that normalize on their
        #: own mesh, e.g. R2P1DMeshRunner
        self.raw_output = bool(raw_output)
        # "yuv420": host decode stops at packed output-res 4:2:0 planes
        # (pure gathers, 1.5 bytes/pixel on the wire); the consuming
        # network stage fuses upsample+BT.601+normalize into its jit
        # (rnb_tpu/ops/yuv.py). The benchmark host's single core is the
        # throughput ceiling (RESULTS.md), so moving the colourspace
        # arithmetic on-device lifts end-to-end throughput directly.
        # "dct": the MJPEG decode stops at entropy-decoded, dequantized
        # DCT coefficients shipped as packed sparse int16 rows
        # (rnb_tpu/ops/dct.py — ~0.5x the yuv420 wire bytes at the
        # default budget); IDCT + chroma upsample + BT.601 + normalize
        # run fused on-device ahead of conv1, deleting the host's
        # remaining per-pixel work.
        if pixel_path not in ("rgb", "yuv420", "dct"):
            raise ValueError("pixel_path must be 'rgb', 'yuv420' or "
                             "'dct', got %r" % (pixel_path,))
        # raw_output + yuv420 composes: the loader ships packed planes
        # and the mesh consumer's sharded program runs the fused yuv
        # ingest (configure the SAME pixel_path on both stages)
        self.pixel_path = pixel_path
        self.dct_coeffs = None
        if pixel_path == "dct":
            if raw_output:
                raise ValueError(
                    "pixel_path='dct' cannot combine with raw_output: "
                    "mesh consumers ingest raw pixel batches, not "
                    "packed coefficient rows")
            self.dct_coeffs = (int(dct_coeffs_per_frame)
                               if dct_coeffs_per_frame is not None
                               else default_dct_coeffs(FRAME_HW,
                                                       FRAME_HW))
            if self.dct_coeffs < 1:
                raise ValueError("dct_coeffs_per_frame must be >= 1, "
                                 "got %r" % (dct_coeffs_per_frame,))
        elif dct_coeffs_per_frame is not None:
            raise ValueError("dct_coeffs_per_frame only applies to "
                             "pixel_path='dct'")
        #: the wire dtype every decode/staging/transfer buffer of this
        #: stage uses: int16 packed coefficient rows under dct, u8
        #: pixel/plane rows otherwise
        self._wire_dtype = (np.int16 if pixel_path == "dct"
                            else np.uint8)
        sampler_kwargs = {}
        if num_clips_population is not None:
            sampler_kwargs["num_clips_population"] = num_clips_population
        if weights is not None:
            sampler_kwargs["weights"] = weights
        self.sampler = R2P1DSampler(consecutive_frames=consecutive_frames,
                                    **sampler_kwargs)
        self.max_clips = int(max_clips)
        self.consecutive_frames = int(consecutive_frames)
        # Row bucketing: pad each video to the smallest bucket >= its
        # clip count instead of always to max_clips. jit caches one
        # executable per bucket shape, so with the default skewed clip
        # population ([1,15]@[10,1], sampler.py) ~91% of videos move
        # and compute 15x less than max-shape padding. Opt-in per
        # config; downstream stages must warm the same buckets.
        self.row_buckets = _normalize_row_buckets(row_buckets,
                                                  self.max_clips,
                                                  "max_clips")
        # Ragged row-pool dispatch (rnb_tpu.ops.ragged): every emission
        # ships the ONE pool shape with an explicit rows_valid + per-
        # request segment offsets — no bucket padding, one warmup
        # compile, continuous autotune. row_buckets, if configured,
        # stop being shipped shapes and become the COUNTERFACTUAL pad
        # rule the pad_rows_eliminated counter is measured against.
        self.ragged = bool(ragged)
        self.pool_rows = (resolve_pool_rows(ragged_pool_rows,
                                            self.max_clips, "max_clips")
                          if self.ragged else None)
        if self.ragged and self.raw_output:
            raise ValueError("ragged cannot be combined with "
                             "raw_output: mesh consumers shard a fixed "
                             "clip axis, not a rows_valid pool")
        #: padding-waste accounting (PadCounter; 0-pad under ragged)
        self.padding = PadCounter()
        #: ragged accounting, drained via the executor's ragged sink
        self.ragged_stats = ({"pool_rows": self.pool_rows,
                              "emissions": 0, "rows": 0,
                              "pad_rows_eliminated": 0,
                              "cache_hit_rows": 0}
                             if self.ragged else None)
        if self.raw_output and len(self.row_buckets) > 1:
            # raw consumers (R2P1DMeshRunner) shard the clip axis over a
            # fixed mesh — a variable bucketed clip axis cannot satisfy
            # the sp divisibility requirement
            raise ValueError("row_buckets cannot be combined with "
                             "raw_output: mesh consumers need a fixed "
                             "clip axis")
        self.prefetch_depth = int(prefetch)
        self._fallback_pool = None  # lazily built thread pool
        # non-native fallback decode pool sizing: defaults to the
        # native DecodePool rule (RNB_DECODE_THREADS env, else
        # min(8, cores)) instead of a hardcoded width
        if fallback_decode_threads is None:
            self.fallback_decode_threads = default_decode_threads()
        else:
            self.fallback_decode_threads = int(fallback_decode_threads)
            if self.fallback_decode_threads < 1:
                raise ValueError("fallback_decode_threads must be >= 1, "
                                 "got %r" % (fallback_decode_threads,))
        self._starts_cache = {}  # video -> clip starts (see _sample_starts)
        #: pipeline-step index when the job traces (rnb_tpu.trace):
        #: set via enable_trace(), gates the phase-refinement stamps
        #: (decode{step}_done / transfer{step}_start/_done) so
        #: trace-off runs keep the pre-trace stamp schema byte-stable
        self._trace_step: Optional[int] = None
        # Zero-copy decode staging (rnb_tpu.staging): pre-allocated
        # host slots the native decoder writes straight into, removing
        # the per-request/per-emission bucket-shaped allocation and
        # assembly memcpy from the hot path. staging_slots=0 disables
        # (the seed copy path); None auto-sizes per loader kind.
        self.transfer_async = bool(transfer_async)
        if self.transfer_async and not self.SUPPORTS_TRANSFER_ASYNC:
            raise ValueError(
                "transfer_async requires a stage that emits "
                "asynchronously (R2P1DFusingLoader); %s completes "
                "requests synchronously" % type(self).__name__)
        if staging_slots is not None:
            staging_slots = int(staging_slots)
            if staging_slots < 0:
                raise ValueError("staging_slots must be >= 0 "
                                 "(0 disables staging), got %r"
                                 % (staging_slots,))
        slots = (self._staging_default_slots() if staging_slots is None
                 else staging_slots)
        self.staging = None
        if slots and native_available() \
                and self._staging_default_slots() > 0:
            # floor the explicit knob at the loader's structural
            # minimum: the plain loader's submit window holds
            # prefetch+1 slots before the first complete() (same
            # thread) can release one, so fewer than prefetch+2 slots
            # would deadlock submit against itself. The fusing loader
            # pressure-drains in _acquire_fused_slot and works at 1.
            slots = max(slots, self._staging_min_slots())
            # the zero-copy path exists only for the native decoder
            # (submit_into writes caller buffers) and only on code
            # paths that decode into caller targets — a plain loader
            # without prefetch decodes synchronously in __call__ and
            # would never touch a pool, so an explicit staging_slots
            # is ignored there (default_slots()==0) rather than
            # allocating dead slots and reporting misleading Staging:
            # telemetry. Non-native backends keep the copy fallback.
            self.staging = StagingPool(self._staging_shapes(), slots,
                                       dtype=self._wire_dtype)
        # Device-resident decoded-clip cache + in-flight coalescing
        # (rnb_tpu.cache): opt-in per config via `cache_mb`. The cached
        # value is the padded on-device uint8 batch (post-device_put,
        # pre-preprocess), so a hit skips decode AND host->device
        # transfer — the two dominant host terms (RESULTS.md round 5) —
        # and feeds the identical jitted path a miss would, keeping
        # hit/miss logits bit-identical.
        self.cache = None
        self._inflight_keys = None
        if cache_mb:
            from rnb_tpu.cache import ClipCache, InflightTable
            self.cache = ClipCache(cache_mb, device=self._jax_device)
            self._inflight_keys = InflightTable()
            # decode-config fingerprint: everything that changes the
            # decoded bytes or the padded value shape. Clip starts are
            # deterministic per video id given the sampler config
            # (sampler.py seeds per id), so no seed belongs here.
            self._cache_cfg = (
                "r2p1d", tuple(self.sampler.num_clips_population),
                tuple(float(p) for p in self.sampler.probabilities),
                self.consecutive_frames, FRAME_HW, self.pixel_path,
                self.max_clips, self.row_buckets,
                # ragged entries hold host row extents, bucketed ones
                # padded device batches — the two must never alias
                self.ragged,
                # the dct wire row length depends on the coefficient
                # budget: two budgets must never alias one entry
                self.dct_coeffs)
        # Paged device memory (rnb_tpu.pager), wired by the executor
        # via enable_pager(): the clip cache's blob storage becomes
        # page-table entries in a pager arena (hits gather on device,
        # zero host bytes) and — under pager.feature_cache — repeat
        # requests can skip the downstream forward entirely
        self.pager = None
        self._clip_arena = None
        self._zero_pool = None
        self._feature_stub = None
        self._preprocess_ragged = None
        #: jit-entry signature accounting (rnb_tpu.compilestats):
        #: distinct preprocess input signatures == executables this
        #: stage requires; frozen by the executor at window start so
        #: any later new signature surfaces as a mid-run recompile
        self.compiles = None
        if self.raw_output or self.pixel_path in ("yuv420", "dct"):
            # raw mode: consumer normalizes on its mesh. yuv420/dct:
            # the network stage's jit owns the whole ingest; the
            # loader ships packed u8 planes / int16 coefficient rows —
            # warm only the transfer path (one shape per bucket; ONE
            # pool shape under ragged — device_put itself never
            # compiles)
            self._preprocess = None
            for rows in self._warm_shapes():
                dummy = np.zeros(self._batch_shape(rows),
                                 dtype=self._wire_dtype)
                for _ in range(num_warmups):
                    jax.block_until_ready(
                        jax.device_put(dummy, self._jax_device))
        elif self.ragged:
            # ragged ingest: ONE compiled executable serves every
            # batch composition — the rows_valid scalar is traced,
            # and the TPU kernel's grid skip spends no arithmetic on
            # rows past it (rnb_tpu/ops/ragged.py)
            self._preprocess = None
            self._preprocess_ragged = _shared_ragged_preprocess(
                self._jax_device)
            self.compiles = SignatureTracker()
            dummy = np.zeros(self._batch_shape(self.pool_rows),
                             dtype=np.uint8)
            # vocabulary declared even under num_warmups=0 (see the
            # runner's warmup loop)
            self.compiles.observe(dummy)
            for _ in range(num_warmups):
                jax.block_until_ready(self._preprocess_ragged(
                    jax.device_put(dummy, self._jax_device),
                    np.int32(self.pool_rows)))
        else:
            self._preprocess = _shared_preprocess(self._jax_device)
            self.compiles = SignatureTracker()
            # warm-up: compile the preprocess for every bucket shape and
            # fault in the transfer path
            for rows in self._warm_shapes():
                dummy = np.zeros(self._batch_shape(rows),
                                 dtype=np.uint8)
                self.compiles.observe(dummy)
                for _ in range(num_warmups):
                    jax.block_until_ready(self._preprocess(
                        jax.device_put(dummy, self._jax_device)))
        # decode warm-up on real sample files (the reference warmed its
        # NVVL loader on 3 sample mp4s, models/r2p1d/model.py:133-138):
        # faults in file IO, header parse and the native pool so the
        # first measured request pays no cold cost. num_warmups=0 is the
        # opt-out and must skip this too.
        if num_warmups > 0:
            self._warm_decode(num_samples=3)

    def _warm_decode(self, num_samples: int = 3) -> None:
        import os
        root = os.environ.get("RNB_TPU_DATA_ROOT")
        if not root or not os.path.isdir(root):
            return
        samples = []
        for dirpath, dirnames, filenames in sorted(os.walk(root)):
            dirnames.sort()
            for fn in sorted(filenames):
                if fn.endswith(".y4m"):
                    samples.append(os.path.join(dirpath, fn))
                    if len(samples) >= num_samples:
                        break
            if len(samples) >= num_samples:
                break
        for path in samples:
            try:
                decoder = get_decoder(path)
                length = decoder.num_frames(path)
                starts = self.sampler.sample(
                    length, video_id=path)[: self.max_clips]
                self._decode_sync(decoder, path, starts)
            except Exception as e:
                # warm-up is best-effort: a corrupt sample file must
                # not kill stage init (the hot loop contains the same
                # error per-request); unclassified errors still abort
                if classify_error(e) is FATAL:
                    raise
                print("[rnb-tpu] WARNING: decode warm-up skipped %s: %s"
                      % (path, e))

    def enable_trace(self, tracer, step_idx: int) -> None:
        """Executor protocol (rnb_tpu.runner): turn on the per-request
        phase-refinement stamps and register this stage's sampled
        occupancy sources with the job tracer. Called only on
        trace-enabled runs."""
        self._trace_step = int(step_idx)
        if self.staging is not None:
            tracer.add_counter_source(
                trace.name("staging.s%d.free", step_idx),
                self.staging.available)

    def _stamp_decode_done(self, time_card) -> None:
        """Phase-refinement: this request's decode completed (trace
        mode only — one None test otherwise)."""
        if self._trace_step is None:
            return
        _record_clamped(time_card,
                        "decode%d_done" % self._trace_step, time.time())
        trace.instant("loader.decode_ready", rid=time_card.id)

    def _staging_default_slots(self) -> int:
        """Auto slot budget: the prefetch window plus one transferring
        slot (submit must never deadlock waiting on a complete() that
        runs later on the same executor thread). 0 = no pool: without
        prefetch the plain loader decodes synchronously in __call__
        and never targets a slot."""
        return self.prefetch_depth + 2 if self.prefetch_depth > 0 else 0

    def _staging_min_slots(self) -> int:
        """Smallest slot count this loader can run without submit
        deadlocking against its own complete() (see __init__)."""
        return self.prefetch_depth + 2

    def _warm_shapes(self):
        """Row counts warm-up must fault in: the bucket vocabulary —
        or the ONE pool shape under ragged dispatch."""
        return (self.pool_rows,) if self.ragged else self.row_buckets

    def _ship_rows(self, n: int) -> int:
        """Rows an emission holding ``n`` valid rows actually ships:
        its pad bucket — or the fixed pool capacity under ragged."""
        return self.pool_rows if self.ragged else self._bucket_for(n)

    def _note_emission_padding(self, valid: int, shipped: int,
                               cards) -> None:
        """Padding-waste + ragged accounting for one emission (the
        shared rule, rnb_tpu.stage.note_emission_accounting); the
        counterfactual under ragged is this stage's configured bucket
        vocabulary (max-shape padding when none is named), so a
        same-seed bucketed arm's pad_rows equals pad_rows_eliminated
        exactly."""
        note_emission_accounting(
            self.padding, self.ragged_stats, cards, valid, shipped,
            self._bucket_for(valid) if self.ragged else 0)

    def _normalize_emission(self, device_u8, valid: int):
        """The one preprocess dispatch every emission path shares:
        bucketed jit, ragged jit (traced rows_valid scalar), or a
        pass-through for raw/yuv consumers. Observes the jit-entry
        signature for the Compiles: accounting."""
        if self._preprocess_ragged is not None:
            self.compiles.observe(device_u8)
            return self._preprocess_ragged(device_u8, np.int32(valid))
        if self._preprocess is not None:
            self.compiles.observe(device_u8)
            return self._preprocess(device_u8)
        return device_u8

    def _wrap_batch(self, data, valid: int, offsets=None):
        """The emitted tensor: a RaggedBatch carrying the segment
        table under ragged dispatch, the seed PaddedBatch otherwise."""
        if self.ragged:
            return RaggedBatch(data, valid,
                               tuple(offsets) if offsets is not None
                               else (0, int(valid)))
        return PaddedBatch(data, valid)

    def _staging_shapes(self):
        """One sub-pool per emitted bucket shape (ONE pool shape under
        ragged dispatch)."""
        return [self._batch_shape(rows) for rows in self._warm_shapes()]

    def _stage_target(self, n: int):
        """Decode-target buffer for one native request:
        ``(buffer, slot, row0)`` — a staging-slot row view on the
        zero-copy path, or a fresh allocation when staging is off
        (the copy fallback, baselined under RNB-H007)."""
        if self.staging is not None:
            slot = self.staging.acquire(
                self._batch_shape(self._ship_rows(n)))
            self.staging.add_ref(slot)
            return slot.buf[:n], slot, 0
        return (np.empty(self._batch_shape(n), dtype=self._wire_dtype),
                None, 0)

    def _release_handle_slot(self, handle) -> None:
        """Retire a handle's staging-slot reference (idempotent): its
        rows are consumed, dead, or replaced by a re-decode."""
        slot = getattr(handle, "slot", None)
        if slot is not None and self.staging is not None:
            self.staging.retire_ref(slot)
            handle.slot = None

    def _release_handle_plan(self, handle) -> None:
        """Release a handle's pinned page plans (drop/shed/failure
        paths, idempotent): pages an eviction parked in limbo under
        the pin re-enter the free list, so a shed hit can never leak
        pages (rnb_tpu.pager pin/limbo discipline)."""
        for attr in ("gather_plan", "feature_plan", "cached"):
            plan = getattr(handle, attr, None)
            if plan is not None and hasattr(plan, "release"):
                plan.release()
                if attr != "cached":
                    setattr(handle, attr, None)

    def enable_pager(self, pager) -> None:
        """Executor protocol (rnb_tpu.runner): install the page
        allocator. The clip cache switches to page-table entries in a
        fresh ``clips`` arena sized from the cache's own byte budget
        (the bytes the blob cache would have owned), and the loader
        preallocates the ONE pool-shaped device zero array that
        full-gather hits and feature hits dispatch with — a hit then
        ships zero host memcpy bytes. Requires ragged dispatch (the
        pool is the one gather seam) and an enabled clip cache."""
        import jax
        if not self.ragged:
            raise ValueError(
                "pager requires ragged dispatch: paged gathers "
                "overlay rows of the ONE pool shape (configure the "
                "root 'ragged' key)")
        if self.cache is None:
            raise ValueError(
                "pager requires an enabled clip cache (cache_mb): "
                "the page arena replaces its blob storage")
        self.pager = pager
        pager.size_hint(self.cache.capacity_bytes)
        self._clip_arena = pager.create_arena(
            "clips", self._batch_shape(1)[1:], self._wire_dtype,
            budget_bytes=self.cache.capacity_bytes,
            device=self._jax_device)
        self.cache.attach_arena(self._clip_arena)
        zeros = np.zeros(self._batch_shape(self.pool_rows),
                         dtype=self._wire_dtype)
        self._zero_pool = jax.device_put(zeros, self._jax_device)
        pager.adopt_shared("loader-zero-pool", self._zero_pool,
                           device_label=str(self._jax_device))
        # feature hits ship a stub emission downstream (the consumer
        # gathers its own output rows and never reads the payload);
        # the stub must still BE the declared wire value — normalized
        # once here, outside the measured window
        stub = self._normalize_emission(self._zero_pool, 0)
        if stub is not self._zero_pool:
            import jax as _jax
            _jax.block_until_ready(stub)
            pager.adopt_shared("loader-feature-stub", stub,
                               device_label=str(self._jax_device))
        self._feature_stub = stub

    def _decode_sync(self, decoder, video, starts):
        """Synchronous decode through this loader's pixel path."""
        if self.pixel_path == "yuv420":
            return decoder.decode_clips_yuv(video, starts,
                                            self.consecutive_frames,
                                            width=FRAME_HW,
                                            height=FRAME_HW)
        if self.pixel_path == "dct":
            return decoder.decode_clips_dct(video, starts,
                                            self.consecutive_frames,
                                            width=FRAME_HW,
                                            height=FRAME_HW,
                                            coeffs=self.dct_coeffs)
        return decoder.decode_clips(video, starts,
                                    self.consecutive_frames,
                                    width=FRAME_HW, height=FRAME_HW)

    def _batch_shape(self, rows: Optional[int] = None):
        n = rows if rows is not None else self.max_clips
        if self.pixel_path == "yuv420":
            return (n, self.consecutive_frames,
                    packed_frame_bytes(FRAME_HW, FRAME_HW))
        if self.pixel_path == "dct":
            return (n, self.consecutive_frames,
                    dct_frame_elems(FRAME_HW, FRAME_HW,
                                    self.dct_coeffs))
        return (n, self.consecutive_frames, FRAME_HW, FRAME_HW, 3)

    def _bucket_for(self, n: int) -> int:
        for bucket in self.row_buckets:
            if n <= bucket:
                return bucket
        return self.row_buckets[-1]

    def input_shape(self):
        return None

    @staticmethod
    def output_shape():
        return ((MAX_CLIPS, CONSECUTIVE_FRAMES, FRAME_HW, FRAME_HW, 3),)

    @classmethod
    def output_shape_for(cls, max_clips: int = MAX_CLIPS,
                         consecutive_frames: int = CONSECUTIVE_FRAMES,
                         pixel_path: str = "rgb",
                         dct_coeffs_per_frame=None, **_kwargs):
        if pixel_path == "yuv420":
            return ((int(max_clips), int(consecutive_frames),
                     packed_frame_bytes(FRAME_HW, FRAME_HW)),)
        if pixel_path == "dct":
            return ((int(max_clips), int(consecutive_frames),
                     dct_frame_elems(FRAME_HW, FRAME_HW,
                                     dct_coeffs_per_frame)),)
        return ((int(max_clips), int(consecutive_frames),
                 FRAME_HW, FRAME_HW, 3),)

    @classmethod
    def output_dtype_for(cls, raw_output: bool = False,
                         pixel_path: str = "rgb", **_kwargs):
        # raw mode ships the padded uint8 batch; yuv420 ships packed u8
        # planes and dct ships packed int16 coefficient rows for the
        # consumer's fused ingest; otherwise the jitted preprocess
        # emits normalized bfloat16
        if pixel_path == "dct":
            return "int16"
        if raw_output or pixel_path == "yuv420":
            return "uint8"
        return "bfloat16"

    #: clips per native-pool ticket when a submitted video fans out:
    #: small enough that a 15-clip video engages several workers, large
    #: enough that 1-clip videos cost one submit/wait round trip
    POOL_CHUNK_CLIPS = 4

    #: per-video clip-start cache cap: benchmark datasets cycle a small
    #: id population; anything larger falls back to re-sampling
    STARTS_CACHE_MAX = 8192

    def _sample_starts(self, decoder, video: str):
        """Clip starts for one video — cached. The sampler is
        deterministic per video id (sampler.py seeds per id) and a
        file's frame count is fixed, so a repeated id re-derives
        identical starts; before caching, the probe+sample path cost
        ~200 us/request = 20% of the host core at ~1k videos/s
        (hostprof, round 5). A file replaced on disk mid-run keeps its
        cached starts — benchmark semantics, same as the native
        decoder's per-video metadata caches."""
        starts = self._starts_cache.get(video)
        if starts is None:
            length = decoder.num_frames(video)
            starts = [int(s) for s in
                      self.sampler.sample(length, video_id=video)]
            starts = starts[: self.max_clips]
            if len(self._starts_cache) < self.STARTS_CACHE_MAX:
                self._starts_cache[video] = starts
        return starts

    def _cache_lookup(self, video: str, key=None):
        """(key, entry) for one request — (None, None) when caching is
        off. Counted and hostprof-sectioned: the lookup (one stat + one
        dict probe) is the only cost a cache-enabled miss adds. Under
        a paged cache the hit value is a pinned GatherPlan
        (rnb_tpu.cache.ClipCache.acquire), not a blob entry. ``key``
        short-circuits the content hash when the caller already
        computed it (the feature-page probe)."""
        if self.cache is None:
            return None, None
        with hostprof.section("loader.cache_lookup"):
            if key is None:
                key = content_key(video, self._cache_cfg)
            if self.cache.paged:
                entry = self.cache.acquire(key)
            else:
                entry = self.cache.lookup(key)
        return key, entry

    def _feature_probe(self, video: str):
        """(content_key, plan): probe the feature-page cache ahead of
        the clip cache — a hit there supersedes everything (the whole
        stage-0..N work is skipped). (None, None) when feature pages
        are off; (key, None) on a plain miss, the key then feeds
        :meth:`_cache_lookup` so the content hash runs once."""
        if self.pager is None or self.pager.feature is None \
                or self.cache is None:
            return None, None
        key = content_key(video, self._cache_cfg)
        return key, self.pager.feature.acquire(key)

    def _stamp_feature_insert(self, time_card, key, row0: int,
                              n: int) -> None:
        """Mark one successfully transferred request's pool row range
        as a feature-insert candidate: the CONSUMING stage performs
        the insert strictly after its forward returned
        (insert-after-success), reading the stamp off the card."""
        if self.pager is not None and self.pager.feature is not None \
                and self.pager.feature.ready and key is not None:
            time_card.feature_insert = (key, int(row0), int(n))

    def _materialize_hit(self, entry, time_card):
        """Serve one request from a cache entry: no decode, no
        transfer — straight into the same jitted preprocess a miss
        feeds (or as-is for raw/yuv420 consumers).

        Under ragged dispatch the entry is a **host row extent**
        (rnb_tpu.cache.insert_rows): the decode is skipped but the
        rows re-pad into the pool and ride a fresh transfer — the
        pool is the one dispatch shape, so there is no per-request
        padded device value to serve zero-copy (README "Ragged
        dispatch" documents the trade)."""
        time_card.num_clips = entry.valid
        time_card.cache_hit = True
        if self.ragged:
            if self.ragged_stats is not None:
                self.ragged_stats["cache_hit_rows"] += entry.valid
            if self._trace_step is not None:
                _record_clamped(time_card, "decode%d_done"
                                % self._trace_step, time.time())
            if self.cache.paged:
                return self._materialize_pages(entry, time_card)
            return self._materialize(entry.batch, entry.valid,
                                     time_card)
        if self._trace_step is not None:
            # a hit pays no decode/hold/transfer: zero-length phases
            # keep every card's key sequence identical per instance
            # (TimeCardSummary asserts one schema per run)
            now = time.time()
            step = self._trace_step
            _record_clamped(time_card, "decode%d_done" % step, now)
            _record_clamped(time_card, "transfer%d_start" % step, now)
            _record_clamped(time_card, "transfer%d_done" % step, now)
        self._note_emission_padding(entry.valid,
                                    int(entry.batch.shape[0]),
                                    [time_card])
        return (PaddedBatch(self._normalize_emission(entry.batch,
                                                     entry.valid),
                            entry.valid),), None, time_card

    def _materialize_pages(self, plan, time_card):
        """Serve a paged ragged hit with ZERO host bytes: the entry's
        page rows gather straight over the preallocated device zero
        pool — no decode, no staging rows, no host memcpy, no
        device_put (the staging plane counts a bypassed emission).
        The gather feeds the identical normalize dispatch a miss
        feeds, so hit/miss logits stay bit-identical."""
        n = plan.valid
        if self._trace_step is not None:
            # no transfer happens: zero-length phases keep the card's
            # key sequence identical to a miss (TimeCardSummary
            # asserts one schema per step instance)
            now = time.time()
            step = self._trace_step
            _record_clamped(time_card, "transfer%d_start" % step, now)
            _record_clamped(time_card, "transfer%d_done" % step, now)
        src = np.full((self.pool_rows,), -1, np.int32)
        src[:n] = plan.src_rows
        with hostprof.section("loader.cache_gather"):
            device_u8 = self._clip_arena.gather(self._zero_pool, src)
        plan.release()
        if self.staging is not None:
            self.staging.note_bypassed()
        self._note_emission_padding(n, self.pool_rows, [time_card])
        batch = self._normalize_emission(device_u8, n)
        return (self._wrap_batch(batch, n),), None, time_card

    def _materialize_feature(self, plan, time_card):
        """A feature-page hit: the request skips decode, transfer AND
        the downstream forward. The emission ships the preallocated
        stub pool (never read downstream) and the pinned plan rides
        the time card to the consuming stage, which gathers the exact
        output rows the original request computed and releases the
        pin. Insert-after-success upstream guarantees those rows came
        from a forward that returned."""
        n = plan.valid
        time_card.num_clips = n
        time_card.feature_hit = True
        time_card.feature_plan = plan
        if self._trace_step is not None:
            now = time.time()
            step = self._trace_step
            _record_clamped(time_card, "decode%d_done" % step, now)
            _record_clamped(time_card, "transfer%d_start" % step, now)
            _record_clamped(time_card, "transfer%d_done" % step, now)
        self.pager.note_feature_saved(n * self._clip_arena.row_bytes)
        if self.staging is not None:
            self.staging.note_bypassed()
        self._note_emission_padding(n, self.pool_rows, [time_card])
        return (self._wrap_batch(self._feature_stub, n),), None, \
            time_card

    def submit(self, non_tensors, time_card) -> _DecodeHandle:
        """Kick off decode of one request; pair with :meth:`complete`.

        Native .y4m requests become DecodePool tickets (decode runs on
        the C++ worker pool immediately); other backends decode on a
        small fallback thread pool. Either way the calling executor
        thread returns without blocking on pixel work.

        With the clip cache enabled, a hit returns a work-free cached
        handle, and a request whose key is already decoding in the
        prefetch window coalesces onto that leader (shares its decoded
        buffer — no second decode) instead of re-submitting.
        """
        video = str(non_tensors)
        fkey, fplan = self._feature_probe(video)
        if fplan is not None:
            handle = _DecodeHandle(None, fplan.valid)
            handle.feature_plan = fplan
            time_card.num_clips = fplan.valid
            time_card.feature_hit = True
            return handle
        key, entry = self._cache_lookup(video, key=fkey)
        if entry is not None:
            time_card.num_clips = entry.valid
            time_card.cache_hit = True
            return _DecodeHandle(None, entry.valid, cached=entry)
        if key is not None:
            time_card.cache_hit = False
            leader = self._inflight_keys.get(key)
            if leader is not None:
                time_card.num_clips = leader.n
                time_card.cache_coalesced = True
                self.cache.note_coalesced()
                follower = _DecodeHandle(None, leader.n, leader=leader)
                if leader.slot is not None and self.staging is not None:
                    # the follower reads the leader's slot rows for its
                    # own transfer — it must hold its own reference or
                    # the leader's completion could recycle the slot
                    # under the follower's still-pending read
                    self.staging.add_ref(leader.slot)
                    follower.slot = leader.slot
                    follower.row0 = leader.row0
                return follower
        handle = self._decode_submit(video, time_card)
        if key is not None:
            handle.key = key
            self._inflight_keys.put(key, handle)
        return handle

    def _decode_submit(self, video: str, time_card) -> _DecodeHandle:
        """The raw async-decode kickoff behind :meth:`submit` — no
        cache interaction (the fusing loader runs its own lookup and
        coalescing around this)."""
        with hostprof.section("loader.probe+sample"):
            decoder = get_decoder(video)
            starts = self._sample_starts(decoder, video)
        n = len(starts)
        time_card.num_clips = n
        # flow anchor: decode kicked off for this request (one None
        # test when tracing is off, rnb_tpu.trace)
        trace.instant("loader.decode_submit", rid=time_card.id)
        # trust the backend get_decoder() chose: a .y4m path whose file
        # vanished resolves to SyntheticDecoder there, and submitting it
        # to the native pool anyway would kill the run the synchronous
        # path survives
        if isinstance(decoder, NativeY4MDecoder):
            out, slot, row0 = self._stage_target(n)
            pixfmt = {"yuv420": PIX_YUV420,
                      "dct": PIX_DCT}.get(self.pixel_path, PIX_RGB)
            pool = DecodePool.shared()
            tickets = []
            try:
                with hostprof.section("loader.pool_submit"):
                    for lo in range(0, n, self.POOL_CHUNK_CLIPS):
                        hi = min(lo + self.POOL_CHUNK_CLIPS, n)
                        tickets.append(pool.submit_into(
                            video, starts[lo:hi], self.consecutive_frames,
                            out[lo:hi], pixfmt=pixfmt, width=FRAME_HW,
                            height=FRAME_HW))
            except Exception:
                # a partial submit must not leak the earlier tickets —
                # un-waited tickets pin the batch buffer in the pool's
                # pending map for the process's life
                partial = _DecodeHandle(out, n, pool=pool,
                                        tickets=tickets, slot=slot,
                                        row0=row0)
                try:
                    partial.wait(video)
                except ValueError:
                    pass
                self._release_handle_slot(partial)
                raise
            return _DecodeHandle(out, n, pool=pool, tickets=tickets,
                                 slot=slot, row0=row0)
        if self._fallback_pool is None:
            self._fallback_pool = ThreadPoolExecutor(
                max_workers=self.fallback_decode_threads,
                thread_name_prefix="rnb-decode")

        handle = _DecodeHandle(None, n)
        rid = time_card.id

        def _work():
            # hand the decoded batch to the handle directly — no
            # staging copy into the preallocated buffer (the span puts
            # the decode body on the rnb-decode thread's trace track;
            # native-pool decodes run in C++ and are delimited by the
            # submit/ready instants instead)
            with trace.span("loader.decode", rid):
                handle.out = self._decode_sync(decoder, video, starts)

        handle.future = self._fallback_pool.submit(_work)
        return handle

    def _materialize(self, clips: np.ndarray, n: int, time_card,
                     cache_key=None):
        """Pad decoded clips to their row bucket, transfer, normalize.

        With ``cache_key`` set, the freshly transferred padded device
        batch is inserted into the clip cache — insert-after-success
        only: this line is reached only once decode and transfer both
        completed, so failed/contained requests never populate entries.
        """
        jax, _ = _jax_numpy()
        target = self._batch_shape(self._ship_rows(n))
        if clips.shape == target:
            # bucket == clip count (the dominant 1-clip case): the
            # decode buffer already is the transfer buffer — no pad copy
            padded = clips
        elif self.ragged:
            # ragged consumers mask rows >= rows_valid in-jit, so the
            # pool tail can stay uninitialized — for the dominant
            # 1-clip request that skips a pool-minus-one-row memset
            padded = np.empty(target, dtype=self._wire_dtype)
            padded[:n] = clips
        else:
            padded = np.zeros(target, dtype=self._wire_dtype)
            padded[:n] = clips
        if cache_key is not None and self.cache is not None \
                and self.ragged and not self.cache.paged:
            # ragged entries are host row extents (exactly n rows,
            # no pool padding) — copied out here, before the transfer,
            # while the decode buffer is live
            with hostprof.section("loader.cache_insert"):
                self.cache.insert_rows(cache_key, clips, n)
        if self._trace_step is not None:
            _record_clamped(time_card,
                            "transfer%d_start" % self._trace_step,
                            time.time())
        with trace.span("loader.transfer", time_card.id):
            device_u8 = jax.device_put(padded, self._jax_device)
        if self._trace_step is not None:
            _record_clamped(time_card,
                            "transfer%d_done" % self._trace_step,
                            time.time())
        if cache_key is not None and self.cache is not None \
                and self.ragged and self.cache.paged:
            # paged insert is post-transfer DEVICE work (insert-after-
            # success and zero extra host copies): pool rows [0, n)
            # publish into pages by donated on-device writes
            with hostprof.section("loader.cache_insert"):
                self.cache.insert_pages(cache_key, device_u8, 0, n)
            self._stamp_feature_insert(time_card, cache_key, 0, n)
        if cache_key is not None and self.cache is not None \
                and not self.ragged:
            # zero-copy insert: the padded device array IS the cached
            # value (immutable jax.Array) — no extra transfer
            with hostprof.section("loader.cache_insert"):
                self.cache.insert_device(cache_key, device_u8, n)
        self._note_emission_padding(n, int(target[0]), [time_card])
        batch = self._normalize_emission(device_u8, n)
        return (self._wrap_batch(batch, n),), None, time_card

    def _materialize_slot(self, handle: _DecodeHandle, time_card,
                          cache_key=None):
        """The staged twin of :meth:`_materialize`: the decode landed
        directly in a bucket-shaped staging slot, so the slot IS the
        transfer buffer — no pad allocation, no assembly copy. Only
        the padding tail is zeroed (seed byte parity), the transfer is
        confirmed lazily at the slot's next acquire, and the slot is
        recycled strictly after that confirmation (rnb_tpu.staging
        alias handling keeps an aliasing backend from ever reusing
        memory a live device batch still reads)."""
        jax, _ = _jax_numpy()
        slot, n = handle.slot, handle.n
        if n < slot.buf.shape[0] and not self.ragged:
            # bucketed byte parity needs a zeroed pad tail; under
            # ragged every consumer masks rows >= rows_valid inside
            # its jit (rnb_tpu/ops/ragged.py contract), so the memset
            # — up to pool-1 rows per request — is pure host waste
            slot.buf[n:] = 0
        if cache_key is not None and self.cache is not None \
                and self.ragged and not self.cache.paged:
            # ragged entries are host row extents, copied out of the
            # slot while its rows are still live (pre-handoff)
            with hostprof.section("loader.cache_insert"):
                self.cache.insert_rows(cache_key, slot.buf, n)
        self.staging.begin_transfer(slot)
        if self._trace_step is not None:
            _record_clamped(time_card,
                            "transfer%d_start" % self._trace_step,
                            time.time())
        with hostprof.section("loader.device_put"), \
                trace.span("loader.transfer", time_card.id):
            device_u8 = jax.device_put(slot.buf, self._jax_device)
        self.staging.finish_transfer(slot, device_u8)
        self.staging.note_staged()
        if self._trace_step is not None:
            _record_clamped(time_card,
                            "transfer%d_done" % self._trace_step,
                            time.time())
        self._release_handle_slot(handle)
        if cache_key is not None and self.cache is not None \
                and self.ragged and self.cache.paged:
            # paged insert, post-transfer (see _materialize)
            with hostprof.section("loader.cache_insert"):
                self.cache.insert_pages(cache_key, device_u8, 0, n)
            self._stamp_feature_insert(time_card, cache_key, 0, n)
        if cache_key is not None and self.cache is not None \
                and not self.ragged:
            # still zero-copy: the cached device array owns its bytes
            # once the transfer is confirmed; the slot recycle gate
            # (and the alias probe behind it) guarantees exactly that
            with hostprof.section("loader.cache_insert"):
                self.cache.insert_device(cache_key, device_u8, n)
        self._note_emission_padding(n, int(device_u8.shape[0]),
                                    [time_card])
        return (self._wrap_batch(self._normalize_emission(device_u8, n),
                                 n),), None, time_card

    def complete(self, handle: _DecodeHandle, non_tensors, time_card):
        """Wait for a submitted decode, then pad/transfer/normalize
        (or serve the cached/coalesced result without decode work)."""
        if handle.feature_plan is not None:
            plan, handle.feature_plan = handle.feature_plan, None
            return self._materialize_feature(plan, time_card)
        if handle.cached is not None:
            return self._materialize_hit(handle.cached, time_card)
        if handle.leader is not None:
            # coalesced follower: the leader decoded for both; a failed
            # leader re-raises its classified error here (containment
            # then dead-letters this request too). No cache insert —
            # the leader already did it.
            try:
                handle.wait(str(non_tensors))
            except Exception:
                self._release_handle_slot(handle)
                raise
            self._stamp_decode_done(time_card)
            if handle.slot is not None:
                # the follower pays its own transfer straight from the
                # leader's slot rows (its own reference keeps them live)
                return self._materialize_slot(handle, time_card)
            return self._materialize(handle.out, handle.n, time_card)
        try:
            handle.wait(str(non_tensors))
        except Exception:
            self._release_handle_slot(handle)
            raise
        finally:
            # the decode is finalized either way: later requests for
            # this key consult the cache (success) or decode afresh
            if self._inflight_keys is not None:
                self._inflight_keys.pop(handle.key)
        self._stamp_decode_done(time_card)
        if handle.slot is not None:
            return self._materialize_slot(handle, time_card,
                                          cache_key=handle.key)
        return self._materialize(handle.out, handle.n, time_card,
                                 cache_key=handle.key)

    def discard(self, handle: _DecodeHandle, non_tensors=None) -> None:
        """Retire a submitted decode whose result will never be used
        (abort path) so native tickets don't pin buffers forever —
        and release its staging-slot reference, so a contained or
        aborted request can never leak a slot."""
        try:
            handle.wait(str(non_tensors))
        except Exception:
            pass  # abort path: decode errors are moot
        self._release_handle_slot(handle)
        self._release_handle_plan(handle)
        if self._inflight_keys is not None:
            self._inflight_keys.pop(getattr(handle, "key", None))

    def __call__(self, tensors, non_tensors, time_card):
        # synchronous path (no prefetching executor, R2P1DSingleStep):
        # decode inline on the calling thread — no thread-pool hop, no
        # extra staging copy on the hot path
        video = str(non_tensors)
        fkey, fplan = self._feature_probe(video)
        if fplan is not None:
            return self._materialize_feature(fplan, time_card)
        key, entry = self._cache_lookup(video, key=fkey)
        if entry is not None:
            return self._materialize_hit(entry, time_card)
        decoder = get_decoder(video)
        starts = self._sample_starts(decoder, video)
        clips = self._decode_sync(decoder, video, starts)
        n = clips.shape[0]
        time_card.num_clips = n
        self._stamp_decode_done(time_card)
        if key is not None:
            time_card.cache_hit = False
        return self._materialize(clips, n, time_card, cache_key=key)


class _FuseRecord:
    """One in-flight/ready request of the fusing loader: the decode
    handle plus every TimeCard riding on it — the leader's and any
    coalesced followers' (rnb_tpu.cache), which share the single
    decode and the single fused emission."""

    __slots__ = ("handle", "video", "cards", "key", "fkey", "t_ready")

    def __init__(self, handle, video, card, key=None, fkey=None):
        self.handle = handle
        self.video = video
        self.cards = [card]
        self.key = key       # cache key, or None when caching is off
        self.fkey = fkey     # content key for feature-page inserts
        self.t_ready = 0.0   # monotonic instant the decode was harvested


class R2P1DFusingLoader(R2P1DLoader):
    """Decode stage with loader-side dynamic batching.

    Replicate & Batch without the extra stage: every incoming request
    is submitted to the decode pool immediately; requests whose decode
    has completed are harvested in FIFO order and emitted as ONE fused
    device batch — a single ``device_put``, a single downstream
    dispatch carrying a TimeCardList. This removes the per-request
    ring hop, executor thread and per-request transfers that made the
    standalone loader->Batcher->net topology host-bound on a 1-core
    host (RESULTS.md round 4: the batched topology's device sat at 69%
    occupancy while the 2-stage pipeline's ran ~97%), while keeping
    the Batcher's device-efficiency win: a fused 6-row dispatch runs
    ~1.45x more FLOPs/s than six 1-row ones (xprof round-4 capture).

    Emission policy (adaptive, unlike the fixed-k Batcher):
      * emit when ``fuse`` requests are ready or their combined clip
        rows reach the ring's max shape;
      * emit a partial batch when nothing is left in flight, so light
        Poisson load pays no batch-fill latency;
      * emit when the oldest ready request has waited longer than
        ``max_hold_ms`` (bounds p99 at mid load);
      * block on the oldest in-flight decode only once ``depth``
        requests are pending (backpressure toward the client queue).

    Reference lineage: batcher.py:17-34 (the fixed-k Batcher) +
    README.md:46-110 (NVVL's async loadfile) — fused into one stage
    the way NVVL fused sampling+decode+batch assembly.

    **Zero-copy staging + transfer pipeline** (rnb_tpu.staging): with
    a staging pool (default on over the native decoder), submit-time
    row planning makes the decode pool write each request directly
    into its slice of a pre-allocated slot — a full take emits the
    slot's bucket prefix with no allocation and no assembly copy —
    and ``transfer_async`` moves the ``device_put`` to a dedicated
    worker so batch N transfers while batch N+1 decodes. Completed
    emissions surface through :meth:`take_ready`, which the executor
    drains ahead of new input. README "Transfer pipeline".
    """

    #: emissions happen between model calls, so device_put can move to
    #: the transfer worker without breaking any synchronous contract
    SUPPORTS_TRANSFER_ASYNC = True

    #: the emission policy's hold/target/bucket knobs can be driven by
    #: the load-adaptive controller (rnb_tpu.autotune)
    SUPPORTS_AUTOTUNE = True

    #: this stage feeds the controller's service-time EWMA itself
    #: (batch close -> ready-queue span, _pop_ready): under
    #: transfer_async every emission surfaces via take_ready()/poll(),
    #: so the executor's stamp-based feed — which skips `flushed`
    #: emissions — would never observe a sample and the controller
    #: would price service at 0 forever; the executor must NOT also
    #: feed this stage from the TimeCard stamps (rnb_tpu.runner)
    AUTOTUNE_SELF_SERVICE = True

    #: default staging depth: one slot filling with planned decodes,
    #: one transferring, one spare so a hold-timeout partial emission
    #: cannot stall planning (double/triple buffering)
    DEFAULT_STAGING_SLOTS = 3

    GUARDED_BY = {"_out_ready": "_out_lock"}

    UNGUARDED_OK = {
        "_ready": "executor-thread confined; only the _out_ready "
                  "handoff crosses the transfer-worker boundary",
        "_inflight": "executor-thread confined (see _ready)",
        "_open_slot": "executor-thread confined (see _ready)",
        "_open_rows": "executor-thread confined (see _ready)",
        "_open_count": "executor-thread confined (see _ready)",
        "_failed": "executor-thread confined (see _ready)",
        "_stage_retries": "executor-thread confined (see _ready)",
        "_deadline_shed": "executor-thread confined (see _ready)",
        "autotune": "executor-thread confined (see _ready)",
        "ragged_stats": "executor-thread confined (see _ready)",
    }

    def __init__(self, device, fuse: int = 6, depth: Optional[int] = None,
                 max_hold_ms: float = 5.0, **kwargs):
        if kwargs.get("prefetch"):
            raise ValueError(
                "R2P1DFusingLoader manages its own decode pipeline; "
                "its in-flight window is `depth`, not `prefetch`")
        super().__init__(device, **kwargs)
        if int(fuse) < 1:
            raise ValueError("fuse must be >= 1, got %r" % (fuse,))
        self.fuse = int(fuse)
        self.depth = int(depth) if depth is not None else 2 * self.fuse
        self.max_hold_ms = float(max_hold_ms)
        self._inflight = deque()  # _FuseRecord, decode still running
        self._ready = deque()     # _FuseRecord, decode complete
        # -- zero-copy staging + transfer pipeline (rnb_tpu.staging) --
        #: the one slot shape fused planning targets: buckets are
        #: emitted as C-contiguous row prefixes of the max shape
        self._slot_shape = self._batch_shape(self.max_clips)
        self._open_slot = None   # slot currently accepting row plans
        self._open_rows = 0      # rows planned into the open slot
        self._open_count = 0     # requests planned into the open slot
        #: completed emissions awaiting pickup (take_ready/poll/flush);
        #: appended by the transfer worker under transfer_async
        self._out_ready = deque()
        self._out_lock = threading.Lock()
        self._worker = None
        if self.transfer_async:
            self._worker = TransferWorker(pool=self.staging)
        # requests whose decode failed with a *classified* error while
        # their batch was being assembled: (time_card, reason), drained
        # by the executor's take_failed() protocol (rnb_tpu.runner)
        self._failed = []
        # transient re-decode attempts performed inside _wait_contained,
        # drained by the executor's take_retries() protocol so they
        # land in the job-wide num_retries accounting
        self._stage_retries = 0
        #: (max_retries, retry_backoff_ms) — the executor copies the
        #: step's schema knobs here after construction (the knobs are
        #: schema, not model kwargs, so they never arrive via **kwargs)
        self.fault_retry_budget = (0, 0.0)
        #: load-adaptive batching controller (rnb_tpu.autotune), set
        #: by the executor via enable_autotune(); None = the static
        #: fuse/max_hold_ms emission policy exactly as configured
        self.autotune = None
        #: deadline-expired requests dropped from the ready queue
        #: before emission (rnb_tpu.health), parked for the
        #: executor's take_shed() drain — inert without deadlines
        self._deadline_shed = []

    def take_shed(self):
        """Executor hook (rnb_tpu.runner): requests this stage shed
        internally because their deadline expired while the loader
        held their decoded rows -> [(card, where)]."""
        out, self._deadline_shed = self._deadline_shed, []
        return out

    def _drop_expired_ready(self) -> None:
        """The 'loader hold' deadline boundary (rnb_tpu.health): a
        decoded request whose absolute deadline passed while it waited
        on the ready queue is dropped before fusing — its slot rows
        are released (the emission takes the gapped copy path, exactly
        like a contained mid-slot decode failure) and it never burns a
        transfer or downstream service. A record is only dropped when
        EVERY card riding it (leader + coalesced followers) expired:
        the rows are shared, and one live follower still needs them.
        Inert when no card carries a deadline stamp."""
        if not self._ready or not any(
                getattr(rec.cards[0], "deadline_s", None) is not None
                for rec in self._ready):
            return
        kept = deque()
        for rec in self._ready:
            if all(_deadline_expired(tc) for tc in rec.cards):
                self._drop_coalesce(rec)
                self._release_handle_slot(rec.handle)
                # a shed paged hit releases its pin before its gather
                # ever dispatches — counted hit rows therefore bound
                # gather rows from above, never equal them exactly
                self._release_handle_plan(rec.handle)
                self._deadline_shed.extend((tc, "hold")
                                           for tc in rec.cards)
            else:
                kept.append(rec)
        self._ready = kept

    def enable_autotune(self, settings) -> BatchController:
        """Executor protocol (rnb_tpu.runner): drive this stage's
        hold deadline / accumulation target with a BatchController
        over the stage's own warmed bucket set — decisions can only
        name shapes warm-up already compiled. Under ragged dispatch
        every row count hits the same executable, so the candidate
        set is continuous (1..pool_rows): hold/batch decisions stop
        being quantized to the warmed-bucket vocabulary."""
        if self.ragged:
            self.autotune = BatchController.for_stage(
                settings, tuple(range(1, self.pool_rows + 1)),
                self.pool_rows)
            return self.autotune
        self.autotune = BatchController.for_stage(
            settings, self.row_buckets, self.max_clips)
        return self.autotune

    def enable_trace(self, tracer, step_idx: int) -> None:
        """On top of the base wiring (refinement stamps + staging
        occupancy): sample this stage's decode window — decodes in
        flight plus decoded-but-unemitted requests (deque len reads
        are GIL-atomic, safe from the sampler thread)."""
        super().enable_trace(tracer, step_idx)
        tracer.add_counter_source(
            trace.name("loader.s%d.inflight", step_idx),
            lambda: len(self._inflight) + len(self._ready))

    def _harvest(self) -> None:
        """Move decode-complete requests from in-flight to ready,
        preserving FIFO order (a slow head occupies the whole pool
        anyway, so out-of-order harvest buys nothing)."""
        while self._inflight and self._inflight[0].handle.ready:
            rec = self._inflight.popleft()
            rec.t_ready = time.monotonic()
            trace.instant("loader.decode_ready", rid=rec.cards[0].id)
            self._ready.append(rec)

    def _drop_coalesce(self, rec: "_FuseRecord") -> None:
        """Close a record's coalescing window (it is being finalized):
        later requests for its key consult the cache or re-decode."""
        if self._inflight_keys is not None:
            self._inflight_keys.pop(rec.key)

    def _park_failed(self, rec: "_FuseRecord", reason: str) -> None:
        """Every card riding this record — leader and coalesced
        followers — fails as a unit; none is ever cached. A contained
        failure releases its staging-slot rows (the slot recycles once
        its surviving batchmates are through) and any pinned page
        plan, and never stamps a feature insert."""
        self._drop_coalesce(rec)
        self._release_handle_slot(rec.handle)
        self._release_handle_plan(rec.handle)
        self._failed.extend((tc, reason) for tc in rec.cards)

    def _staging_default_slots(self) -> int:
        return self.DEFAULT_STAGING_SLOTS

    def _staging_min_slots(self) -> int:
        # _acquire_fused_slot frees slots by emitting before it ever
        # blocks, so even a single slot cannot self-deadlock
        return 1

    def _staging_shapes(self):
        # fused emissions ship bucket-sized row prefixes of ONE slot
        # shape — smaller buckets are contiguous prefix views, so no
        # per-bucket sub-pools are needed
        return [self._batch_shape(self.max_clips)]

    def _stage_target(self, n: int):
        """Submit-time row planning: place this request's rows into
        the open staging slot so the native pool decodes straight into
        its final position in the fused batch. The slot seals (next
        request opens a fresh one) exactly on the emission take rules
        — ``fuse`` requests or the row cap — so a full take is a
        contiguous row prefix and ships zero-copy."""
        if self.staging is None:
            return super()._stage_target(n)
        cap = self.max_clips
        if (self._open_slot is None or self._open_count >= self.fuse
                or self._open_rows + n > cap):
            self._open_slot = self._acquire_fused_slot()
            self._open_rows = 0
            self._open_count = 0
        slot = self._open_slot
        row0 = self._open_rows
        self.staging.add_ref(slot)
        self._open_rows += n
        self._open_count += 1
        return slot.buf[row0:row0 + n], slot, row0

    def _acquire_fused_slot(self):
        """A fresh slot for planning. On exhaustion, free slots by
        finishing our own work first (retire the oldest decode, emit)
        — the emission path is what releases slots, and it runs on
        this same executor thread, so blocking before draining would
        be a self-deadlock. Only when every slot is held by an
        in-flight transfer does this block (counted backpressure,
        bounded by the transfer worker)."""
        slot = self.staging.try_acquire(self._slot_shape)
        while slot is None:
            if self._inflight or self._ready:
                if not self._ready and self._inflight:
                    rec = self._inflight.popleft()
                    if self._wait_contained(rec):
                        rec.t_ready = time.monotonic()
                        self._ready.append(rec)
                self._harvest()
                self._emit()
                slot = self.staging.try_acquire(self._slot_shape)
                continue
            slot = self.staging.acquire(self._slot_shape)
        return slot

    def _wait_contained(self, rec: "_FuseRecord") -> bool:
        """Wait one decode; True on success. A *transient* failure
        (rnb_tpu.faults taxonomy) is retried by synchronous re-decode
        up to the step's ``fault_retry_budget``; a *permanent* failure
        (or an exhausted budget) parks the request(s) on the
        take_failed() queue instead of poisoning its batchmates or
        being mis-attributed to whichever request triggered the
        emission; unclassified errors stay fatal."""
        handle, video = rec.handle, rec.video
        try:
            handle.wait(video)
            return True
        except Exception as e:
            kind = classify_error(e)
            if kind is FATAL:
                raise
            reason = fault_reason(e)
            if kind is TRANSIENT:
                max_retries, backoff_ms = self.fault_retry_budget
                for _ in range(int(max_retries)):
                    self._stage_retries += 1
                    if backoff_ms > 0:
                        time.sleep(backoff_ms / 1000.0)
                    try:
                        # the failed handle's tickets are already
                        # retired (wait() retires before raising);
                        # re-decode synchronously into the handle
                        decoder = get_decoder(video)
                        starts = self._sample_starts(decoder, video)
                        handle.out = self._decode_sync(decoder, video,
                                                       starts)
                        handle.error = None  # recovered (sticky wait)
                        # the re-decode owns a fresh buffer; the slot
                        # rows are dead (the emission for this record
                        # takes the copy path)
                        self._release_handle_slot(handle)
                        return True
                    except Exception as e2:
                        kind2 = classify_error(e2)
                        if kind2 is FATAL:
                            raise
                        reason = fault_reason(e2)
                        if kind2 is not TRANSIENT:
                            # re-decode reached a permanent verdict:
                            # further retries cannot help
                            self._park_failed(rec, reason)
                            return False
                reason = "retries-exhausted:" + reason
            self._park_failed(rec, reason)
            return False

    def take_failed(self):
        """Drain internally-contained requests (executor protocol,
        rnb_tpu.runner._drain_stage_failures)."""
        out, self._failed = self._failed, []
        return out

    def take_retries(self) -> int:
        """Drain the internal transient-retry count (executor
        protocol): retries performed during fused-batch assembly, fed
        into the job-wide num_retries accounting."""
        n, self._stage_retries = self._stage_retries, 0
        return n

    def _emit(self) -> bool:
        """Fuse ready requests (up to ``fuse`` / the ring max rows)
        into one padded batch + TimeCardList and ship it — zero-copy
        straight from the staging slot when the take is the slot's
        contiguous row prefix, else through the seed copy path. The
        finished emission lands on the ready queue (``_pop_ready``):
        synchronously after the inline transfer, or from the transfer
        worker under ``transfer_async``. Returns True when ready
        records were consumed (progress), False when nothing was
        takeable; a take whose every decode failed still returns True
        (the failures are on the take_failed() queue)."""
        with trace.span("loader.emit"):
            return self._emit_take()

    def _emit_take(self) -> bool:
        """:meth:`_emit` body (split out so the traced path can wrap
        the whole take/assemble/handoff in one timeline span)."""
        cap = self.max_clips
        take, rows = [], 0
        while self._ready and len(take) < self.fuse:
            handle = self._ready[0].handle
            if take and rows + handle.n > cap:
                break
            rec = self._ready.popleft()
            # finalizing: close the coalescing window now — by the time
            # a later same-key request arrives, the successful decode is
            # in the cache (inserted below, same call)
            self._drop_coalesce(rec)
            take.append(rec)
            rows += handle.n
        if not take:
            return False
        # the take loop guarantees this (submit caps each request at
        # max_clips); a silent min() here would mask clip loss instead
        # of surfacing the broken invariant
        assert rows <= cap, (rows, cap)
        if hostprof.ENABLED:
            # batch-hold accounting: how long the oldest taken request
            # sat ready waiting for batchmates — the fill-wait half of
            # the latency/throughput trade, split out of emit_wait so
            # hostprof tables distinguish "holding for a batch" from
            # "waiting on decode"
            hostprof.add("loader.hold_wait",
                         max(0.0, time.monotonic() - take[0].t_ready))
        for rec in take:
            if rec.handle.slot is not None \
                    and rec.handle.slot is self._open_slot:
                # taking from the open slot seals it: later submits
                # must not plan rows into a buffer that is about to
                # be (or already is) handed to a transfer
                self._open_slot = None
                break
        ok = []
        with hostprof.section("loader.emit_wait"):
            for rec in take:
                if self._wait_contained(rec):
                    ok.append(rec)
        if not ok:
            return True
        rows = sum(rec.handle.n for rec in ok)
        # under ragged the emission ships the ONE pool shape with an
        # explicit rows_valid; the segment table maps each constituent
        # request to its row range
        bucket = self.pool_rows if self.ragged else \
            self._bucket_for(rows)
        offsets = None
        if self.ragged:
            offsets = segment_offsets_of(rec.handle.n for rec in ok)
        if self.autotune is not None:
            # every batched emission is attributed to its shipped
            # bucket (the actual row count under ragged, where every
            # count is a legal dispatch); emissions with no preceding
            # decision (forced drains) are back-filled as immediate
            # decisions so the --check invariant decisions >=
            # emissions holds
            self.autotune.note_emission(rows if self.ragged else bucket)
        # service-span origin for the autotune estimator: the batch
        # just closed (stopped accumulating); everything from here to
        # the emission landing on the ready queue — assemble, cache
        # insert, device_put (inline or on the worker), preprocess
        # dispatch — is this stage's residual service, the term
        # decide() budgets against slo_ms alongside the residual-fill
        # wait
        t_close = time.monotonic()
        if self._trace_step is not None:
            # phase-refinement stamps for every card shipping in this
            # emission: its decode ended at the record's harvest
            # instant (epoch-converted from the monotonic t_ready, and
            # clamped so a follower swallowed after the decode reads a
            # zero-length decode phase), and its hold ended NOW — the
            # batch just closed and the transfer path begins
            now_epoch = time.time()
            now_mono = time.monotonic()
            step = self._trace_step
            for rec in ok:
                decoded_at = now_epoch - max(0.0, now_mono - rec.t_ready)
                for tc in rec.cards:
                    _record_clamped(tc, "decode%d_done" % step,
                                    decoded_at)
                    _record_clamped(tc, "transfer%d_start" % step,
                                    now_epoch)
        out, slot = self._assemble(ok, rows, bucket)
        gather_plans = None
        insert_jobs = None
        if self.cache is not None and self.cache.paged:
            # paged cache: hit rows overlay from the clip arena and
            # miss rows publish into pages — both on DEVICE, after
            # the pool's transfer (_overlay_pages in the transfer
            # body), so the host-side insert/hit memcpys of the blob
            # path below are deleted outright. Insert-after-success
            # holds: the jobs run only once device_put returned.
            gather_plans = []
            insert_jobs = []
            for i, rec in enumerate(ok):
                h = rec.handle
                row0 = int(offsets[i])
                if h.gather_plan is not None:
                    gather_plans.append((row0, h.gather_plan))
                    h.gather_plan = None
                elif rec.key is not None:
                    insert_jobs.append((rec.key, row0, h.n))
                self._stamp_feature_insert(rec.cards[0], rec.fkey,
                                           row0, h.n)
        elif self.cache is not None:
            # insert-after-success: only decodes that reached this
            # point populate the cache. Both insert flavors copy the
            # rows out of the slot BEFORE the transfer/recycle below,
            # so a cached entry can never alias recycled staging
            # memory. Ragged entries are host row extents (exactly n
            # rows, no bucket padding, no insert-time device_put —
            # hits re-enter the pool fill); bucketed entries stay the
            # padded device batch hits serve zero-copy.
            with hostprof.section("loader.cache_insert"):
                for rec in ok:
                    if rec.key is not None:
                        n = rec.handle.n
                        if self.ragged:
                            self.cache.insert_rows(rec.key,
                                                   rec.handle.out, n)
                        else:
                            self.cache.insert_host(
                                rec.key, rec.handle.out, n,
                                self._batch_shape(self._bucket_for(n)),
                                dtype=self._wire_dtype)
        cards = []
        for rec in ok:
            cards.extend(rec.cards)
        self._note_emission_padding(rows, bucket, cards)
        if slot is not None:
            # the taken rows are consumed once the transfer below
            # confirms; the begin/finish_transfer hold keeps the slot
            # unreusable until then, so the refs can retire now
            self.staging.begin_transfer(slot)
            for rec in ok:
                self._release_handle_slot(rec.handle)
        # the controller's service estimator is keyed by the same
        # vocabulary its decisions use: the shipped bucket — or, under
        # ragged, the VALID row count (every emission ships the pool
        # shape, but with a chunked network body the real service
        # scales with valid rows; keying all samples at pool_rows
        # would blend every candidate's estimate into one EWMA)
        service_key = rows if self.ragged else bucket
        if self._worker is not None:
            # pipelined handoff: the worker transfers batch N while
            # this thread plans/harvests batch N+1
            self._worker.submit(
                lambda: self._transfer_job(out, slot, rows, cards,
                                           service_key, t_close,
                                           offsets, gather_plans,
                                           insert_jobs))
            return True
        self._transfer_sync(out, slot, rows, cards, service_key,
                            t_close, offsets, gather_plans,
                            insert_jobs)
        return True

    def _min_live_row(self, slot) -> int:
        """Lowest row of a not-yet-taken decode planned into ``slot``
        (records still in the ready/in-flight windows); the slot's row
        capacity when none. Bounds how far an emission may read/zero
        the slot without racing a live decode."""
        lo = slot.buf.shape[0]
        for rec in self._ready:
            h = rec.handle
            if h.slot is slot and h.row0 < lo:
                lo = h.row0
        for rec in self._inflight:
            h = rec.handle
            if h.slot is slot and h.row0 < lo:
                lo = h.row0
        return lo

    def _assemble(self, ok, rows: int, bucket: int):
        """The fused batch bytes for one emission: ``(array, slot)``.
        A non-None slot means zero-copy — the array is the slot's
        C-contiguous bucket prefix, assembled by the decoder itself.
        None means the copy fallback ran: non-native decodes, re-decoded
        retries, partial-slot takes (hold-timeout leftovers), a
        contained failure's row gap, or staging disabled."""
        slot = ok[0].handle.slot
        if slot is not None and ok[0].handle.row0 == 0 \
                and bucket <= slot.buf.shape[0]:
            staged, row = True, 0
            for rec in ok:
                h = rec.handle
                if h.slot is not slot or h.row0 != row:
                    staged = False  # gap: failure/retry/partial history
                    break
                row += h.n
            if staged and bucket > self._min_live_row(slot):
                # the transfer window would cover rows a live decode
                # is still writing — only possible after a partial
                # (hold-timeout) take left batchmates in flight
                staged = False
            if staged:
                if bucket > rows and not self.ragged:
                    with hostprof.section("loader.emit_copy"):
                        # seed byte parity: padding rows stay zeroed.
                        # Under ragged the consumer's kernel masks the
                        # pool tail, so the memset is skipped
                        slot.buf[rows:bucket] = 0
                self.staging.note_staged()
                return slot.buf[:bucket], slot
        with hostprof.section("loader.emit_alloc"):
            # copy fallback (RNB-H007 baselined): rows [0, rows) are
            # overwritten below; only the padding tail needs zeroing
            out = np.empty(self._batch_shape(bucket),
                           dtype=self._wire_dtype)
        row = 0
        with hostprof.section("loader.emit_copy"):
            for rec in ok:
                n = rec.handle.n
                out[row:row + n] = rec.handle.out[:n]
                row += n
            if row < out.shape[0] and not self.ragged:
                # ragged consumers mask the pool tail in-jit; only the
                # bucketed path needs zeroed padding bytes
                out[row:] = 0
        for rec in ok:
            # rows copied out: slot references retire immediately
            self._release_handle_slot(rec.handle)
        if self.staging is not None:
            self.staging.note_copied()
        return out, None

    def _overlay_pages(self, batch, gather_plans, insert_jobs):
        """Paged-cache device work for one emission, strictly after
        its pool transfer: overlay hit rows from the clip arena (the
        only place they ever materialize — their slot rows shipped
        uninitialized) and publish miss rows into pages
        (insert-after-success: decode and transfer both completed by
        now). Runs before the normalize dispatch, so gathered hit
        rows feed the identical jitted path a miss feeds."""
        if gather_plans:
            src = np.full((int(batch.shape[0]),), -1, np.int32)
            for row0, plan in gather_plans:
                src[row0:row0 + plan.valid] = plan.src_rows
            with hostprof.section("loader.cache_gather"):
                batch = self._clip_arena.gather(batch, src)
            for _, plan in gather_plans:
                # dispatched: the gather captured the slab value, so
                # the pins can release (rnb_tpu.pager limbo rule)
                plan.release()
        if insert_jobs:
            with hostprof.section("loader.cache_insert"):
                for key, row0, n in insert_jobs:
                    self.cache.insert_pages(key, batch, row0, n)
        return batch

    def _transfer_sync(self, out, slot, rows: int, cards,
                       bucket: int, t_close: float,
                       offsets=None, gather_plans=None,
                       insert_jobs=None) -> None:
        """Inline transfer on the executor thread (transfer_async
        off): the seed path minus the assembly — the transfer is
        confirmed lazily at the slot's next acquire, so the executor
        still never blocks on transfer completion."""
        jax, _ = _jax_numpy()
        with hostprof.section("loader.device_put"), \
                trace.span("loader.transfer"):
            batch = jax.device_put(out, self._jax_device)
        if slot is not None:
            self.staging.finish_transfer(slot, batch)
        if gather_plans is not None or insert_jobs is not None:
            batch = self._overlay_pages(batch, gather_plans,
                                        insert_jobs)
        if self._trace_step is not None:
            at = time.time()
            for tc in cards:
                _record_clamped(tc, "transfer%d_done" % self._trace_step,
                                at)
        if self._preprocess is not None or \
                self._preprocess_ragged is not None:
            with hostprof.section("loader.preprocess_dispatch"):
                batch = self._normalize_emission(batch, rows)
        self._push_ready(((self._wrap_batch(batch, rows, offsets),),
                          None, TimeCardList(cards)),
                         bucket, time.monotonic() - t_close)

    def _transfer_job(self, out, slot, rows: int, cards,
                      bucket: int, t_close: float,
                      offsets=None, gather_plans=None,
                      insert_jobs=None) -> None:
        """Transfer-worker body: issue the device_put for batch N
        while the executor decodes batch N+1 into the next slot;
        confirm completion (alias-probed) before releasing the slot's
        transfer hold. Runs off the executor thread."""
        jax, _ = _jax_numpy()
        with hostprof.section("transfer.device_put"), \
                trace.span("loader.transfer"):
            batch = jax.device_put(out, self._jax_device)
        if slot is not None:
            with hostprof.section("transfer.confirm"):
                self.staging.confirm_now(slot, batch)
        if gather_plans is not None or insert_jobs is not None:
            batch = self._overlay_pages(batch, gather_plans,
                                        insert_jobs)
        if self._trace_step is not None:
            at = time.time()
            for tc in cards:
                _record_clamped(tc, "transfer%d_done" % self._trace_step,
                                at)
        if self._preprocess is not None or \
                self._preprocess_ragged is not None:
            with hostprof.section("transfer.preprocess_dispatch"):
                batch = self._normalize_emission(batch, rows)
        self._push_ready(((self._wrap_batch(batch, rows, offsets),),
                          None, TimeCardList(cards)),
                         bucket, time.monotonic() - t_close)

    def _push_ready(self, emission, bucket=None,
                    service_s=None) -> None:
        """Queue a finished emission; ``bucket``/``service_s`` carry
        the batch-close -> ready service span alongside it. The span
        is measured where completion happens (possibly the transfer
        worker thread) but fed to the single-threaded controller only
        at ``_pop_ready``, on the owning executor thread."""
        with self._out_lock:
            self._out_ready.append((emission, bucket, service_s))

    def _pop_ready(self):
        with self._out_lock:
            if self._out_ready:
                emission, bucket, service_s = self._out_ready.popleft()
            else:
                return None
        if self.autotune is not None and bucket is not None:
            # self-reported service estimator: under transfer_async
            # every emission surfaces here (never through a stamp-
            # bearing __call__ return), so the runner's stamp-based
            # feed would otherwise starve and service_for() would
            # stay optimistically 0 — the loader reports its own
            # close->ready span instead (AUTOTUNE_SELF_SERVICE)
            self.autotune.observe_service(bucket, service_s)
        return emission

    def take_ready(self):
        """Executor protocol (rnb_tpu.runner): a completed fused
        emission ready to publish, or None. Drained at the top of the
        hot loop so finished transfers publish ahead of new input.
        Re-raises transfer-pipeline failures on the executor thread —
        a dead worker must abort the job, not hang it."""
        if self._worker is not None:
            self._worker.raise_if_failed()
        if self.staging is not None:
            self.staging.raise_if_failed()
        return self._pop_ready()

    def _emit_hit(self, entry, time_card):
        """A cache hit emits immediately as its own dispatch: there is
        no decode to overlap and no host work to amortize, so holding
        it for fusion would only add latency. Wrapped in a TimeCardList
        for schema uniformity with fused emissions."""
        tensors, non_tensors, tc = self._materialize_hit(entry, time_card)
        return tensors, non_tensors, TimeCardList([tc])

    #: harvest-check tick while decodes are in flight but nothing is
    #: ready: bounds how late a completed decode is noticed
    HARVEST_TICK_S = 0.005

    def next_deadline_s(self):
        """Seconds until this stage next needs an idle poll, or None
        when it holds no work. The executor shrinks its queue-poll
        timeout to this, so hold-timeout emissions fire ~on time
        instead of on the next 50 ms poll tick — the round-5 frontier
        measured that granularity as the light-load p99 floor
        (57-61 ms at 111 req/s vs the 5-8 ms configured hold)."""
        with self._out_lock:
            if self._out_ready:
                return 0.0  # a completed emission awaits publishing
        self._harvest()  # peek-only: fresh view of completed decodes
        if self._ready:
            if not self._inflight:
                return 0.0  # nothing else can fuse: emit now
            waited = time.monotonic() - self._ready[0].t_ready
            if self.autotune is not None:
                # the executor's poll clamp derives from the
                # controller's deadline, not the static constant —
                # peek: this runs every poll tick, and counting ticks
                # as decisions would corrupt the Autotune: accounting
                dec = self.autotune.peek(
                    len(self._ready),
                    sum(rec.handle.n for rec in self._ready), waited)
                remaining = max(0.0, dec.hold_s - waited)
            else:
                remaining = max(0.0, self.max_hold_ms / 1000.0 - waited)
            # two triggers race: the hold expiry AND an in-flight
            # decode completing (which can satisfy the fuse/rows/
            # nothing-in-flight rules early) — bound by the sooner
            return min(remaining, self.HARVEST_TICK_S)
        if self._inflight:
            return self.HARVEST_TICK_S
        if self._worker is not None and self._worker.outstanding():
            return self.HARVEST_TICK_S  # a transfer is still in flight
        return None

    def poll(self):
        """Idle tick from the executor (no arrival within its queue
        poll window): emit a held batch that has met an emission rule
        — most importantly the hold-timeout, which otherwise could
        only fire on the NEXT arrival and would pay a full
        inter-arrival gap instead of max_hold_ms (+ the executor's
        poll granularity). Returns an emission or None (an emission
        handed to the transfer worker surfaces on a later poll /
        take_ready once its transfer completes)."""
        out = self._pop_ready()
        if out is not None:
            return out
        self._harvest()
        self._drop_expired_ready()
        if not self._ready:
            return None
        rows_ready = sum(rec.handle.n for rec in self._ready)
        waited_s = time.monotonic() - self._ready[0].t_ready
        if self.autotune is not None:
            # controller-supplied deadline and accumulation target
            # replace the static max_hold_ms / fixed-fuse comparison:
            # immediate dispatch when growing the batch cannot meet
            # the latency budget, a grown target when it can — always
            # capped by the static fuse/row ceilings
            dec = self.autotune.decide(len(self._ready), rows_ready,
                                       waited_s)
            should_emit = (len(self._ready) >= self.fuse
                           or rows_ready >= self.max_clips
                           or rows_ready >= dec.target_rows
                           or not self._inflight
                           or waited_s >= dec.hold_s)
        else:
            should_emit = (len(self._ready) >= self.fuse
                           or rows_ready >= self.max_clips
                           or not self._inflight
                           or waited_s * 1000.0 > self.max_hold_ms)
        if should_emit:
            self._emit()
            return self._pop_ready()
        return None

    def __call__(self, tensors, non_tensors, time_card):
        video = str(non_tensors)
        fkey, fplan = self._feature_probe(video)
        if fplan is not None:
            # feature-page hit: no decode, no transfer, no downstream
            # forward — emit standalone immediately (holding it for
            # fusion would only add latency; there is nothing to
            # amortize), like the bucketed _emit_hit below
            tensors_out, nt, tc = self._materialize_feature(
                fplan, time_card)
            return tensors_out, nt, TimeCardList([tc])
        key, entry = self._cache_lookup(video, key=fkey)
        if entry is not None and self.ragged:
            # ragged hit: the hit fills its pool rows like a decode
            # that completed instantly — it rides the next fused
            # emission (one pool transfer for hits and misses alike)
            # instead of dispatching standalone.
            n = entry.valid
            time_card.num_clips = n
            time_card.cache_hit = True
            if self.ragged_stats is not None:
                self.ragged_stats["cache_hit_rows"] += n
            target, hit_slot, hit_row0 = self._stage_target(n)
            if self.cache.paged:
                # zero-copy paged hit: the reserved slot rows ship
                # UNINITIALIZED — the pinned plan rides the handle and
                # the entry's page rows overlay them on device, after
                # the pool's transfer (_overlay_pages). No host byte
                # of this request ever moves.
                handle = _DecodeHandle(target, n, slot=hit_slot,
                                       row0=hit_row0)
                handle.gather_plan = entry
            else:
                # blob hit: the decode is skipped; the memcpy into
                # the slot slice is the whole cost (its own hostprof
                # section, split from the lookup above)
                with hostprof.section("loader.cache_gather"):
                    np.copyto(target, entry.batch[:n])
                handle = _DecodeHandle(target, n, slot=hit_slot,
                                       row0=hit_row0)
            self._stamp_decode_done(time_card)
            if self.autotune is not None:
                self.autotune.observe_rows(n)
            rec = _FuseRecord(handle, video, time_card, key=None,
                              fkey=fkey)
            # join the in-flight window IN ARRIVAL ORDER (the handle
            # is already complete, so harvest promotes it at its FIFO
            # turn): jumping straight to _ready would reorder the
            # slot's planned row ranges and force every such take off
            # the zero-copy staged path onto the assembly-copy
            # fallback
            self._inflight.append(rec)
            out = self.poll()
            if out is not None:
                return out
            return None, None, None
        if entry is not None:
            # hit: serve from the device-resident entry right now — no
            # decode, no transfer, no fuse wait
            return self._emit_hit(entry, time_card)
        if key is not None:
            time_card.cache_hit = False
            live = self._inflight_keys.get(key)
            if live is not None:
                # coalesce: park this request on the in-flight decode;
                # it rides the leader's fused emission through the
                # TimeCardList fan-out (one decode, one row range, N
                # stamped cards)
                time_card.num_clips = live.handle.n
                time_card.cache_coalesced = True
                self.cache.note_coalesced()
                live.cards.append(time_card)
                out = self.poll()
                if out is not None:
                    return out
                return None, None, None
        handle = self._decode_submit(video, time_card)
        if self.autotune is not None:
            # rows-per-request estimator: converts a bucket-growth
            # target into a residual request count (coalesced
            # followers add cards, not rows, so they do not feed this)
            self.autotune.observe_rows(handle.n)
        rec = _FuseRecord(handle, video, time_card, key=key, fkey=fkey)
        if key is not None:
            self._inflight_keys.put(key, rec)
        self._inflight.append(rec)
        out = self.poll()  # harvest + the emission rules
        if out is not None:
            return out
        if len(self._inflight) >= self.depth:
            # backpressure: retire the oldest decode before accepting
            # more work, then ship what is ready
            rec = self._inflight.popleft()
            if self._wait_contained(rec):
                rec.t_ready = time.monotonic()
                self._ready.append(rec)
            self._harvest()
            self._emit()
            out = self._pop_ready()
            if out is not None:
                return out
        return None, None, None

    #: ready-queue poll tick while waiting on the transfer worker at
    #: end-of-stream — bounded by one transfer's latency
    FLUSH_TICK_S = 0.0005

    def flush(self):
        """End-of-stream: drain everything, one fused batch per call
        (the executor calls flush() until it returns None). Under
        ``transfer_async`` this also drains the transfer worker —
        emissions it still holds surface here before the stage
        reports itself dry."""
        out = self._pop_ready()
        if out is not None:
            return out
        while self._inflight:
            rec = self._inflight.popleft()
            if self._wait_contained(rec):
                rec.t_ready = time.monotonic()
                self._ready.append(rec)
        while True:
            if self._ready:
                self._emit()
                out = self._pop_ready()
                if out is not None:
                    return out
                # that whole batch failed (cards on the take_failed()
                # queue) or it was handed to the transfer worker —
                # keep draining either way
                continue
            if self._worker is not None and self._worker.outstanding():
                self._worker.raise_if_failed()
                time.sleep(self.FLUSH_TICK_S)
                out = self._pop_ready()
                if out is not None:
                    return out
                continue
            if self._worker is not None:
                # a failing last job can drop outstanding() to 0 with
                # its error recorded but not yet observed — re-check
                # before reporting a clean drain, or the runner would
                # break out silently with the batch's requests lost
                self._worker.raise_if_failed()
            if self.staging is not None:
                self.staging.raise_if_failed()
            return None

    def discard_pending(self) -> None:
        """Abort path (called from the executor's finally): retire
        every submitted decode so native tickets don't pin buffers
        forever — and every staging-slot reference, then stop the
        transfer worker (draining its queue keeps the slot accounting
        balanced). Ready-but-unemitted handles hold un-retired tickets
        too — harvest only peeks, it never waits."""
        for rec in list(self._inflight) + list(self._ready):
            self._drop_coalesce(rec)
            self.discard(rec.handle, rec.video)
        self._inflight.clear()
        self._ready.clear()
        self._open_slot = None
        if self._worker is not None:
            self._worker.close()
        with self._out_lock:
            # abort path: completed-but-unpublished emissions are
            # dropped, exactly like ready-but-unemitted records
            self._out_ready.clear()


class R2P1DRunner(StageModel):
    """Neural-net stage over any contiguous layer range [start..end].

    Reference equivalent: R2P1DRunner (models/r2p1d/model.py:20-84).
    Weights come from the shared checkpoint filtered to the range;
    replicas share one executable and one device parameter copy.
    ``max_rows`` must match the row count this stage actually receives
    (max clips, or the segment row count under segment parallelism) so
    warm-up compiles the exact shape.
    """

    #: dispatches can arrive as a flat row pool at ONE compiled shape
    #: (RaggedBatch) — the stage then warms exactly one executable and
    #: its yuv420 fused ingest masks the pool tail via the ragged
    #: primitive (root 'ragged' config key, rnb_tpu.ops.ragged)
    SUPPORTS_RAGGED = True

    #: under pager.feature_cache this stage is the feature-page
    #: consumer: it inserts its output rows after each successful
    #: forward and serves feature hits by gathering them back
    #: (rnb_tpu.pager; enable_pager below)
    SUPPORTS_PAGER = True

    #: this stage declares a partition spec for the step-level `shard`
    #: key (rnb_tpu.parallel.shardplan): temporal conv kernels and the
    #: head shard their output-channel axis. rnb-lint RNB-G010 rejects
    #: `shard` on steps whose model class does not declare this.
    SUPPORTS_SHARD = True

    def __init__(self, device, start_index: int = 1,
                 end_index: int = NUM_LAYERS,
                 num_classes: int = KINETICS_CLASSES,
                 layer_sizes=R18_LAYER_SIZES,
                 max_rows: int = MAX_CLIPS,
                 consecutive_frames: int = CONSECUTIVE_FRAMES,
                 num_warmups: int = NUM_WARMUPS,
                 ckpt_path: Optional[str] = None,
                 row_buckets=None, factored_shortcut: bool = False,
                 pixel_path: str = "rgb",
                 ragged: bool = False, ragged_pool_rows=None,
                 ragged_chunk_rows=None, dct_coeffs_per_frame=None,
                 shard_devices=None, shard_degree=None,
                 shard_axis: str = "tp",
                 shard_hbm_budget_mb=None,
                 **kwargs):
        super().__init__(device)
        import jax
        if not (1 <= start_index <= end_index <= NUM_LAYERS):
            raise ValueError("invalid layer range [%s..%s]"
                             % (start_index, end_index))
        if pixel_path not in ("rgb", "yuv420", "dct"):
            raise ValueError("pixel_path must be 'rgb', 'yuv420' or "
                             "'dct', got %r" % (pixel_path,))
        if pixel_path in ("yuv420", "dct") and start_index != 1:
            raise ValueError("pixel_path=%r fuses the ingest in "
                             "front of layer 1; a [%d..%d] stage "
                             "receives activations, not frames"
                             % (pixel_path, start_index, end_index))
        if dct_coeffs_per_frame is not None and pixel_path != "dct":
            raise ValueError("dct_coeffs_per_frame only applies to "
                             "pixel_path='dct'")
        self.start_index = int(start_index)
        self.end_index = int(end_index)
        self.max_rows = int(max_rows)
        self.pixel_path = pixel_path
        self.dct_coeffs_per_frame = dct_coeffs_per_frame
        # Ragged row-pool dispatch (rnb_tpu.ops.ragged): the stage's
        # input is always the ONE pool shape (== the declared max row
        # axis) plus a traced rows_valid scalar — one warmup compile
        # covers every batch composition, and for yuv420 the fused
        # ingest's Pallas grid skip spends no arithmetic on pad rows.
        self.ragged = bool(ragged)
        self.pool_rows = (resolve_pool_rows(ragged_pool_rows,
                                            self.max_rows, "max_rows")
                          if self.ragged else None)
        # the ragged applier's dynamic row-tile grid: None = auto
        # (default_ragged_chunk), 0 = whole-pool apply, else a divisor
        # of the pool capacity
        self.ragged_chunk_rows = 0
        if self.ragged:
            if ragged_chunk_rows is None:
                self.ragged_chunk_rows = default_ragged_chunk(
                    self.pool_rows)
            else:
                self.ragged_chunk_rows = int(ragged_chunk_rows)
                if self.ragged_chunk_rows < 0 or (
                        self.ragged_chunk_rows
                        and self.pool_rows % self.ragged_chunk_rows):
                    raise ValueError(
                        "ragged_chunk_rows=%r must be 0 (whole-pool "
                        "apply) or a positive divisor of pool_rows=%d"
                        % (ragged_chunk_rows, self.pool_rows))
        # Intra-stage tensor parallelism (rnb_tpu.parallel.shardplan):
        # shard_degree=None means the step declared no `shard` key at
        # all — a declared degree (1 included) arms the feasibility
        # gate and the Shard: accounting, so an operator iterating
        # degrees sees the same telemetry shape at every point
        self.shard_declared = shard_degree is not None
        self.shard_degree = int(shard_degree) if self.shard_declared \
            else 1
        self.shard_axis = str(shard_axis)
        self.shard_hbm_budget_mb = (
            float(shard_hbm_budget_mb)
            if shard_hbm_budget_mb is not None else None)
        if self.shard_degree < 1:
            raise ValueError("shard_degree must be >= 1, got %r"
                             % (shard_degree,))
        if self.shard_degree > 1:
            from rnb_tpu.parallel.shardplan import validate_degree
            validate_degree(self.shard_degree, start_index, end_index,
                            num_classes)
            if self.ragged and self.ragged_chunk_rows:
                if ragged_chunk_rows is not None:
                    raise ValueError(
                        "ragged_chunk_rows=%r cannot be combined with "
                        "shard_degree=%d: the sharded applier is ONE "
                        "whole-pool program (chunking would change the "
                        "op graph and break bit parity with the "
                        "unsharded forward)"
                        % (ragged_chunk_rows, self.shard_degree))
                # the auto-chunk default collapses to whole-pool apply
                self.ragged_chunk_rows = 0
        layer_sizes = tuple(layer_sizes)
        self._jax_device = _resolve(device)
        #: the exact network-shape arguments the analytic FLOP walk
        #: needs (rnb_tpu/models/r2p1d/flops.py) — kept verbatim so
        #: the devobs compute seam below can never drift from the
        #: network this stage actually compiled
        self._flops_args = dict(
            consecutive_frames=int(consecutive_frames),
            num_classes=int(num_classes),
            layer_sizes=layer_sizes,
            factored_shortcut=bool(factored_shortcut))
        # factored_shortcut matches converted reference checkpoints
        # (models/r2p1d/convert.py); default is the plain projection
        self._merge = None
        self._input_sharding = None
        self._shard_mesh = None
        if self.shard_degree > 1:
            from jax.sharding import NamedSharding, PartitionSpec
            from rnb_tpu.parallel.shardplan import (
                build_shard_mesh, make_sharded_apply, make_merge,
                shard_variables)
            if shard_devices is not None:
                ring = [_resolve(DeviceSpec(d)) for d in shard_devices]
            else:
                ring = list(jax.devices()[:self.shard_degree])
            if len(ring) != self.shard_degree:
                raise ValueError(
                    "shard_degree=%d needs exactly that many devices, "
                    "got %d" % (self.shard_degree, len(ring)))
            self._shard_mesh = build_shard_mesh(ring, self.shard_degree,
                                                self.shard_axis)
            host_vars = _shared_params(self.start_index, self.end_index,
                                       num_classes, layer_sizes,
                                       ckpt_path, self._jax_device,
                                       bool(factored_shortcut))
            self._variables = shard_variables(host_vars,
                                              self._shard_mesh,
                                              self.shard_axis)
            self._apply = make_sharded_apply(
                self.start_index, self.end_index, num_classes,
                layer_sizes, self._shard_mesh,
                factored_shortcut=bool(factored_shortcut),
                pixel_path=pixel_path, ragged=self.ragged,
                axis_name=self.shard_axis)(self._variables)
            if self.end_index == NUM_LAYERS:
                self._merge = make_merge(self._shard_mesh,
                                         self.shard_axis)
            self._input_sharding = NamedSharding(self._shard_mesh,
                                                 PartitionSpec())
        else:
            self._apply = _shared_apply(self.start_index, self.end_index,
                                        num_classes, layer_sizes,
                                        bool(factored_shortcut),
                                        pixel_path=pixel_path,
                                        ragged=self.ragged,
                                        ragged_chunk=self.ragged_chunk_rows)
            self._variables = _shared_params(self.start_index,
                                             self.end_index,
                                             num_classes, layer_sizes,
                                             ckpt_path, self._jax_device,
                                             bool(factored_shortcut))
        # warm-up on the exact steady-state shape and dtype — both come
        # from the same static declarations (input_shape_for /
        # input_dtype_for) the pipeline checker matches against the
        # upstream step, so the compiled signature and the declared
        # wire contract can never diverge. A wrong-shape/dtype dummy
        # would compile a signature the hot loop never uses and pay the
        # real compile on the first request instead.
        self._steady_shape = self.input_shape_for(
            start_index=self.start_index, max_rows=self.max_rows,
            consecutive_frames=consecutive_frames,
            pixel_path=self.pixel_path,
            dct_coeffs_per_frame=self.dct_coeffs_per_frame)[0]
        import jax.numpy as jnp
        warm_dtype = getattr(jnp, self.input_dtype_for(
            start_index=self.start_index, pixel_path=self.pixel_path))
        self._warm_dtype = warm_dtype
        # match the loader's row bucketing: compile one executable per
        # bucket row count so no compile lands in the measured window.
        # Under ragged dispatch the warmup matrix collapses to the ONE
        # pool shape — any row_buckets in the config are the bucketed
        # counterfactual, never warmed shapes — which is exactly what
        # the Compiles: accounting asserts at runtime.
        if self.ragged:
            warm_rows = (self.pool_rows,)
        else:
            warm_rows = _normalize_row_buckets(row_buckets,
                                               self.max_rows,
                                               "max_rows")
        # feature pages (rnb_tpu.pager), wired via enable_pager()
        self.pager = None
        self._feature_arena = None
        self._logit_pool = None
        # Shard feasibility gate + accounting: a declared `shard` key
        # (any degree, 1 included) projects the per-device HBM
        # footprint with the ONE formula the planner also uses
        # (shardplan.projected_device_mb) and — when hbm_budget_mb is
        # armed — REJECTS the launch when the projection does not fit.
        # This is the honest "this stage does not fit at this degree"
        # failure the headline shard config demonstrates at degree 1;
        # memledger owns the live accounting once a feasible launch
        # runs.
        self.shard_stats = None
        if self.shard_declared:
            from rnb_tpu.parallel.shardplan import (
                min_feasible_degree, projected_device_mb,
                split_param_bytes)
            rep_bytes, sh_bytes = split_param_bytes(self._variables)
            pool_bytes = 0
            if self.ragged:
                per_row = int(np.dtype(warm_dtype).itemsize)
                for extent in self._steady_shape[1:]:
                    per_row *= int(extent)
                pool_bytes = int(self.pool_rows) * per_row
            projected = projected_device_mb(rep_bytes, sh_bytes,
                                            pool_bytes,
                                            self.shard_degree)
            floor = 1
            if self.shard_hbm_budget_mb is not None:
                floor = min_feasible_degree(
                    rep_bytes, sh_bytes, pool_bytes,
                    self.shard_hbm_budget_mb)
            self.shard_stats = {
                "degree": self.shard_degree,
                "axis": self.shard_axis,
                "gathers": 0,
                "collective_ms": 0.0,
                "rows": 0,
                "budget_mb": self.shard_hbm_budget_mb,
                "projected_mb": projected,
                "replicated_bytes": int(rep_bytes),
                "sharded_bytes": int(sh_bytes),
                "pool_bytes": int(pool_bytes),
                "min_degree": floor if floor is not None else 0,
            }
            if self.shard_hbm_budget_mb is not None \
                    and projected > self.shard_hbm_budget_mb:
                feasible = min_feasible_degree(
                    rep_bytes, sh_bytes, pool_bytes,
                    self.shard_hbm_budget_mb)
                raise ValueError(
                    "shard launch rejected: projected per-device HBM "
                    "%.1f MiB at shard degree %d exceeds "
                    "hbm_budget_mb=%.1f for layers [%d..%d] "
                    "(replicated %.1f MiB + sharded %.1f MiB / degree "
                    "+ pool %.1f MiB); smallest feasible degree of "
                    "(1, 2, 4, 8): %s"
                    % (projected, self.shard_degree,
                       self.shard_hbm_budget_mb, self.start_index,
                       self.end_index, rep_bytes / 2**20,
                       sh_bytes / 2**20, pool_bytes / 2**20,
                       feasible if feasible is not None else "none"))
        #: set by the executor's bind_shard_step() so the merge
        #: collective's hostprof section / trace span carry the step
        #: index even on trace-disabled runs
        self._sec_collective = None
        self._tr_collective = None
        #: jit-entry signature accounting (rnb_tpu.compilestats):
        #: distinct applier input signatures == executables this stage
        #: requires; frozen by the executor at measured-window start
        self.compiles = SignatureTracker()
        for rows in warm_rows:
            host = np.zeros((rows,) + self._steady_shape[1:],
                            warm_dtype)
            # the declared shape vocabulary is observed even under
            # num_warmups=0 (warmup explicitly opted out): the
            # steady_new accounting flags OUT-OF-VOCABULARY
            # signatures — drift — not the expected first-call
            # compile of an unwarmed run
            self.compiles.observe(host)
            if num_warmups > 0:
                if self._input_sharding is not None:
                    dummy = jax.device_put(host, self._input_sharding)
                else:
                    dummy = jax.device_put(host, self._jax_device)
                for _ in range(num_warmups):
                    if self.ragged:
                        out = self._apply(self._variables, dummy,
                                          np.int32(rows))
                    else:
                        out = self._apply(self._variables, dummy)
                    jax.block_until_ready(out)
                    if self._merge is not None:
                        # warm the merge collective too: its compile
                        # must not land inside the measured window
                        jax.block_until_ready(self._merge(out))

    def input_shape(self):
        return (self._steady_shape,)

    def bind_shard_step(self, step_idx: int) -> None:
        """Executor protocol (rnb_tpu.runner): hand the stage its step
        index so the merge collective can be host-timed under the
        ``exec{i}.collective`` hostprof section / trace span. Called
        unconditionally (unlike enable_trace) because the collective
        tax must reach hostprof and the Shard: accounting even on
        trace-disabled runs; a no-op for unsharded stages."""
        if self._merge is None:
            return
        self._sec_collective = "exec%d.collective" % int(step_idx)
        self._tr_collective = trace.name("exec%d.collective",
                                         int(step_idx))

    def enable_pager(self, pager) -> None:
        """Executor protocol (rnb_tpu.runner): attach this stage as
        the feature-page consumer. Its config fingerprint keys every
        entry (two configs can never alias), its ``features`` arena
        holds output logit rows written strictly after each
        successful forward, and a feature hit gathers those exact
        rows back over a preallocated zero pool — bit-identical to
        re-running the forward, because they ARE the original
        forward's rows."""
        import jax
        self.pager = pager
        if pager.feature is None:
            return
        if self.shard_degree > 1:
            raise ValueError(
                "pager.feature_cache cannot attach to a shard-sharded "
                "stage (shard_degree=%d): the feature arena is a "
                "single-device gather pool, while sharded logits live "
                "on a %d-device mesh" % (self.shard_degree,
                                         self.shard_degree))
        if not self.ragged:
            raise ValueError(
                "pager.feature_cache requires ragged dispatch on the "
                "consuming stage: feature rows gather into the ONE "
                "pool shape")
        num_classes = int(self._flops_args["num_classes"])
        if self.end_index != NUM_LAYERS:
            raise ValueError(
                "pager.feature_cache requires the consuming stage to "
                "end the network (end_index=%d): cached rows must be "
                "final outputs, not mid-pipeline activations another "
                "stage still transforms" % (self.end_index,))
        fingerprint = (
            "r2p1d-logits", self.start_index, self.end_index,
            num_classes, self._flops_args["layer_sizes"],
            self._flops_args["factored_shortcut"],
            self._flops_args["consecutive_frames"],
            self.pixel_path, self.dct_coeffs_per_frame)
        self._feature_arena = pager.create_arena(
            "features", (num_classes,), np.float32,
            device=self._jax_device,
            gather_keys=("feature_gathers", "feature_gather_rows"))
        pager.feature.attach(self._feature_arena, fingerprint)
        zeros = np.zeros((self.pool_rows, num_classes), np.float32)
        self._logit_pool = jax.device_put(zeros, self._jax_device)
        pager.adopt_shared("runner-logit-pool", self._logit_pool,
                           device_label=str(self._jax_device))

    def _take_feature_plan(self, time_card):
        """The pinned feature-page plan riding this dispatch's card,
        if any (stamped by the loader's feature-hit emission), removed
        from the card so downstream consumers never see it."""
        if self.pager is None or self.pager.feature is None:
            return None
        cards = (time_card.time_cards
                 if isinstance(time_card, TimeCardList)
                 else (time_card,))
        for tc in cards:
            plan = getattr(tc, "feature_plan", None)
            if plan is not None:
                tc.feature_plan = None
                return plan
        return None

    def _insert_features(self, out, time_card) -> None:
        """Publish this forward's output rows for every constituent
        request the loader stamped (insert-after-success: this runs
        only once ``_apply`` returned; contained failures and sheds
        never reach it)."""
        feature = None if self.pager is None else self.pager.feature
        if feature is None or not feature.ready:
            return
        cards = (time_card.time_cards
                 if isinstance(time_card, TimeCardList)
                 else (time_card,))
        for tc in cards:
            job = getattr(tc, "feature_insert", None)
            if job is not None:
                tc.feature_insert = None
                key, row0, n = job
                feature.insert(key, out, row0, n)

    def _cost_bytes_per_row(self):
        """Per-row "bytes accessed" from XLA's own cost model of the
        compiled steady-shape applier — the arithmetic-intensity
        denominator of the Compute stages: roofline detail. None when
        the backend exposes no cost analysis (the figure is then
        unreported rather than guessed). Called only on devobs-enabled
        runs, pre-barrier, where the warmed signature makes the
        lower/compile a cache hit."""
        try:
            import jax
            import jax.numpy as jnp
            arg = jax.ShapeDtypeStruct(self._steady_shape,
                                       self._warm_dtype)
            if self.ragged:
                lowered = self._apply.lower(
                    self._variables, arg,
                    jax.ShapeDtypeStruct((), jnp.int32))
            else:
                lowered = self._apply.lower(self._variables, arg)
            analysis = lowered.compile().cost_analysis()
            if isinstance(analysis, (list, tuple)):
                analysis = analysis[0] if analysis else {}
            nbytes = float(analysis.get("bytes accessed", 0.0))
        except Exception:
            return None
        rows = int(self._steady_shape[0])
        if nbytes <= 0.0 or rows <= 0:
            return None
        return nbytes / rows

    def compute_profile(self):
        """The devobs compute/memory seam (rnb_tpu.devobs): declared
        per-row FLOPs from the analytic walk this stage's exact
        network shape feeds, the shared parameter copy's footprint
        (keyed by object identity, so replicas sharing one
        ``_shared_params`` copy dedupe in the ledger), and — under
        ragged dispatch — the one pool-shaped input's bytes."""
        import jax

        from rnb_tpu.models.r2p1d.flops import range_flops_per_clip
        flops_per_row = range_flops_per_clip(
            self.start_index, self.end_index, **self._flops_args)
        params_bytes = int(jax.tree_util.tree_reduce(
            lambda acc, leaf: acc + int(getattr(leaf, "nbytes", 0)),
            self._variables, 0))
        pool_bytes = 0
        if self.ragged:
            per_row = 1
            for extent in self._steady_shape[1:]:
                per_row *= int(extent)
            pool_bytes = (int(self.pool_rows) * per_row
                          * int(np.dtype(self._warm_dtype).itemsize))
        return {
            "flops_per_row": int(flops_per_row),
            "devices": 1,
            "bytes_per_row": self._cost_bytes_per_row(),
            "params_key": ("params", id(self._variables)),
            "params_bytes": params_bytes,
            "pool_bytes": pool_bytes,
        }

    @classmethod
    def input_shape_for(cls, start_index: int = 1,
                        max_rows: int = MAX_CLIPS,
                        consecutive_frames: int = CONSECUTIVE_FRAMES,
                        pixel_path: str = "rgb",
                        dct_coeffs_per_frame=None, **_kwargs):
        # the exact steady-state input shape warm-up compiles. The
        # temporal extent follows the pipeline's consecutive_frames
        # everywhere: at layer 1 it IS consecutive_frames; mid-pipeline
        # it is whatever the upstream range [1..start-1] downsampled
        # those frames to (the static LAYER_INPUT_SHAPES table only
        # covers the default 8)
        from rnb_tpu.models.r2p1d.network import range_output_shape
        if pixel_path == "yuv420":
            shape = (int(consecutive_frames),
                     packed_frame_bytes(FRAME_HW, FRAME_HW))
        elif pixel_path == "dct":
            shape = (int(consecutive_frames),
                     dct_frame_elems(FRAME_HW, FRAME_HW,
                                     dct_coeffs_per_frame))
        elif int(start_index) == 1:
            shape = ((int(consecutive_frames),)
                     + tuple(LAYER_INPUT_SHAPES[1][1:]))
        else:
            shape = range_output_shape(1, int(start_index) - 1,
                                       int(consecutive_frames))
        return ((int(max_rows),) + tuple(shape),)

    @classmethod
    def input_dtype_for(cls, start_index: int = 1,
                        pixel_path: str = "rgb", **_kwargs):
        # the dtype the pipeline actually flows: packed uint8 planes
        # under pixel_path='yuv420', packed int16 coefficient rows
        # under 'dct'; the loader's preprocess emits bfloat16 into
        # layer 1; an upstream network stage emits float32 activations
        # (R2Plus1DClassifier casts its output)
        if pixel_path == "yuv420":
            return "uint8"
        if pixel_path == "dct":
            return "int16"
        return "bfloat16" if int(start_index) == 1 else "float32"

    @classmethod
    def output_dtype_for(cls, **_kwargs):
        return "float32"

    @staticmethod
    def output_shape():
        # full-range default; partial ranges declare their exact
        # feature-map shape via output_shape_for below
        return ((MAX_CLIPS, KINETICS_CLASSES),)

    @classmethod
    def output_shape_for(cls, start_index: int = 1,
                         end_index: int = NUM_LAYERS,
                         num_classes: int = KINETICS_CLASSES,
                         max_rows: int = MAX_CLIPS,
                         consecutive_frames: int = CONSECUTIVE_FRAMES,
                         **_kwargs):
        # exact per-range shape — fixes the restriction the reference
        # shipped broken (hardcoded (10, 400) for every range, its TODO
        # #69 at models/r2p1d/model.py:76-80): a conv1-4 stage declares
        # its feature map, so the runtime can size rings for a
        # mid-pipeline layer split
        from rnb_tpu.models.r2p1d.network import range_output_shape
        per_row = range_output_shape(int(start_index), int(end_index),
                                     int(consecutive_frames),
                                     int(num_classes))
        return ((int(max_rows),) + per_row,)

    def __call__(self, tensors, non_tensors, time_card):
        jax, _ = _jax_numpy()
        pb = tensors[0]
        fplan = self._take_feature_plan(time_card)
        if fplan is not None:
            # feature-page hit: the loader shipped a stub pool and
            # skipped decode + transfer; this stage skips the whole
            # forward and gathers the exact logit rows the original
            # request computed over a preallocated zero pool
            src = np.full((int(self._logit_pool.shape[0]),), -1,
                          np.int32)
            src[:fplan.valid] = fplan.src_rows
            out = self._feature_arena.gather(self._logit_pool, src)
            fplan.release()
            offsets = getattr(pb, "segment_offsets",
                              (0, int(pb.valid)))
            return (RaggedBatch(out, pb.valid, offsets),), \
                non_tensors, time_card
        if self._input_sharding is not None:
            x = jax.device_put(pb.data, self._input_sharding)
        else:
            x = jax.device_put(pb.data, self._jax_device)
        self.compiles.observe(x)
        if self.ragged:
            out = self._apply(self._variables, x, np.int32(pb.valid))
        else:
            out = self._apply(self._variables, x)
        if self._merge is not None:
            # the forward leaves logits channel-sharded; the merge
            # gather is the stage-level collective, host-timed as its
            # own span so the collective tax is a measured number —
            # block on the forward first so the timing brackets ONLY
            # the collective
            jax.block_until_ready(out)
            rid = getattr(time_card, "id", None)
            t0 = time.perf_counter()
            if self._sec_collective is not None:
                with hostprof.section(self._sec_collective), \
                        trace.span(self._tr_collective, rid):
                    out = self._merge(out)
                    jax.block_until_ready(out)
            else:
                out = self._merge(out)
                jax.block_until_ready(out)
            stats = self.shard_stats
            stats["gathers"] += 1
            stats["collective_ms"] += (time.perf_counter() - t0) * 1e3
        if self.shard_stats is not None:
            self.shard_stats["rows"] += int(pb.valid)
        self._insert_features(out, time_card)
        if self.ragged:
            # the pool shape rides through: downstream consumers (and
            # the executor's payload validation) see the same segment
            # table the loader filled
            offsets = getattr(pb, "segment_offsets",
                              (0, int(pb.valid)))
            return (RaggedBatch(out, pb.valid, offsets),), \
                non_tensors, time_card
        return (PaddedBatch(out, pb.valid),), non_tensors, time_card


class R2P1DSingleStep(StageModel):
    """Fused decode + full network in one stage — the no-pipelining
    baseline (reference models/r2p1d/model.py:161-235). Emits the
    predicted class id as the non-tensor payload; declares no tensor
    outputs, so the runtime allocates no rings for it."""

    # open config kwargs (row_buckets, pixel_path, cache_mb, ...) are
    # forwarded to the embedded loader/runner pair — the static
    # unconsumed-key check (rnb_tpu.analysis.graph) honors their
    # constructor signatures through this declaration
    FORWARDS_CONFIG_TO = (R2P1DLoader, R2P1DRunner)

    def __init__(self, device, num_classes: int = KINETICS_CLASSES,
                 layer_sizes=R18_LAYER_SIZES, max_clips: int = MAX_CLIPS,
                 consecutive_frames: int = CONSECUTIVE_FRAMES,
                 num_warmups: int = NUM_WARMUPS,
                 ckpt_path: Optional[str] = None, **kwargs):
        super().__init__(device)
        self.loader = R2P1DLoader(device, max_clips=max_clips,
                                  consecutive_frames=consecutive_frames,
                                  num_warmups=num_warmups, **kwargs)
        # surface the embedded loader's clip cache (if configured) and
        # staging pool so the executor's stats sinks see them
        # (rnb_tpu.runner)
        self.cache = self.loader.cache
        self.staging = self.loader.staging
        # the inner runner must warm the same bucket shapes the loader
        # emits, or the first occurrence of each bucket would pay a
        # silent XLA recompile inside the measured window
        self.net = R2P1DRunner(device, start_index=1, end_index=NUM_LAYERS,
                               num_classes=num_classes,
                               layer_sizes=layer_sizes,
                               max_rows=max_clips,
                               consecutive_frames=consecutive_frames,
                               num_warmups=num_warmups,
                               ckpt_path=ckpt_path,
                               row_buckets=kwargs.get("row_buckets"),
                               factored_shortcut=kwargs.get(
                                   "factored_shortcut", False),
                               pixel_path=kwargs.get("pixel_path",
                                                     "rgb"),
                               dct_coeffs_per_frame=kwargs.get(
                                   "dct_coeffs_per_frame"))

    def enable_trace(self, tracer, step_idx: int) -> None:
        """Forward to the embedded loader: its phase-refinement
        stamps and occupancy sources apply to this fused step's
        index (rnb_tpu.runner executor protocol)."""
        self.loader.enable_trace(tracer, step_idx)

    def compute_profile(self):
        """devobs seam: the embedded network's profile IS this fused
        step's (the loader contributes bytes via its own cache/staging
        attributes, not FLOPs)."""
        return self.net.compute_profile()

    def input_shape(self):
        return None

    @staticmethod
    def output_shape():
        return None

    def __call__(self, tensors, non_tensors, time_card):
        _, jnp = _jax_numpy()
        (pb,), _, time_card = self.loader(None, non_tensors, time_card)
        (logits,), _, time_card = self.net((pb,), None, time_card)
        # sum+argmax on device; only the class id crosses to the host
        # (a full logits D2H per video would serialize on transfer
        # latency — painful through a remote-TPU tunnel)
        pred = int(jnp.argmax(
            jnp.sum(logits.data[: logits.valid], axis=0)))
        return None, pred, time_card


class R2P1DMeshRunner(StageModel):
    """Clip-sharded inference stage over a device sub-mesh.

    The TPU-native successor to the reference's segment-parallel
    topology (config/r2p1d-segment.json: loader fans each video out as
    ``num_segments`` row-splits to replica processes, a host aggregator
    re-sums the logits — reference runner.py:138-173,
    models/r2p1d/model.py:238-285). Here the split, the compute and the
    merge are ONE compiled program over an ``sp`` mesh axis: every core
    computes logits for its clip shard and a ``psum`` over ICI reduces
    them on-device — no queue fan-out, no TimeCard forks, no host
    aggregator hop.

    Config: home the stage on one device (its executor thread) and pass
    ``mesh_devices`` = the logical device indices forming the sub-mesh
    (the home device should be among them), factored as ``dp`` x
    ``sp = len(mesh_devices)/dp``. ``sp`` need not divide ``max_clips``
    — the sharded step pads the clip axis to the next multiple inside
    the compiled program (masked rows), so e.g. 8 cores serve 15-clip
    batches with none idle. Consumes the loader's ``raw_output`` uint8
    batches and emits predicted class ids (final-stage contract, no
    tensor outputs).

    Pipeline-friendliness (round-3 verdict weak#5): with ``dp > 1`` the
    stage accumulates ``dp`` queued videos and dispatches them as ONE
    sharded step (videos over ``dp``, clips over ``sp``). With
    ``sync_preds=False`` the emitted predictions are **device values**
    — no per-video host sync blocks the executor thread; in-flight
    dispatches are bounded, ``flush()`` pads and runs a partial video
    batch at end-of-stream, and ``finalize()`` drains outstanding
    device work before the finish barrier so the measured *window*
    still covers all compute. Caveat (same as the executor's
    ``async_dispatch``): per-record ``inference{i}`` spans then measure
    dispatch, not device compute, so latency percentiles from async
    runs under-report — the default ``sync_preds=True`` blocks per
    dispatch and keeps them honest.
    """

    def __init__(self, device, mesh_devices, dp: int = 1,
                 max_clips: int = MAX_CLIPS,
                 consecutive_frames: int = CONSECUTIVE_FRAMES,
                 num_classes: int = KINETICS_CLASSES,
                 layer_sizes=R18_LAYER_SIZES,
                 num_warmups: int = NUM_WARMUPS,
                 ckpt_path: Optional[str] = None,
                 max_inflight: int = 4, sync_preds: bool = True,
                 factored_shortcut: bool = False,
                 pixel_path: str = "rgb", **kwargs):
        super().__init__(device)
        from collections import deque

        import numpy as _np
        import jax
        from jax.sharding import Mesh

        from rnb_tpu.devices import DeviceSpec
        from rnb_tpu.parallel.sharded import ShardedInference

        self.dp = int(dp)
        if len(mesh_devices) % self.dp != 0:
            raise ValueError("dp=%d must divide len(mesh_devices)=%d"
                             % (self.dp, len(mesh_devices)))
        devs = [DeviceSpec(int(d)).resolve() for d in mesh_devices]
        mesh = Mesh(_np.array(devs).reshape(
            self.dp, len(devs) // self.dp), ("dp", "sp"))
        self.max_clips = int(max_clips)
        self.consecutive_frames = int(consecutive_frames)
        self.max_inflight = int(max_inflight)
        self.sync_preds = bool(sync_preds)
        self._si = ShardedInference(
            mesh, max_clips=self.max_clips,
            consecutive_frames=self.consecutive_frames,
            num_classes=num_classes, layer_sizes=tuple(layer_sizes),
            ckpt_path=ckpt_path, factored_shortcut=factored_shortcut,
            pixel_path=pixel_path)
        self.pixel_path = pixel_path
        #: devobs compute seam inputs (see compute_profile): the mesh
        #: covers len(mesh_devices) devices and every row costs the
        #: full [1..5] network
        self._mesh_size = len(devs)
        self._flops_args = dict(
            consecutive_frames=self.consecutive_frames,
            num_classes=int(num_classes),
            layer_sizes=tuple(layer_sizes),
            factored_shortcut=bool(factored_shortcut))
        self._acc = []            # (PaddedBatch, TimeCard) awaiting dp fill
        self._inflight = deque()  # unretired device prediction arrays
        dummy = np.zeros(self._si.batch_shape(self.dp), np.uint8)
        for _ in range(num_warmups):
            vids, mask = self._si.place(dummy, [self.max_clips] * self.dp)
            jax.block_until_ready(self._si.run(vids, mask))

    def input_shape(self):
        # one source of truth for the per-video shape in either pixel
        # path: the sharded step's own batch geometry
        return (self._si.batch_shape(1)[1:],)

    def input_sharding(self):
        """Edge-contract target (rnb_tpu.handoff, root ``handoff``
        key): per-item payloads land mesh-replicated, so the
        ``dp``-stacked dispatch reshards purely on-device — the
        sharded program's clip padding happens inside the jit, so the
        raw per-video clip axis cannot be pre-split over ``sp``
        (max_clips need not divide), but a replicated placement
        already puts the bytes on every core the shard_map will
        read from."""
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(self._si.mesh, PartitionSpec())

    @classmethod
    def input_shape_for(cls, max_clips: int = MAX_CLIPS,
                        consecutive_frames: int = CONSECUTIVE_FRAMES,
                        pixel_path: str = "rgb", **_kwargs):
        # mirrors ShardedInference.batch_shape(1)[1:] without building
        # the mesh: one raw loader video batch per dispatch row
        if pixel_path == "yuv420":
            return ((int(max_clips), int(consecutive_frames),
                     packed_frame_bytes(FRAME_HW, FRAME_HW)),)
        return ((int(max_clips), int(consecutive_frames),
                 FRAME_HW, FRAME_HW, 3),)

    @classmethod
    def input_dtype_for(cls, **_kwargs):
        # consumes the loader's raw_output uint8 batches in either
        # pixel path (the sharded program owns normalize/ingest)
        return "uint8"

    def compute_profile(self):
        """devobs seam: full-range per-row FLOPs over the whole
        sub-mesh (the MFU denominator counts every core the shard_map
        spans); the replicated parameter copy's bytes are counted once
        per mesh (keyed by the shared variables object)."""
        import jax

        from rnb_tpu.models.r2p1d.flops import range_flops_per_clip
        flops_per_row = range_flops_per_clip(1, NUM_LAYERS,
                                             **self._flops_args)
        params_bytes = int(jax.tree_util.tree_reduce(
            lambda acc, leaf: acc + int(getattr(leaf, "nbytes", 0)),
            self._si.variables, 0))
        return {
            "flops_per_row": int(flops_per_row),
            "devices": self._mesh_size,
            "bytes_per_row": None,
            "params_key": ("params", id(self._si.variables)),
            "params_bytes": params_bytes,
            "pool_bytes": 0,
        }

    @staticmethod
    def output_shape():
        return None

    def _dispatch(self, pbs, cards):
        """One sharded step over len(pbs)==dp videos; async device
        preds out, bounded in-flight window."""
        jax, jnp = _jax_numpy()

        # re-home the loader's device batches straight onto the mesh
        # sharding (device-to-device, ICI on hardware — no host bounce)
        batch = jnp.stack([pb.data for pb in pbs])
        vids = jax.device_put(batch, self._si.batch_sharding)
        mask = self._si.place_mask([pb.valid for pb in pbs])
        logits = self._si.run(vids, mask)
        preds = jnp.argmax(logits, axis=-1)  # computed on-device
        if self.sync_preds:
            # honest latency spans: the executor stamps
            # inference_finish right after we return
            jax.block_until_ready(preds)
        else:
            self._inflight.append(preds)
            while len(self._inflight) > self.max_inflight:
                # bound the async queue: retire the oldest dispatch
                jax.block_until_ready(self._inflight.popleft())
        out_card = (TimeCardList(list(cards)) if len(cards) > 1
                    else cards[0])
        return None, preds, out_card

    def __call__(self, tensors, non_tensors, time_card):
        pb = tensors[0]
        want = self.input_shape()[0]
        if tuple(pb.data.shape) != tuple(want):
            # fail fast with the likely cause: the loader and this
            # stage must agree on pixel_path (a mismatch would
            # otherwise surface as a cryptic shape error deep inside
            # shard_map tracing)
            raise ValueError(
                "mesh stage received batch shape %r but expects %r — "
                "do the loader's and this stage's pixel_path settings "
                "agree? (this stage: %r)"
                % (tuple(pb.data.shape), tuple(want), self.pixel_path))
        self._acc.append((pb, time_card))
        if len(self._acc) < self.dp:
            return None, None, None  # swallow until the dp axis fills
        pbs, cards = zip(*self._acc)
        self._acc = []
        return self._dispatch(list(pbs), list(cards))

    def flush(self):
        """End-of-stream: run the partial video batch, padding the dp
        axis with zero videos (mask 0 — dead rows, no result rows)."""
        if not self._acc:
            return None
        _, jnp = _jax_numpy()
        pbs, cards = zip(*self._acc)
        self._acc = []
        pbs = list(pbs)
        while len(pbs) < self.dp:
            pbs.append(PaddedBatch(jnp.zeros_like(pbs[0].data), 0))
        return self._dispatch(pbs, list(cards))

    def finalize(self):
        """Drain outstanding device work (called by the executor before
        the finish barrier, keeping the measured window honest)."""
        jax, _ = _jax_numpy()
        while self._inflight:
            jax.block_until_ready(self._inflight.popleft())


class R2P1DAggregator(StageModel):
    """Host-side merge of segment logits (reference
    models/r2p1d/model.py:238-285): accumulates summed logits per
    request id until ``aggregate`` segments arrived, merges the forked
    TimeCards, and emits the argmax class. Declares no tensor outputs.
    """

    def __init__(self, device, aggregate: int, **kwargs):
        super().__init__(device)
        self.aggregate = int(aggregate)
        if self.aggregate < 1:
            raise ValueError("aggregate must be >= 1")
        # request id -> [summed logits, [TimeCard, ...]]
        self._pending: Dict[Any, list] = {}

    def input_shape(self):
        return ((MAX_CLIPS, KINETICS_CLASSES),)

    @staticmethod
    def output_shape():
        return None

    def __call__(self, tensors, non_tensors, time_card):
        logits = np.asarray(tensors[0].data,
                            np.float32)[: tensors[0].valid]
        contribution = logits.sum(axis=0)
        entry = self._pending.setdefault(time_card.id,
                                         [np.zeros_like(contribution), []])
        entry[0] = entry[0] + contribution
        entry[1].append(time_card)
        if len(entry[1]) < self.aggregate:
            return None, None, None  # swallow until all segments arrive
        del self._pending[time_card.id]
        merged = (TimeCard.merge(entry[1]) if self.aggregate > 1
                  else entry[1][0])
        pred = int(entry[0].argmax())
        return None, pred, merged


class R2P1DVideoPathIterator(VideoPathIterator):
    """Cycles a video dataset forever (reference
    models/r2p1d/model.py:86-113 scanned a root/label/video tree).
    Scans ``root`` (or $RNB_TPU_DATA_ROOT) for video files (.y4m
    uncompressed, .mjpg/.mjpeg compressed); without a dataset it cycles
    a fixed population of synthetic video ids, which the decode layer
    resolves procedurally.
    """

    EXTENSIONS = video_path_provider.VIDEO_EXTENSIONS

    @classmethod
    def scan_tree(cls, root: str) -> list:
        """Sorted video paths from a root/label/video tree; delegates
        to the jax-free scan in rnb_tpu.video_path_provider."""
        return video_path_provider.scan_video_tree(root, cls.EXTENSIONS)

    def __init__(self, root: Optional[str] = None,
                 num_synthetic: int = 200):
        super().__init__()
        import itertools
        import os
        root = root or os.environ.get("RNB_TPU_DATA_ROOT")
        videos = (self.scan_tree(root)
                  if root and os.path.isdir(root) else [])
        if not videos:
            videos = ["synth://kinetics/video-%04d" % i
                      for i in range(num_synthetic)]
        self._videos = videos
        self._cycle = itertools.cycle(videos)

    def dataset(self):
        """Finite universe for popularity wrappers (ZipfPathIterator)."""
        return list(self._videos)

    def __iter__(self):
        return self._cycle


class LargeSmallSelector(QueueSelector):
    """Content-aware router: rare large (max-clip) videos go to queue 1,
    everything else to queue 0, so small videos can be batched without
    head-of-line blocking — the Replicate & Batch placement policy
    (reference models/r2p1d/model.py:288-296). Keyed off the
    ``num_clips`` the loader stamped on the TimeCard.

    The "large" threshold binds to the producing loader's configured
    clip population (``bind_stage``): a config sampling
    ``num_clips_population`` != the default [1, 15] still routes its
    own largest class to the dedicated lane. Falls back to the module
    default when the stage exposes no sampler."""

    def __init__(self, num_queues: int):
        super().__init__(num_queues)
        if num_queues != 2:
            raise ValueError("LargeSmallSelector routes over exactly two "
                             "queues (got %d)" % num_queues)
        self._threshold = MAX_CLIPS

    def bind_stage(self, model) -> None:
        sampler = getattr(model, "sampler", None)
        threshold = getattr(sampler, "max_clips", None)
        if threshold:
            # the loader truncates every request at its own max_clips
            # cap (submit/__call__ starts[:max_clips]), so a population
            # max above the cap would be an unreachable threshold and
            # the large lane would starve
            cap = getattr(model, "max_clips", None)
            if cap:
                threshold = min(int(threshold), int(cap))
            self._threshold = int(threshold)

    def select(self, tensors, non_tensors, time_card) -> int:
        return (1 if getattr(time_card, "num_clips", 0) >= self._threshold
                else 0)
