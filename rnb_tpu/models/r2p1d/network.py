"""R(2+1)D action-recognition network in Flax, TPU-first.

The factored spatiotemporal convolution of Tran et al., CVPR'18: each
3-D conv is decomposed into a 2-D spatial conv + BN + ReLU + 1-D
temporal conv, with the intermediate channel count chosen so the
factored pair has the same parameter budget as the full 3-D kernel.

Capability parity with the reference's partial-network builder
(models/r2p1d/network.py:9-60 and the R2Plus1D-PyTorch submodule it
imports): any contiguous layer range [start..end] of the 5-layer
R(2+1)D-18 can be instantiated, with a trailing global-average-pool +
flatten when layer 5 is included and the classification head only when
the range reaches layer 5.

TPU-first design choices (deliberate deviations from the reference's
CUDA/torch layout, not omissions):
  * **NDHWC (channels-last) activations** — the layout XLA:TPU tiles
    best; the reference used torch NCDHW.
  * **bfloat16 activations/params with fp32 BatchNorm statistics** via
    a dtype knob, so convs land on the MXU at full rate.
  * The residual shortcut on downsampling blocks is a plain strided
    1x1x1 conv + BN (the standard ResNet projection); the reference's
    submodule factored even this 1x1x1 conv into a (2+1)D pair, which
    adds a bottleneck without a modeling rationale.
  * A BN + ReLU follows the stem conv (standard ResNet stem); the
    reference applied the stem conv bare.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from rnb_tpu.ops.handoff_dma import ring_all_gather_body


def _gather_shard_params(axis_name: str, shards: int):
    """``nn.map_variables`` trans_in_fn: reassemble a weight-sharded
    module's full-width params from the local shard via the handoff
    ring all-gather (pure data movement, so the gathered kernel is
    bitwise the unsharded one). Only meaningful inside a ``shard_map``
    over ``axis_name``."""
    gather = ring_all_gather_body(axis_name, shards, axis=-1)

    def trans_in(tree):
        return jax.tree_util.tree_map(gather, tree)

    return trans_in

NUM_LAYERS = 5
KINETICS_CLASSES = 400
R18_LAYER_SIZES = (2, 2, 2, 2)  # residual blocks in layers 2..5

#: Per-layer-range input shapes (rows, T, H, W, C), row dim = clip count.
#: Mirrors the reference's input-shape table (models/r2p1d/model.py:29-33)
#: transposed to NDHWC.
LAYER_INPUT_SHAPES = {
    1: (8, 112, 112, 3),
    2: (8, 56, 56, 64),
    3: (8, 56, 56, 64),
    4: (4, 28, 28, 128),
    5: (2, 14, 14, 256),
}

LAYER_FEATURES = {2: 64, 3: 128, 4: 256, 5: 512}


def range_output_shape(start: int, end: int,
                       consecutive_frames: int = 8,
                       num_classes: int = KINETICS_CLASSES
                       ) -> Tuple[int, ...]:
    """Per-row output shape of the layer range [start..end].

    Walks the network's downsampling schedule: the stem halves H/W,
    layers 3-5 halve T/H/W (stride-2 convs with SAME-style padding, so
    odd extents round up). A range reaching layer 5 pools + classifies
    to ``(num_classes,)``. This is the exact shape the runtime needs to
    size buffer rings for a mid-pipeline layer split — the reference
    hardcoded full-range logits and documented the partial-range case
    as broken (its TODO #69, models/r2p1d/model.py:76-80).
    """
    if not (1 <= start <= end <= NUM_LAYERS):
        raise ValueError("invalid layer range [%s..%s]" % (start, end))
    t, h, w, c = LAYER_INPUT_SHAPES[start]
    if start == 1:
        t = int(consecutive_frames)
    for layer in range(start, end + 1):
        if layer == 1:
            h, w, c = -(-h // 2), -(-w // 2), 64
        else:
            c = LAYER_FEATURES[layer]
            if layer >= 3:
                t, h, w = -(-t // 2), -(-h // 2), -(-w // 2)
    if end == NUM_LAYERS:
        return (int(num_classes),)
    return (t, h, w, c)


def normalize_u8(x, dtype=jnp.bfloat16):
    """uint8 [0,255] frames -> ``dtype`` in [-1, 1] — the one
    normalization every ingest path (pipeline loader preprocess,
    sharded mesh step) must share. Pallas kernel on TPU, jnp
    elsewhere (rnb_tpu.ops.preprocess)."""
    from rnb_tpu.ops.preprocess import normalize_u8 as _impl
    return _impl(x, dtype=dtype)


def factored_channels(in_features: int, out_features: int,
                      t: int, d: int) -> int:
    """Intermediate width M_i of the (2+1)D factorization.

    Chosen so spatial (1,d,d) + temporal (t,1,1) convs together match
    the parameter count of the full (t,d,d) 3-D kernel (Tran et al.
    eq. for M_i).
    """
    num = t * d * d * in_features * out_features
    den = d * d * in_features + t * out_features
    return max(1, num // den)


class SpatioTemporalConv(nn.Module):
    """(2+1)D factored convolution: spatial 2-D conv, BN, ReLU, then
    temporal 1-D conv. Unbiased convs; BN carries the affine terms.

    ``shards > 1`` is the intra-stage tensor-parallel form (used only
    inside a ``shard_map`` over a ``shard_axis``-named mesh axis,
    rnb_tpu.parallel.shardplan): the *temporal* conv kernel lives
    SHARDED on its output-channel axis — each mesh member holds
    ``1/shards`` of its bytes at rest, which is where degree k buys
    its per-device HBM headroom — and is reassembled to full width by
    the handoff ring all-gather right before the conv
    (``nn.map_variables`` swaps the gathered kernel in). The conv
    itself then runs at the FULL declared width, so the activation
    math is op-for-op the unsharded program and the outputs are
    bitwise identical — a gather is pure data movement, and keeping
    the compute graph structurally identical is the only thing that
    survives XLA's bf16 excess-precision fusion (output-channel
    *compute* slicing is 1-ulp nondeterministic across program
    shapes; see shardplan's module docstring). The spatial conv, BN
    and shortcuts stay replicated: the factorization's ``mid`` widths
    (:func:`factored_channels`) are not divisible by 2/4, and ``mid``
    is always computed from the FULL feature count, so the
    parameter-parity formula is untouched by sharding.
    """

    features: int
    kernel: Tuple[int, int]       # (temporal extent, spatial extent)
    stride: Tuple[int, int] = (1, 1)  # (temporal, spatial)
    dtype: Any = jnp.bfloat16
    shards: int = 1
    shard_axis: str = "tp"

    @nn.compact
    def __call__(self, x, train: bool = False):
        t, d = self.kernel
        st, sd = self.stride
        mid = factored_channels(x.shape[-1], self.features, t, d)
        pad_d = d // 2
        pad_t = t // 2
        x = nn.Conv(mid, kernel_size=(1, d, d), strides=(1, sd, sd),
                    padding=((0, 0), (pad_d, pad_d), (pad_d, pad_d)),
                    use_bias=False, dtype=self.dtype, name="spatial")(x)
        x = nn.BatchNorm(use_running_average=not train, dtype=self.dtype,
                         name="bn")(x)
        x = nn.relu(x)
        if self.features % self.shards:
            raise ValueError(
                "shards=%d does not divide the temporal conv's %d "
                "output channels" % (self.shards, self.features))
        Conv = nn.Conv
        if self.shards > 1:
            Conv = nn.map_variables(
                nn.Conv, "params",
                trans_in_fn=_gather_shard_params(self.shard_axis,
                                                 self.shards),
                mutable=False)
        x = Conv(self.features, kernel_size=(t, 1, 1),
                 strides=(st, 1, 1),
                 padding=((pad_t, pad_t), (0, 0), (0, 0)),
                 use_bias=False, dtype=self.dtype, name="temporal")(x)
        return x


class SpatioTemporalResBlock(nn.Module):
    """Pre-shortcut residual block of two (2+1)D convs.

    ``factored_shortcut`` reproduces the reference submodule's
    downsampling shortcut exactly — a *factored* 1x1x1 (2+1)D pair with
    BN+ReLU in the middle — so checkpoints converted from the
    reference's torch format (checkpoint_convert) load with bit-exact
    structure. Off by default: the plain strided projection is the
    standard ResNet choice and avoids an unmotivated bottleneck.
    """

    features: int
    downsample: bool = False
    factored_shortcut: bool = False
    dtype: Any = jnp.bfloat16
    shards: int = 1
    shard_axis: str = "tp"

    @nn.compact
    def __call__(self, x, train: bool = False):
        stride = 2 if self.downsample else 1
        res = SpatioTemporalConv(self.features, kernel=(3, 3),
                                 stride=(stride, stride), dtype=self.dtype,
                                 shards=self.shards,
                                 shard_axis=self.shard_axis,
                                 name="conv1")(x, train)
        res = nn.BatchNorm(use_running_average=not train, dtype=self.dtype,
                           name="bn1")(res)
        res = nn.relu(res)
        res = SpatioTemporalConv(self.features, kernel=(3, 3),
                                 dtype=self.dtype, shards=self.shards,
                                 shard_axis=self.shard_axis,
                                 name="conv2")(res, train)
        res = nn.BatchNorm(use_running_average=not train, dtype=self.dtype,
                           name="bn2")(res)

        if self.downsample:
            if self.factored_shortcut:
                x = SpatioTemporalConv(self.features, kernel=(1, 1),
                                       stride=(2, 2), dtype=self.dtype,
                                       shards=self.shards,
                                       shard_axis=self.shard_axis,
                                       name="shortcut")(x, train)
            else:
                x = nn.Conv(self.features, kernel_size=(1, 1, 1),
                            strides=(2, 2, 2), use_bias=False,
                            dtype=self.dtype, name="shortcut")(x)
            x = nn.BatchNorm(use_running_average=not train, dtype=self.dtype,
                             name="shortcut_bn")(x)
        return nn.relu(x + res)


class SpatioTemporalResLayer(nn.Module):
    """A stack of residual blocks; the first may downsample."""

    features: int
    num_blocks: int
    downsample: bool = False
    factored_shortcut: bool = False
    dtype: Any = jnp.bfloat16
    shards: int = 1
    shard_axis: str = "tp"

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = SpatioTemporalResBlock(self.features,
                                   downsample=self.downsample,
                                   factored_shortcut=self.factored_shortcut,
                                   dtype=self.dtype, shards=self.shards,
                                   shard_axis=self.shard_axis,
                                   name="block0")(x, train)
        for i in range(1, self.num_blocks):
            x = SpatioTemporalResBlock(self.features, dtype=self.dtype,
                                       shards=self.shards,
                                       shard_axis=self.shard_axis,
                                       name="block%d" % i)(x, train)
        return x


class R2Plus1DNet(nn.Module):
    """Any contiguous layer range [start..end] of R(2+1)D-18.

    Layer 1 is the (2+1)D stem (3->64, spatial stride 2); layers 2-5 are
    residual stages 64/128/256/512 with spatiotemporal downsampling from
    layer 3 on. Including layer 5 appends global average pooling and a
    flatten to (rows, 512); the classification head lives in
    :class:`R2Plus1DClassifier`. Equivalent capability to the
    reference's R2Plus1DLayerNet (models/r2p1d/network.py:9-41).
    """

    start: int = 1
    end: int = NUM_LAYERS
    layer_sizes: Sequence[int] = R18_LAYER_SIZES
    factored_shortcut: bool = False
    dtype: Any = jnp.bfloat16
    shards: int = 1
    shard_axis: str = "tp"

    def __post_init__(self):
        super().__post_init__()
        if not (1 <= self.start <= self.end <= NUM_LAYERS):
            raise ValueError("invalid layer range [%s..%s]"
                             % (self.start, self.end))

    @nn.compact
    def __call__(self, x, train: bool = False):
        for layer in range(self.start, self.end + 1):
            if layer == 1:
                x = SpatioTemporalConv(64, kernel=(3, 7), stride=(1, 2),
                                       dtype=self.dtype, shards=self.shards,
                                       shard_axis=self.shard_axis,
                                       name="conv1")(x, train)
                x = nn.BatchNorm(use_running_average=not train,
                                 dtype=self.dtype, name="stem_bn")(x)
                x = nn.relu(x)
            else:
                x = SpatioTemporalResLayer(
                    LAYER_FEATURES[layer],
                    num_blocks=self.layer_sizes[layer - 2],
                    downsample=(layer >= 3),
                    factored_shortcut=self.factored_shortcut,
                    dtype=self.dtype, shards=self.shards,
                    shard_axis=self.shard_axis,
                    name="conv%d" % layer)(x, train)
        if self.end == NUM_LAYERS:
            x = jnp.mean(x, axis=(1, 2, 3))  # global spatiotemporal pool
        return x


class R2Plus1DClassifier(nn.Module):
    """Partial net + linear head when the range reaches the last layer.

    Equivalent capability to the reference's R2Plus1DLayerWrapper
    (models/r2p1d/network.py:44-60). Logits are returned in float32
    regardless of the compute dtype.
    """

    start: int = 1
    end: int = NUM_LAYERS
    num_classes: int = KINETICS_CLASSES
    layer_sizes: Sequence[int] = R18_LAYER_SIZES
    factored_shortcut: bool = False
    dtype: Any = jnp.bfloat16
    #: intra-stage tensor-parallel degree (shard_map only): the head's
    #: kernel/bias live column-sharded at rest, are ring-gathered for
    #: the full-width matmul (bitwise the unsharded logits), and each
    #: member keeps only its own column block — so logits leave the
    #: forward channel-sharded and the stage-level merge collective is
    #: the one host-timed gather (rnb_tpu.parallel.shardplan): the
    #: collective tax is measured, never buried inside the forward
    shards: int = 1
    shard_axis: str = "tp"

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = R2Plus1DNet(start=self.start, end=self.end,
                        layer_sizes=self.layer_sizes,
                        factored_shortcut=self.factored_shortcut,
                        dtype=self.dtype, shards=self.shards,
                        shard_axis=self.shard_axis,
                        name="net")(x, train)
        if self.end == NUM_LAYERS:
            if self.num_classes % self.shards:
                raise ValueError(
                    "shards=%d does not divide the %d-class head"
                    % (self.shards, self.num_classes))
            Dense = nn.Dense
            if self.shards > 1:
                Dense = nn.map_variables(
                    nn.Dense, "params",
                    trans_in_fn=_gather_shard_params(self.shard_axis,
                                                     self.shards),
                    mutable=False)
            x = Dense(self.num_classes, dtype=self.dtype,
                      name="linear")(x)
            if self.shards > 1:
                # keep only this member's column block: the slice is
                # pure movement, so the merge gather reassembles the
                # full-width logits bit-exactly
                local = self.num_classes // self.shards
                idx = lax.axis_index(self.shard_axis)
                x = lax.dynamic_slice_in_dim(x, idx * local, local,
                                             axis=-1)
        return x.astype(jnp.float32)
