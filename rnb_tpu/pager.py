"""One HBM page allocator: paged clip-cache entries + feature pages.

Until this module, three subsystems owned device memory separately —
the whole-blob LRU clip cache (rnb_tpu.cache: every hit memcpys rows
into the open staging slot; entries larger than the budget are skipped
outright), the per-(loader, shape) staging slabs (rnb_tpu.staging),
and handoff adoptions (rnb_tpu.handoff) — which fragments HBM and
makes a cache hit cost a host copy. Following Ragged Paged Attention
(PAPERS.md) applied to video rows, this module provides the unifying
layer:

* **One slab, fixed-size row pages** (:class:`Arena`): each arena owns
  a single device allocation ``(num_pages * page_rows,) + row_shape``
  — the only legal pool-shaped device allocation outside stage init
  (rnb-lint RNB-H010 enforces this) — carved into pages on a free
  list. Entries hold page *reference lists*: no fragmentation (any
  free pages serve any entry), no oversize skips (an entry needs
  pages, not a contiguous extent), and eviction frees pages, not
  blobs.
* **Zero-copy hits**: a hit pins its entry's pages and returns a
  :class:`GatherPlan` — flat slab row indices the consumption seam
  hands to the gather-from-pages kernel (rnb_tpu.ops.pages) AFTER the
  pool's device transfer. The hit rows never exist as host bytes.
* **Pin/limbo discipline**: pages freed (evicted) while a plan still
  pins them move to a limbo list and only re-enter the free list at
  unpin — an insert can therefore never recycle a page an in-flight
  gather has planned but not yet dispatched. (Once dispatched, jax's
  functional arrays make the read safe regardless: the gather captured
  the slab value; later donated writes produce a new one.)
* **Feature pages** (:class:`FeatureCache`, config-gated by
  ``pager.feature_cache``): post-stage activation rows keyed by
  (content key, stage fingerprint). The consuming stage registers its
  fingerprint; the loader probes at admission and, on a hit, the
  request skips decode, transfer AND the whole stage-0..N forward —
  the runner gathers the exact logit rows the original request
  computed (bit-identical by construction). Insert-after-success only:
  the runner inserts strictly after its forward returned, so contained
  failures and deadline sheds never populate feature pages.
* **Accounting**: registered under the declared ``page_pool`` owner in
  rnb_tpu.memledger (slabs are live-backed persistent arrays); exact
  counters (allocs/frees/live pages, gathers, gather rows, feature
  lookups/hits/bytes saved) surfaced end-to-end — the ``Pages:``
  log-meta line, the ``pages.*`` metric family, and the
  ``parse_utils --check`` invariants (pages allocated == freed + live
  at teardown; feature hits <= lookups; gather rows foot with cache
  hit rows).

Sizing: ``pager.pool_mb`` is the explicit per-arena page budget; when
absent, the arena is sized from the ledger's cache-owner data — the
loader passes its clip-cache byte budget (the bytes the blob cache
would have owned), and the feature arena inherits the same figure via
:meth:`Pager.size_hint` (its rows are orders of magnitude smaller, so
this is a generous ceiling, bounded and visible in ``Memory owners:``
either way).
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from rnb_tpu import lockwitness, memledger

#: fallback arena budget when neither ``pool_mb`` nor a cache-derived
#: size hint exists (a bare pager on a cache-less config)
DEFAULT_ARENA_MB = 64


@dataclasses.dataclass(frozen=True)
class PagerSettings:
    """Validated, defaulted view of the ``pager`` root config key."""

    page_rows: int = 4
    pool_mb: Optional[float] = None
    feature_cache: bool = False

    @staticmethod
    def from_config(raw: Optional[dict]) -> Optional["PagerSettings"]:
        """Settings from the (schema-validated) config dict, or None
        when the key is absent or ``enabled`` is false."""
        if not raw or not raw.get("enabled", True):
            return None
        page_rows = int(raw.get("page_rows", 4))
        if page_rows < 1:
            raise ValueError("pager.page_rows must be >= 1, got %r"
                             % (raw.get("page_rows"),))
        pool_mb = raw.get("pool_mb")
        if pool_mb is not None:
            pool_mb = float(pool_mb)
            if pool_mb <= 0:
                raise ValueError("pager.pool_mb must be > 0, got %r"
                                 % (raw.get("pool_mb"),))
        return PagerSettings(page_rows=page_rows, pool_mb=pool_mb,
                             feature_cache=bool(
                                 raw.get("feature_cache", False)))


class GatherPlan:
    """One pinned hit: flat slab row per valid entry row, released
    after the consumption seam dispatched its gather."""

    __slots__ = ("arena", "pages", "src_rows", "valid", "_released")

    def __init__(self, arena: "Arena", pages: Tuple[int, ...],
                 src_rows: np.ndarray, valid: int):
        self.arena = arena
        self.pages = pages
        self.src_rows = src_rows  # int32 (valid,) flat slab rows
        self.valid = int(valid)
        self._released = False

    def release(self) -> None:
        """Unpin the plan's pages (idempotent — drop paths and the
        post-dispatch path may both reach it)."""
        if not self._released:
            self._released = True
            self.arena.unpin(self.pages)


class Arena:
    """One device slab carved into fixed-size row pages.

    All mutation runs under the owning :class:`Pager`'s lock (hit
    plans are built on executor threads while inserts run on transfer
    workers). The slab itself is updated through the donated writer in
    rnb_tpu.ops.pages — in place, never copied — and read through
    functional gathers, so readers always observe a consistent value.
    """

    #: declared concurrency contract (rnb-lint RNB-C001): the arena
    #: has no lock of its own — every mutable field is guarded by the
    #: owning pager's shared lock (hit plans build on executor threads
    #: while inserts run on transfer workers)
    GUARDED_BY = {
        "_free": "pager.lock",
        "_pins": "pager.lock",
        "_limbo": "pager.lock",
        "_slab": "pager.lock",
    }

    def __init__(self, pager: "Pager", name: str,
                 row_shape: Tuple[int, ...], dtype,
                 budget_bytes: int, device=None,
                 gather_keys: Tuple[str, str] = ("gathers",
                                                 "gather_rows")):
        import jax
        import jax.numpy as jnp
        self.pager = pager
        self.name = str(name)
        # which counter pair this arena's gathers increment: the clip
        # arena foots gather_rows against the clip cache's hit rows,
        # the feature arena keeps its own pair so the --check footing
        # never mixes the two planes
        self.gather_keys = tuple(gather_keys)
        self.row_shape = tuple(int(d) for d in row_shape)
        self.dtype = np.dtype(dtype)
        self.page_rows = int(pager.settings.page_rows)
        row_bytes = int(np.prod(self.row_shape)) * self.dtype.itemsize
        self.row_bytes = row_bytes
        self.page_bytes = row_bytes * self.page_rows
        self.num_pages = max(1, int(budget_bytes) // self.page_bytes)
        slab = jnp.zeros((self.num_pages * self.page_rows,)
                         + self.row_shape, self.dtype)
        if device is not None:
            slab = jax.device_put(slab, device)
        self._slab = slab
        self.device_label = str(device) if device is not None \
            else str(getattr(slab, "device", "device0"))
        #: LIFO free list: recently-freed pages are re-alloc'd first
        #: (their slab rows are warm)
        self._free: List[int] = list(range(self.num_pages))
        self._pins: Dict[int, int] = {}
        self._limbo: set = set()
        # one ledger probe per arena under the declared page_pool
        # owner; live=True — the slab is a persistent device array
        memledger.register("page_pool", self.device_label,
                           ("pager", self.name, id(self)),
                           self.nbytes, live=True)

    @property
    def nbytes(self) -> int:
        return self.num_pages * self.page_bytes

    # -- page lifecycle (call under the pager lock) -------------------

    def pages_needed(self, valid: int) -> int:
        return (int(valid) + self.page_rows - 1) // self.page_rows

    def alloc_locked(self, n_pages: int) -> Optional[Tuple[int, ...]]:
        """Pop ``n_pages`` from the free list, or None (the caller
        evicts and retries, or skips the insert — counted either
        way)."""
        if n_pages > len(self._free):
            self.pager.counters["alloc_fails"] += 1
            return None
        pages = tuple(self._free.pop() for _ in range(n_pages))
        self.pager.counters["allocs"] += n_pages
        return pages

    def free_locked(self, pages: Tuple[int, ...]) -> None:
        """Return pages to the free list; pages a live plan still pins
        park in limbo until their unpin (the eviction-under-gather
        safety rule)."""
        for page in pages:
            if self._pins.get(page, 0) > 0:
                self._limbo.add(page)
            else:
                self._free.append(page)
                self.pager.counters["frees"] += 1

    def pin_locked(self, pages: Tuple[int, ...]) -> None:
        for page in pages:
            self._pins[page] = self._pins.get(page, 0) + 1

    def unpin(self, pages: Tuple[int, ...]) -> None:
        with self.pager.lock:
            for page in pages:
                left = self._pins.get(page, 0) - 1
                if left > 0:
                    self._pins[page] = left
                    continue
                self._pins.pop(page, None)
                if page in self._limbo:
                    # the eviction already happened; the page only now
                    # becomes reusable
                    self._limbo.discard(page)
                    self._free.append(page)
                    self.pager.counters["frees"] += 1

    def live_pages_locked(self) -> int:
        """Pages not on the free list: entry-held + limbo."""
        return self.num_pages - len(self._free)

    # -- row addressing ------------------------------------------------

    def flat_rows(self, pages: Tuple[int, ...],
                  valid: int) -> np.ndarray:
        """int32 (valid,) flat slab row of each entry row: row ``r``
        lives at ``pages[r // page_rows] * page_rows + r % page_rows``."""
        r = np.arange(int(valid))
        return (np.asarray(pages, np.int64)[r // self.page_rows]
                * self.page_rows + r % self.page_rows).astype(np.int32)

    # -- slab IO ------------------------------------------------------

    def write_entry_locked(self, pages: Tuple[int, ...], src_pool,
                           src_row0: int, valid: int) -> None:
        """Publish ``valid`` device-pool rows starting at ``src_row0``
        into ``pages``: one donated write per page (fixed page_rows
        index vector — clamp-padded tails land in page rows no gather
        references), swapping the slab value atomically under the
        pager lock."""
        from rnb_tpu.ops.pages import write_rows_page
        slab = self._slab
        for pi, page in enumerate(pages):
            base = pi * self.page_rows
            idx = np.minimum(src_row0 + base + np.arange(self.page_rows),
                             src_row0 + valid - 1).astype(np.int32)
            slab = write_rows_page(slab, src_pool, idx,
                                   page * self.page_rows)
        self._slab = slab

    def gather(self, dest_pool, src_rows, interpret: bool = False):
        """Overlay slab rows onto ``dest_pool`` on device (counted);
        ``src_rows`` is the emission-level int32 table (``-1`` keeps
        the pool row)."""
        from rnb_tpu.ops.pages import gather_rows
        src = np.asarray(src_rows, np.int32)
        with self.pager.lock:
            slab = self._slab
            self.pager.counters[self.gather_keys[0]] += 1
            self.pager.counters[self.gather_keys[1]] += \
                int((src >= 0).sum())
        return gather_rows(dest_pool, slab, src, interpret=interpret)

    def snapshot_locked(self) -> Dict[str, int]:
        return {
            "name": self.name,
            "pages": self.num_pages,
            "page_rows": self.page_rows,
            "page_bytes": self.page_bytes,
            "free": len(self._free),
            "limbo": len(self._limbo),
            "bytes": self.nbytes,
        }


class _FeatureEntry:
    __slots__ = ("pages", "valid", "nbytes")

    def __init__(self, pages: Tuple[int, ...], valid: int,
                 nbytes: int):
        self.pages = pages
        self.valid = int(valid)
        self.nbytes = int(nbytes)


class FeatureCache:
    """Post-stage activation rows on feature pages, keyed by
    (content key, stage fingerprint).

    The consuming stage owns the value semantics: it registers its
    fingerprint + row shape via :meth:`attach` (before the run
    barrier), inserts rows strictly AFTER its forward succeeded, and
    gathers hits from the arena. The loader only probes
    (:meth:`acquire`) and stamps the plan onto the request's time
    card. First writer wins; LRU eviction frees pages until an insert
    fits.
    """

    GUARDED_BY = {
        "_arena": "pager.lock",
        "_fingerprint": "pager.lock",
        "_entries": "pager.lock",
    }

    def __init__(self, pager: "Pager"):
        self.pager = pager
        self._arena: Optional[Arena] = None
        self._fingerprint = None
        self._entries: "OrderedDict[tuple, _FeatureEntry]" = \
            OrderedDict()

    def attach(self, arena: Arena, fingerprint) -> None:
        """Register the consuming stage's arena + fingerprint. Keys
        from other fingerprints (a config change, a different stage)
        can never alias: the fingerprint is part of every entry key."""
        with self.pager.lock:
            self._arena = arena
            self._fingerprint = fingerprint

    @property
    def ready(self) -> bool:
        # _arena is published by attach() under the pager lock; the
        # loader probes from its own threads, so the read pairs with it
        with self.pager.lock:
            return self._arena is not None

    def __len__(self) -> int:
        with self.pager.lock:
            return len(self._entries)

    def acquire(self, content_key) -> Optional[GatherPlan]:
        """Counted feature lookup -> pinned plan on a hit (the caller
        releases after its gather dispatched), None on a miss or
        before any stage attached."""
        with self.pager.lock:
            self.pager.counters["feature_lookups"] += 1
            arena = self._arena
            if arena is None:
                return None
            key = (content_key, self._fingerprint)
            entry = self._entries.get(key)
            if entry is None:
                return None
            self._entries.move_to_end(key)
            self.pager.counters["feature_hits"] += 1
            arena.pin_locked(entry.pages)
            return GatherPlan(arena, entry.pages,
                              arena.flat_rows(entry.pages, entry.valid),
                              entry.valid)

    def contains(self, content_key) -> bool:
        with self.pager.lock:
            if self._arena is None:
                return False
            return (content_key, self._fingerprint) in self._entries

    def insert(self, content_key, src_pool, row0: int,
               valid: int) -> bool:
        """Insert ``valid`` output rows (device pool rows
        ``[row0, row0 + valid)``) under ``content_key``. First writer
        wins; evicts LRU entries until the pages fit; skips (False)
        when even a fully-evicted arena cannot hold the entry."""
        valid = int(valid)
        if valid < 1:
            return False
        with self.pager.lock:
            arena = self._arena
            if arena is None:
                return False
            key = (content_key, self._fingerprint)
            if key in self._entries:
                return False
            needed = arena.pages_needed(valid)
            pages = None
            while True:
                pages = arena.alloc_locked(needed)
                if pages is not None or not self._entries:
                    break
                _, evicted = self._entries.popitem(last=False)
                arena.free_locked(evicted.pages)
                self.pager.counters["feature_evictions"] += 1
            if pages is None:
                return False
            arena.write_entry_locked(pages, src_pool, row0, valid)
            self._entries[key] = _FeatureEntry(
                pages, valid, needed * arena.page_bytes)
            self.pager.counters["feature_inserts"] += 1
            return True


class Pager:
    """The per-job page-allocator root: arena registry, shared lock,
    exact counters, and the feature cache. Created by the launcher
    from the ``pager`` root config key and handed to every
    ``SUPPORTS_PAGER`` stage via ``enable_pager``."""

    COUNTER_KEYS = ("allocs", "frees", "alloc_fails", "gathers",
                    "gather_rows", "feature_lookups", "feature_hits",
                    "feature_inserts", "feature_evictions",
                    "feature_gathers", "feature_gather_rows",
                    "feature_bytes_saved")

    GUARDED_BY = {
        "counters": "lock",
        "_arenas": "lock",
        "_size_hint_bytes": "lock",
        "_owned_ids": "lock",
    }

    def __init__(self, settings: PagerSettings):
        self.settings = settings
        self.lock = lockwitness.lock("Pager.lock", threading.RLock)
        self.counters: Dict[str, int] = {k: 0
                                         for k in self.COUNTER_KEYS}
        self._arenas: List[Arena] = []
        self._size_hint_bytes: Optional[int] = None
        self._owned_ids: Dict[int, object] = {}
        self.feature: Optional[FeatureCache] = \
            FeatureCache(self) if settings.feature_cache else None

    # -- sizing --------------------------------------------------------

    def size_hint(self, nbytes: int) -> None:
        """Feed the ledger-derived sizing figure (the loader's clip
        cache budget — the bytes the cache owner would claim); later
        arenas without an explicit ``pool_mb`` inherit it."""
        with self.lock:
            if nbytes and nbytes > 0:
                self._size_hint_bytes = int(nbytes)

    def resolve_budget(self, requested: Optional[int] = None) -> int:
        """Arena byte budget: explicit ``pool_mb`` wins; else the
        caller's own figure; else the size hint; else the default."""
        if self.settings.pool_mb is not None:
            return int(self.settings.pool_mb * (1 << 20))
        if requested and requested > 0:
            return int(requested)
        with self.lock:
            if self._size_hint_bytes:
                return self._size_hint_bytes
        return DEFAULT_ARENA_MB << 20

    # -- arenas --------------------------------------------------------

    def create_arena(self, name: str, row_shape, dtype,
                     budget_bytes: Optional[int] = None,
                     device=None,
                     gather_keys: Tuple[str, str] = ("gathers",
                                                     "gather_rows")
                     ) -> Arena:
        arena = Arena(self, name, row_shape, dtype,
                      self.resolve_budget(budget_bytes), device=device,
                      gather_keys=gather_keys)
        with self.lock:
            self._arenas.append(arena)
        return arena

    # -- shared-object accounting -------------------------------------

    def adopt_shared(self, name: str, arr, device_label=None) -> None:
        """Account a pager-machinery device array (the loaders' zero
        pools feature hits dispatch with) under the page_pool owner,
        and mark it so the handoff edge's residency accounting can
        exclude it (rnb_tpu.handoff ``external_owner`` — the bytes are
        already footed here, and the same array is adopted on every
        feature-hit take)."""
        with self.lock:
            self._owned_ids[id(arr)] = arr
        memledger.register(
            "page_pool",
            str(device_label) if device_label is not None
            else str(getattr(arr, "device", "device0")),
            ("pager-shared", name), int(arr.nbytes), live=True)

    def owns(self, arr) -> bool:
        with self.lock:
            return id(arr) in self._owned_ids

    # -- counters ------------------------------------------------------

    def note_feature_saved(self, nbytes: int) -> None:
        """Wire bytes a feature hit did NOT ship host->device (the
        decode+transfer the hit skipped; the skipped forward is time,
        not bytes, and shows up in throughput instead)."""
        with self.lock:
            self.counters["feature_bytes_saved"] += int(nbytes)

    def snapshot(self) -> Dict[str, int]:
        """Point-in-time counter + occupancy copy for the ``Pages:``
        log-meta line and the ``pages.*`` metric polls."""
        with self.lock:
            snap = dict(self.counters)
            snap["arenas"] = len(self._arenas)
            snap["pages"] = sum(a.num_pages for a in self._arenas)
            snap["page_rows"] = int(self.settings.page_rows)
            snap["live"] = sum(a.live_pages_locked()
                               for a in self._arenas)
            snap["limbo"] = sum(len(a._limbo) for a in self._arenas)
            snap["bytes"] = sum(a.nbytes for a in self._arenas)
            snap["feature_entries"] = (len(self.feature._entries)
                                       if self.feature is not None
                                       else 0)
            return snap
