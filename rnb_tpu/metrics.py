"""Live metrics plane: streaming time-series telemetry, SLO burn-rate
accounting, and an anomaly-triggered flight recorder.

Every signal PRs 1-10 built is end-of-run: BenchmarkResult counters,
log-meta lines and the PR 6 trace all materialize at exit, so a
20-minute run that breaches its SLO at minute 3 is invisible until
minute 20 — the opposite of what a serving tier under Poisson load
needs. This module puts the same signals on the wire *while the run is
live*, in three pieces:

* **A time-series registry** (:class:`MetricsRegistry`, root config key
  ``metrics: {enabled, interval_ms, flight_recorder}``): monotone
  counters, gauges, sliding-window rates and fixed-log2-bucket latency
  histograms. A background flusher appends one snapshot per interval
  to ``logs/<job>/metrics.jsonl`` and writes a Prometheus-style text
  exposition (``metrics.prom``) at teardown — the export surface the
  future cross-host ingest tier (ROADMAP items 2 and 5) schedules on.
  Metric names are DECLARED in ``rnb_tpu.telemetry.METRIC_REGISTRY``
  and enforced twice: statically by rnb-lint RNB-T009 (every
  ``metrics.counter/gauge/observe/mark/name`` call site must use a
  declared name) and at runtime (an undeclared name raises).
* **Bridging, not re-measuring**: the registry taps signals the
  runtime already produces. A :class:`SpanBridge` installs as the
  ``rnb_tpu.trace`` collector so the existing hot-loop spans
  (``exec{i}.model_call``, ``queue_get``, ...) feed latency histograms
  and instants feed counters with zero new hot-path instrumentation;
  ledger objects (FaultStats, DeadlineStats, HedgeGovernor,
  LaneHealthBoard) and stage-owned subsystems (clip cache, staging
  pool, handoff edges) register *poll sources* the flusher reads each
  tick. House rule — metrics are checked, not trusted: the FINAL
  snapshot's counters must cross-foot the BenchmarkResult/log-meta
  ledgers exactly, and ``parse_utils --check`` asserts it (plus
  monotone counters and histogram bucket-sum == count).
* **SLO layer + flight recorder**: completions at the final step feed
  windowed within-deadline goodput and a burn-rate gauge (miss
  fraction over the window divided by the error budget ``1 -
  SLO_TARGET``), surfaced live and as the ``Slo:`` log-meta line. The
  flight recorder keeps a bounded ring of recent trace events even
  when full tracing is off; when a trigger fires — circuit-open, SLO
  burn-rate threshold, shed spike, queue saturation, or a forced dump
  — the ring is exported as a Perfetto-loadable ``flight-<n>.json``
  (structurally valid per ``rnb_tpu.trace.validate_trace``) with the
  metric window around the trigger embedded, so the PR 10 chaos
  incidents leave a black-box postmortem, not just counters.

Cost discipline: like :mod:`rnb_tpu.trace` and :mod:`rnb_tpu.hostprof`,
the disabled path of every module-level hook is one module-global
``None`` test and no allocation (rnb-lint hot-path enforced). With the
``metrics`` root key absent nothing is installed, no new log-meta line
is written, and every artifact stays byte-identical to the pre-metrics
schema.
"""

from __future__ import annotations

import collections
import json
import math
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from rnb_tpu import trace as trace_mod

#: the active per-job registry, installed/cleared by rnb_tpu.benchmark
#: around the measured run (module-global like trace.ACTIVE: jobs run
#: one at a time per process)
ACTIVE: Optional["MetricsRegistry"] = None

#: default snapshot interval — small enough that a short chaos run
#: still produces several snapshots, large enough that the flusher is
#: invisible next to the pipeline's own work
DEFAULT_INTERVAL_MS = 250.0
#: flight-recorder ring capacity (events) and dump budget
DEFAULT_RING_EVENTS = 4096
DEFAULT_MAX_DUMPS = 4
#: SLO burn-rate threshold that trips the flight recorder (burn 1.0 =
#: consuming the error budget exactly; > threshold = burning it down)
DEFAULT_BURN_THRESHOLD = 2.0
#: shed-spike trigger: windowed sheds/second at or above this fires
DEFAULT_SHED_SPIKE_PER_S = 2.0
#: queue-saturation trigger: depth/capacity at or above this fires
DEFAULT_QUEUE_SATURATION = 0.9
#: per-trigger-kind dump cooldown so one sustained incident cannot
#: burn the whole dump budget on near-identical rings
DEFAULT_COOLDOWN_S = 5.0

#: availability objective behind the burn-rate gauge: the error budget
#: is ``1 - SLO_TARGET`` of requests allowed to miss their deadline
SLO_TARGET = 0.99

#: sliding window (seconds) behind every windowed rate and the SLO
#: burn computation
RATE_WINDOW_S = 10.0

#: fixed log2 latency histogram: bucket i covers
#: (2^(i + LOG2_MIN_MS - 1), 2^(i + LOG2_MIN_MS)] milliseconds, with
#: the first bucket absorbing everything below and the last everything
#: above — 18 buckets from 0.125 ms to ~16 s, one fixed shape so
#: snapshots diff and exposition scrapes never reshape
HIST_LOG2_MIN = -3
HIST_NUM_BUCKETS = 18

#: hard cap on distinct series (name + implicit label) the registry
#: will hold — a label-cardinality explosion must degrade to a counted
#: overflow, never to unbounded memory
MAX_SERIES = 512

#: env var forcing one flight dump at teardown (the ``make metrics``
#: gate uses it to assert dump validity without staging an incident)
FORCE_DUMP_ENV = "RNB_FLIGHT_FORCE"

#: trigger kinds the flight recorder recognizes
TRIGGER_CIRCUIT_OPEN = "circuit_open"
TRIGGER_SLO_BURN = "slo_burn"
TRIGGER_SHED_SPIKE = "shed_spike"
TRIGGER_QUEUE_SATURATION = "queue_saturation"
TRIGGER_FORCED = "forced"
TRIGGER_MEMORY_WATERMARK = "memory_watermark"


def name(pattern: str, *args) -> str:
    """Format a registered metric-name pattern once, ahead of a hot
    loop (``metrics.name("queue.e%d.depth", i)``) — same contract as
    :func:`rnb_tpu.trace.name`: the literal stays visible to the
    static checker (RNB-T009) while the hot path pays zero formatting
    cost per event."""
    return pattern % args if args else pattern


def counter(metric_name: str, n: int = 1) -> None:
    """Increment a monotone counter. Disabled path: one None test."""
    m = ACTIVE
    if m is None:
        return
    m.inc_counter(metric_name, n)


def gauge(metric_name: str, value) -> None:
    """Set a gauge to its latest value."""
    m = ACTIVE
    if m is None:
        return
    m.set_gauge(metric_name, value)


def observe(metric_name: str, ms: float) -> None:
    """Record one latency observation (milliseconds) into the metric's
    fixed-log2-bucket histogram."""
    m = ACTIVE
    if m is None:
        return
    m.observe_ms(metric_name, ms)


def mark(metric_name: str, n: int = 1) -> None:
    """Record ``n`` events on a sliding-window rate series."""
    m = ACTIVE
    if m is None:
        return
    m.mark_rate(metric_name, n)


def trigger(reason: str, detail: Optional[dict] = None) -> None:
    """Arm a flight-recorder dump (serviced by the flusher on its next
    tick — never file IO on the caller's thread). Disabled path, and
    the recorder-off path, are one None/attribute test each."""
    m = ACTIVE
    if m is None:
        return
    m.request_dump(reason, detail)


def completions(cards, finish_s: Optional[float] = None) -> None:
    """Final-step completion feed for the live SLO layer: one call per
    registered completion batch (rnb_tpu.runner bookkeeping). Each
    card's within-deadline verdict comes from its own ``deadline_s``
    stamp when present, else from the job's SLO budget applied to its
    end-to-end latency."""
    m = ACTIVE
    if m is None:
        return
    m.note_completions(cards, finish_s)


def register_stage(model, handoff=None) -> None:
    """One-stop stage-side bridge registration (called by the executor
    after stage construction, before the start barrier): stage-owned
    subsystems — the clip cache, the staging pool, a handoff edge —
    become poll sources of the active registry. No-op when metrics are
    off or the stage owns none of them."""
    m = ACTIVE
    if m is None:
        return
    cache = getattr(model, "cache", None)
    if cache is not None and hasattr(cache, "snapshot"):
        m.add_poll(snapshot_poll(
            "cache", cache.snapshot,
            counters=("hits", "misses", "inserts", "evictions",
                      "coalesced", "oversize"),
            gauges=("bytes_resident", "entries")))
    staging = getattr(model, "staging", None)
    if staging is not None and hasattr(staging, "snapshot"):
        m.add_poll(snapshot_poll(
            "staging", staging.snapshot,
            counters=("acquires", "acquire_waits", "staged_batches",
                      "copied_batches", "reallocs"),
            gauges=("slots",)))
    if handoff is not None and hasattr(handoff, "snapshot"):
        m.add_poll(snapshot_poll(
            "handoff", handoff.snapshot,
            counters=("d2d_edges", "host_edges", "d2d_bytes",
                      "host_bytes")))


def snapshot_poll(prefix: str, snapshot_fn: Callable[[], dict],
                  counters: Tuple[str, ...] = (),
                  gauges: Tuple[str, ...] = ()) -> Callable:
    """Adapt a subsystem's ``snapshot()`` dict into a registry poll
    source: each named key becomes ``<prefix>.<key>``. Counter values
    from several sources under one name are SUMMED per tick (each
    source's own counter is monotone, so the sum stays monotone —
    the property ``parse_utils --check`` asserts across snapshots)."""
    def poll():
        snap = snapshot_fn()
        out = []
        for key in counters:
            out.append(("counter", prefix + "." + key,
                        int(snap.get(key, 0))))
        for key in gauges:
            out.append(("gauge", prefix + "." + key,
                        float(snap.get(key, 0))))
        return out
    return poll


class MetricsSettings:
    """Validated per-job knobs (root config key ``metrics``)."""

    __slots__ = ("enabled", "interval_ms", "flight_enabled",
                 "ring_events", "max_dumps", "burn_threshold",
                 "shed_spike_per_s", "queue_saturation", "cooldown_s")

    def __init__(self, enabled: bool = True,
                 interval_ms: float = DEFAULT_INTERVAL_MS,
                 flight_recorder=None):
        self.enabled = bool(enabled)
        self.interval_ms = float(interval_ms)
        fr = flight_recorder
        if fr is None or fr is True:
            fr = {}
        if fr is False:
            fr = {"enabled": False}
        self.flight_enabled = bool(fr.get("enabled", True))
        self.ring_events = int(fr.get("ring_events",
                                      DEFAULT_RING_EVENTS))
        self.max_dumps = int(fr.get("max_dumps", DEFAULT_MAX_DUMPS))
        self.burn_threshold = float(fr.get("burn_threshold",
                                           DEFAULT_BURN_THRESHOLD))
        self.shed_spike_per_s = float(fr.get("shed_spike_per_s",
                                             DEFAULT_SHED_SPIKE_PER_S))
        self.queue_saturation = float(fr.get("queue_saturation",
                                             DEFAULT_QUEUE_SATURATION))
        self.cooldown_s = float(fr.get("cooldown_s",
                                       DEFAULT_COOLDOWN_S))

    @staticmethod
    def from_config(raw: Optional[dict]) -> Optional["MetricsSettings"]:
        """Settings from the validated config dict, or None when the
        key is absent or ``enabled`` is false (metrics fully off: no
        registry, no flusher, no new meta lines, byte-stable logs)."""
        if raw is None:
            return None
        settings = MetricsSettings(
            enabled=raw.get("enabled", True),
            interval_ms=raw.get("interval_ms", DEFAULT_INTERVAL_MS),
            flight_recorder=raw.get("flight_recorder"))
        return settings if settings.enabled else None


# -- series kinds ------------------------------------------------------

def hist_bucket(ms: float) -> int:
    """The fixed-log2 bucket index of one millisecond observation:
    bucket b covers (2^(b-1+LOG2_MIN), 2^(b+LOG2_MIN)] so a value
    exactly on a bound lands in the bucket whose ``le`` covers it."""
    if ms <= 0.0:
        return 0
    idx = int(math.ceil(math.log2(ms))) - HIST_LOG2_MIN
    return max(0, min(HIST_NUM_BUCKETS - 1, idx))


def hist_upper_bounds() -> List[float]:
    """The exposed ``le`` upper bound (ms) of each bucket; the last is
    +inf (everything above the fixed range)."""
    bounds = [2.0 ** (HIST_LOG2_MIN + i)
              for i in range(HIST_NUM_BUCKETS - 1)]
    return bounds + [float("inf")]


class _Hist:
    __slots__ = ("buckets", "count", "sum_ms")

    def __init__(self):
        self.buckets = [0] * HIST_NUM_BUCKETS
        self.count = 0
        self.sum_ms = 0.0

    def add(self, ms: float) -> None:
        self.buckets[hist_bucket(ms)] += 1
        self.count += 1
        self.sum_ms += ms


class _Rate:
    """Sliding-window event counter with bounded memory: events
    aggregate into per-second cells, cells outside the window are
    pruned on every touch — at most ``RATE_WINDOW_S + 1`` cells live
    regardless of event volume."""

    __slots__ = ("cells", "total")

    def __init__(self):
        self.cells: "collections.OrderedDict[int, int]" = \
            collections.OrderedDict()
        self.total = 0  # lifetime marks (monotone, for footing)

    def add(self, n: int, now: float) -> None:
        sec = int(now)
        self.cells[sec] = self.cells.get(sec, 0) + n
        self.total += n
        self._prune(now)

    def _prune(self, now: float) -> None:
        horizon = int(now - RATE_WINDOW_S)
        while self.cells:
            oldest = next(iter(self.cells))
            if oldest >= horizon:
                break
            del self.cells[oldest]

    def per_second(self, now: float) -> float:
        self._prune(now)
        return sum(self.cells.values()) / RATE_WINDOW_S


class _PendingDump:
    __slots__ = ("reason", "detail", "t")

    def __init__(self, reason: str, detail: Optional[dict], t: float):
        self.reason = reason
        self.detail = detail
        self.t = t


class SpanBridge:
    """The trace-hook collector metrics installs (``trace.ACTIVE``):
    every existing span/instant site feeds the registry's bridged
    histograms/counters AND the flight ring, with the real per-job
    :class:`rnb_tpu.trace.Tracer` (when full tracing is also on)
    forwarded to unchanged. Duck-types the Tracer surface the module
    hooks use (``span``/``add_event``), so no trace call site changes.
    """

    __slots__ = ("registry", "forward", "ring", "ring_evicted")

    def __init__(self, registry: "MetricsRegistry",
                 forward=None, ring_events: int = 0):
        self.registry = registry
        self.forward = forward
        self.ring = (collections.deque(maxlen=int(ring_events))
                     if ring_events > 0 else None)
        #: events the bounded ring has evicted — a flight dump must
        #: report its truncation (metrics are checked, not trusted),
        #: so this lands in the dump's dropped_events count
        self.ring_evicted = 0

    def span(self, event_name: str, rid: Optional[int] = None):
        return trace_mod._Span(self, event_name, rid)

    def add_event(self, event_name: str, ph: str, t0: float,
                  dur: float, rid: Optional[int],
                  args: Optional[dict]) -> None:
        if self.forward is not None:
            self.forward.add_event(event_name, ph, t0, dur, rid, args)
        self.registry.bridge_event(event_name, ph, dur)
        ring = self.ring
        if ring is not None:
            if len(ring) == ring.maxlen:
                self.ring_evicted += 1
            ring.append((event_name, ph, t0, dur,
                         threading.current_thread().name, rid, args))

    def ring_events(self) -> list:
        return list(self.ring) if self.ring is not None else []


class MetricsRegistry:
    """Bounded, thread-safe live-metrics state + background flusher.

    One instance per job (rnb_tpu.benchmark owns install/clear). All
    mutators take one lock; the flusher thread snapshots under the
    same lock and does file IO outside it.
    """

    GUARDED_BY = {
        "_counters": "_lock",
        "_gauges": "_lock",
        "_rates": "_lock",
        "_hists": "_lock",
        "_polled_counters": "_lock",
        "_overflowed": "_lock",
        "_fired_triggers": "_lock",
        "_pending_dumps": "_lock",
        "_last_dump_t": "_lock",
        "slo_tracked": "_lock",
        "slo_within": "_lock",
        "slo_missed": "_lock",
        "burn_max": "_lock",
        "num_triggers": "_lock",
        "seq": "_lock",
    }

    UNGUARDED_OK = {
        "_name_kind": "declared-kind memo; racing writers insert "
                      "identical values (the patterns are static)",
        "num_dumps": "written only by the flusher's dump path; other "
                     "threads' bare int reads gate a budget heuristic",
        "_jsonl": "flusher-thread confined after start(); start/stop "
                  "are the controller's lifecycle edges",
        "_flusher": "controller-thread lifecycle (start/stop)",
    }

    def __init__(self, settings: Optional[MetricsSettings] = None,
                 job_dir: Optional[str] = None, job_id: str = "",
                 slo_budget_ms: Optional[float] = None,
                 slo_target: float = SLO_TARGET):
        from rnb_tpu.telemetry import METRIC_REGISTRY
        self.settings = settings or MetricsSettings()
        self.job_dir = job_dir
        self.job_id = job_id
        self.slo_budget_ms = slo_budget_ms
        self.slo_target = float(slo_target)
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._rates: Dict[str, _Rate] = {}
        self._hists: Dict[str, _Hist] = {}
        #: polled-counter values by name (recomputed each tick as the
        #: sum over sources, so restarts of the flusher never double)
        self._polled_counters: Dict[str, int] = {}
        self._polls: List[Callable] = []
        self._gauge_sources: List[Tuple[str, Callable[[], float],
                                        Optional[float]]] = []
        #: name -> declared kind, compiled from the registry patterns
        self._declared: List[Tuple[re.Pattern, str]] = [
            (re.compile("^" + re.escape(spec.pattern)
                        .replace(re.escape("{step}"), r"\d+") + "$"),
             spec.kind)
            for spec in METRIC_REGISTRY]
        self._name_kind: Dict[str, str] = {}
        self._overflowed = 0
        # -- snapshots / flusher --------------------------------------
        self.seq = 0
        self._recent: "collections.deque" = collections.deque(maxlen=8)
        self._jsonl = None
        self._flusher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # -- SLO layer ------------------------------------------------
        self.slo_tracked = 0
        self.slo_within = 0
        self.slo_missed = 0
        self.burn_max = 0.0
        # -- flight recorder ------------------------------------------
        self.bridge: Optional[SpanBridge] = None
        self._pending_dumps: List[_PendingDump] = []
        self.num_dumps = 0
        self.num_triggers = 0
        self._last_dump_t: Dict[str, float] = {}
        #: observers of EVERY trigger firing (not just ones that win a
        #: dump slot) — the devobs plane arms a bounded device-capture
        #: window here, so one anomaly leaves both a flight dump and a
        #: device trace. Called OUTSIDE the registry lock.
        self.trigger_hooks: List[Callable[[str, dict], None]] = []
        self._fired_triggers: List[Tuple[str, dict]] = []

    # -- declaration enforcement --------------------------------------

    def _kind_of(self, metric_name: str) -> str:
        kind = self._name_kind.get(metric_name)
        if kind is None:
            for pattern, declared_kind in self._declared:
                if pattern.match(metric_name):
                    kind = declared_kind
                    break
            self._name_kind[metric_name] = kind or "undeclared"
        if kind is None or kind == "undeclared":
            # runtime twin of rnb-lint RNB-T009: a name the registry
            # does not declare fails loudly at the first use, not as
            # silent drift in the exported series
            raise ValueError(
                "metric %r is not declared in "
                "telemetry.METRIC_REGISTRY — declare it (and its "
                "kind) or fix the call site" % metric_name)
        return kind

    def _admit_locked(self, store: dict, metric_name: str) -> bool:
        # series-cardinality bound: beyond MAX_SERIES total series the
        # registry counts the overflow instead of growing — a label
        # explosion degrades the telemetry, never the host
        if metric_name in store:
            return True
        total = (len(self._counters) + len(self._gauges)
                 + len(self._rates) + len(self._hists))
        if total >= MAX_SERIES:
            self._overflowed += 1
            return False
        return True

    # -- mutators ------------------------------------------------------

    def inc_counter(self, metric_name: str, n: int = 1) -> None:
        self._kind_of(metric_name)
        with self._lock:
            if self._admit_locked(self._counters, metric_name):
                self._counters[metric_name] = \
                    self._counters.get(metric_name, 0) + int(n)

    def set_gauge(self, metric_name: str, value) -> None:
        self._kind_of(metric_name)
        with self._lock:
            if self._admit_locked(self._gauges, metric_name):
                self._gauges[metric_name] = float(value)

    def observe_ms(self, metric_name: str, ms: float) -> None:
        self._kind_of(metric_name)
        with self._lock:
            if self._admit_locked(self._hists, metric_name):
                hist = self._hists.get(metric_name)
                if hist is None:
                    hist = self._hists[metric_name] = _Hist()
                hist.add(float(ms))

    def mark_rate(self, metric_name: str, n: int = 1,
                  now: Optional[float] = None) -> None:
        self._kind_of(metric_name)
        now = time.time() if now is None else now
        with self._lock:
            if self._admit_locked(self._rates, metric_name):
                rate = self._rates.get(metric_name)
                if rate is None:
                    rate = self._rates[metric_name] = _Rate()
                rate.add(int(n), now)

    # -- bridges -------------------------------------------------------

    def bridge_event(self, event_name: str, ph: str,
                     dur: float) -> None:
        """One trace event observed by the :class:`SpanBridge`: spans
        land in the same-named latency histogram, instants in the
        same-named counter — IF the metric registry declares the name
        (the trace vocabulary is wider than the bridged subset, so
        undeclared trace events are simply not metrics)."""
        kind = self._name_kind.get(event_name)
        if kind is None:
            for pattern, declared_kind in self._declared:
                if pattern.match(event_name):
                    kind = declared_kind
                    break
            # the trace vocabulary is wider than the bridged subset:
            # undeclared trace events are cached as such and skipped
            # (the same sentinel _kind_of raises on for real call
            # sites, so the cache cannot launder an undeclared name)
            self._name_kind[event_name] = kind or "undeclared"
        if kind == "histogram" and ph == "X":
            with self._lock:
                if self._admit_locked(self._hists, event_name):
                    hist = self._hists.get(event_name)
                    if hist is None:
                        hist = self._hists[event_name] = _Hist()
                    hist.add(max(0.0, dur) * 1000.0)
        elif kind == "counter" and ph == "i":
            with self._lock:
                if self._admit_locked(self._counters, event_name):
                    self._counters[event_name] = \
                        self._counters.get(event_name, 0) + 1

    def add_poll(self, fn: Callable) -> None:
        """Register a poll source (``fn() -> [(kind, name, value)]``)
        the flusher reads each tick. Counter values under one name sum
        across sources; gauges likewise (occupancy-style values whose
        per-instance sum is the job-wide truth)."""
        with self._lock:
            self._polls.append(fn)

    def add_gauge_source(self, metric_name: str,
                         fn: Callable[[], float],
                         capacity: Optional[float] = None) -> None:
        """Register a live occupancy probe (queue depth, slot count)
        sampled at every flush tick; ``capacity`` arms the
        queue-saturation flight trigger at depth/capacity >=
        the configured threshold."""
        self._kind_of(metric_name)
        with self._lock:
            self._gauge_sources.append((metric_name, fn, capacity))

    def note_completions(self, cards,
                         finish_s: Optional[float] = None) -> None:
        """SLO feed: a batch of requests completed at the final step.
        Within-deadline comes from each card's ``deadline_s`` stamp
        when present (the deadline layer's own contract), else from
        the job budget applied to the card's end-to-end span; with no
        budget at all every completion counts within (the goodput
        series still streams, burn stays 0)."""
        now = time.time() if finish_s is None else finish_s
        tracked = within = 0
        for tc in getattr(cards, "time_cards", None) or \
                ([cards] if not isinstance(cards, (list, tuple))
                 else cards):
            timings = getattr(tc, "timings", None)
            if not timings:
                continue
            tracked += 1
            finish = max(timings.values())
            deadline_s = getattr(tc, "deadline_s", None)
            if deadline_s is not None:
                ok = finish <= deadline_s
            elif self.slo_budget_ms is not None:
                e2e_ms = (finish - min(timings.values())) * 1000.0
                ok = e2e_ms <= self.slo_budget_ms
            else:
                ok = True
            if ok:
                within += 1
        missed = tracked - within
        with self._lock:
            self.slo_tracked += tracked
            self.slo_within += within
            self.slo_missed += missed
            if self._admit_locked(self._rates, "slo.good"):
                rate = self._rates.get("slo.good")
                if rate is None:
                    rate = self._rates["slo.good"] = _Rate()
                if within:
                    rate.add(within, now)
            if missed and self._admit_locked(self._rates, "slo.miss"):
                rate = self._rates.get("slo.miss")
                if rate is None:
                    rate = self._rates["slo.miss"] = _Rate()
                rate.add(missed, now)

    # -- flight recorder ----------------------------------------------

    def request_dump(self, reason: str,
                     detail: Optional[dict] = None) -> None:
        """Arm a dump; the flusher services it (file IO never happens
        on the triggering thread — circuit transitions fire this under
        the health board's lock)."""
        with self._lock:
            self._trigger_locked(reason, detail or {}, time.time())
        self._dispatch_trigger_hooks()

    def _service_dumps_locked(self) -> List[_PendingDump]:
        due, self._pending_dumps = self._pending_dumps, []
        return due

    def _write_dump(self, pending: _PendingDump,
                    snapshots: List[dict]) -> Optional[str]:
        if self.job_dir is None or self.bridge is None:
            return None
        events = self.bridge.ring_events()
        path = os.path.join(self.job_dir,
                            "flight-%d.json" % self.num_dumps)
        # ranked blocking attribution over the dump's ring window
        # (rnb_tpu.critpath): the dump names its suspect spans up
        # front, no separate analysis pass over the events needed
        try:
            from rnb_tpu.critpath import rank_ring_events
            suspects = rank_ring_events(events)
        except Exception:
            suspects = []  # an annotation must not lose the dump
        trace_mod.export_events(
            # dropped_events = what the bounded ring evicted: a
            # truncated window must read as truncated, never complete
            events, self.bridge.ring_evicted, path, self.job_id,
            extra={"flight_trigger": pending.reason,
                   "flight_detail": pending.detail or {},
                   "flight_t_epoch_s": pending.t,
                   "metric_window": snapshots,
                   "critpath": suspects})
        self.num_dumps += 1
        return path

    # -- snapshots / flusher ------------------------------------------

    def snapshot(self, now: Optional[float] = None) -> dict:
        """One interval snapshot: poll every source, derive the SLO
        gauges, evaluate flusher-side flight triggers, and return the
        JSON-ready record. Pure state + probe reads; the caller owns
        file IO."""
        now = time.time() if now is None else now
        polled: Dict[str, int] = {}
        polled_gauges: Dict[str, float] = {}
        with self._lock:
            polls = list(self._polls)
            gauge_sources = list(self._gauge_sources)
        for fn in polls:
            try:
                items = fn()
            except Exception:
                continue  # a dying source must not kill the flusher
            for kind, metric_name, value in items:
                if kind == "counter":
                    polled[metric_name] = \
                        polled.get(metric_name, 0) + int(value)
                else:
                    polled_gauges[metric_name] = \
                        polled_gauges.get(metric_name, 0.0) \
                        + float(value)
        saturated = None
        for metric_name, fn, capacity in gauge_sources:
            try:
                value = float(fn())
            except Exception:
                continue
            polled_gauges[metric_name] = value
            if capacity and value / capacity \
                    >= self.settings.queue_saturation:
                saturated = {"queue": metric_name, "depth": value,
                             "capacity": capacity}
        with self._lock:
            self._polled_counters = polled
            for metric_name, value in polled_gauges.items():
                self._gauges[metric_name] = value
            # SLO derivation over the sliding window
            good = self._rates.get("slo.good")
            miss = self._rates.get("slo.miss")
            sheds = self._rates.get("faults.sheds")
            goodput = good.per_second(now) if good is not None else 0.0
            # slo.miss already includes sheds/failures (the control
            # ledger marks it per shed), so burn uses it ALONE — the
            # faults.sheds rate exists for the shed-spike trigger
            bad_ps = miss.per_second(now) if miss is not None else 0.0
            shed_ps = (sheds.per_second(now)
                       if sheds is not None else 0.0)
            events_ps = goodput + bad_ps
            budget = max(1e-9, 1.0 - self.slo_target)
            burn = ((bad_ps / events_ps) / budget
                    if events_ps > 0 else 0.0)
            self.burn_max = max(self.burn_max, burn)
            self._gauges["slo.goodput_vps"] = goodput
            self._gauges["slo.burn_rate"] = burn
            counters = dict(self._counters)
            for metric_name, value in self._polled_counters.items():
                counters[metric_name] = value
            # the SLO ledger rides the counters section too (monotone
            # by construction), so the final snapshot's footing
            # against the Slo: line is checkable like every other
            counters["slo.tracked"] = self.slo_tracked
            counters["slo.within"] = self.slo_within
            counters["slo.missed"] = self.slo_missed
            self.seq += 1
            record = {
                "seq": self.seq,
                "t": now,
                "counters": counters,
                "gauges": dict(self._gauges),
                "rates": {metric_name: rate.per_second(now)
                          for metric_name, rate
                          in self._rates.items()},
                "histograms": {
                    metric_name: {"count": hist.count,
                                  "sum_ms": hist.sum_ms,
                                  "buckets": list(hist.buckets)}
                    for metric_name, hist in self._hists.items()},
                "series_overflowed": self._overflowed,
            }
            self._recent.append(record)
            if burn >= self.settings.burn_threshold:
                self._trigger_locked(TRIGGER_SLO_BURN,
                                     {"burn_rate": burn}, now)
            if shed_ps >= self.settings.shed_spike_per_s:
                self._trigger_locked(TRIGGER_SHED_SPIKE,
                                     {"sheds_per_s": shed_ps}, now)
            if saturated is not None:
                self._trigger_locked(TRIGGER_QUEUE_SATURATION,
                                     saturated, now)
        self._dispatch_trigger_hooks()
        return record

    def _dispatch_trigger_hooks(self) -> None:
        """Deliver trigger firings to the registered observers outside
        the registry lock (a hook arming a devobs capture must never
        nest under it)."""
        with self._lock:
            fired, self._fired_triggers = self._fired_triggers, []
        for reason, detail in fired:
            for hook in list(self.trigger_hooks):
                try:
                    hook(reason, detail)
                except Exception:
                    continue  # an observer must not break the plane

    def _trigger_locked(self, reason: str, detail: dict,
                        now: float) -> None:
        # every firing reaches the hooks FIRST — even with the flight
        # recorder disarmed (no ring), a devobs capture must still arm
        # on the anomaly; the ring gate below guards only the dump
        # machinery and its trigger counter
        self._fired_triggers.append((reason, dict(detail)))
        if self.bridge is None or self.bridge.ring is None:
            return
        self.num_triggers += 1
        if self.num_dumps + len(self._pending_dumps) \
                >= self.settings.max_dumps:
            return
        last = self._last_dump_t.get(reason)
        if last is not None and now - last < self.settings.cooldown_s:
            return
        self._last_dump_t[reason] = now
        self._pending_dumps.append(_PendingDump(reason, detail, now))

    def tick(self, now: Optional[float] = None) -> dict:
        """One flusher iteration: snapshot, append to metrics.jsonl,
        refresh the Prometheus exposition file, service pending flight
        dumps. Public so tests (and the final flush) drive it without
        the thread."""
        record = self.snapshot(now)
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(record, sort_keys=True) + "\n")
            self._jsonl.flush()
        if self.job_dir is not None:
            # live exposition on EVERY flush interval (not just
            # teardown), written atomically so a file-based scraper
            # can never read a torn exposition — the file twin of the
            # operator server's GET /metrics (rnb_tpu.statusz), which
            # serves the same renderer
            try:
                self._write_exposition(
                    os.path.join(self.job_dir, "metrics.prom"))
            except OSError:
                pass  # a full disk must not kill the flusher
        with self._lock:
            due = self._service_dumps_locked()
            snapshots = list(self._recent)
        for pending in due:
            try:
                self._write_dump(pending, snapshots)
            except Exception:
                continue  # a failing dump must not kill the flusher
        return record

    def start(self) -> None:
        if self.job_dir is not None and self._jsonl is None:
            self._jsonl = open(os.path.join(self.job_dir,
                                            "metrics.jsonl"), "w")
        if self._flusher is None:
            self._flusher = threading.Thread(target=self._flush_loop,
                                             name="metrics-flusher",
                                             daemon=True)
            self._flusher.start()

    def _flush_loop(self) -> None:
        period = max(0.01, self.settings.interval_ms / 1000.0)
        while not self._stop.wait(timeout=period):
            try:
                self.tick()
            except Exception:
                continue  # the flusher must outlive any bad probe

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the flusher, service the forced-dump env hook, take
        the FINAL snapshot (the one --check cross-foots against the
        log-meta ledgers — the caller must only stop after every
        pipeline thread joined so the polled counters are stable),
        and write the Prometheus-style exposition file."""
        self._stop.set()
        if self._flusher is not None:
            self._flusher.join(timeout=timeout)
            self._flusher = None
        if os.environ.get(FORCE_DUMP_ENV):
            self.request_dump(TRIGGER_FORCED, {"env": FORCE_DUMP_ENV})
        # the final tick appends the footing snapshot AND refreshes
        # the exposition file (tick writes it every interval now)
        self.tick()
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None

    def render_exposition(self) -> str:
        """Prometheus text exposition of the CURRENT state — the
        pull-based face the future cross-host ingest tier scrapes
        (ROADMAP item 2); one fixed naming rule: ``rnb_`` prefix,
        dots -> underscores. One renderer backs both faces: the
        per-tick/teardown ``metrics.prom`` file and the operator
        server's live ``GET /metrics`` (rnb_tpu.statusz), so the two
        can never drift."""
        def prom(metric_name: str) -> str:
            return "rnb_" + re.sub(r"[^a-zA-Z0-9_]", "_", metric_name)

        bounds = hist_upper_bounds()
        with self._lock:
            counters = dict(self._counters)
            counters.update(self._polled_counters)
            gauges = dict(self._gauges)
            hists = {metric_name: (list(h.buckets), h.count, h.sum_ms)
                     for metric_name, h in self._hists.items()}
        parts: List[str] = []
        for metric_name in sorted(counters):
            pn = prom(metric_name)
            parts.append("# TYPE %s counter\n" % pn)
            parts.append("%s %d\n" % (pn, counters[metric_name]))
        for metric_name in sorted(gauges):
            pn = prom(metric_name)
            parts.append("# TYPE %s gauge\n" % pn)
            parts.append("%s %g\n" % (pn, gauges[metric_name]))
        for metric_name in sorted(hists):
            buckets, count, sum_ms = hists[metric_name]
            pn = prom(metric_name) + "_ms"
            parts.append("# TYPE %s histogram\n" % pn)
            cumulative = 0
            for bound, n in zip(bounds, buckets):
                cumulative += n
                le = ("+Inf" if math.isinf(bound)
                      else "%g" % bound)
                parts.append('%s_bucket{le="%s"} %d\n'
                             % (pn, le, cumulative))
            parts.append("%s_sum %g\n" % (pn, sum_ms))
            parts.append("%s_count %d\n" % (pn, count))
        return "".join(parts)

    def _write_exposition(self, path: str) -> None:
        """Write :meth:`render_exposition` atomically (tmp +
        ``os.replace``) so file-based scrapers watching the per-tick
        refresh never observe a torn exposition."""
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.render_exposition())
        os.replace(tmp, path)

    # -- reporting ----------------------------------------------------

    def final_snapshot(self) -> Optional[dict]:
        """The last snapshot taken (after :meth:`stop`, the FINAL
        footing record — identical to metrics.jsonl's last line, so
        consumers calibrating from it are reproducible offline)."""
        with self._lock:
            return self._recent[-1] if self._recent else None

    def summary(self) -> Dict[str, int]:
        """Final counters for the ``Metrics:``/``Slo:`` log-meta lines
        and the BenchmarkResult ``metrics_*``/``slo_*`` fields."""
        with self._lock:
            series = (len(self._counters) + len(self._gauges)
                      + len(self._rates) + len(self._hists)
                      + len(self._polled_counters))
            return {
                "snapshots": self.seq,
                "series": series,
                "dumps": self.num_dumps,
                "triggers": self.num_triggers,
                "slo_tracked": self.slo_tracked,
                "slo_within": self.slo_within,
                "slo_missed": self.slo_missed,
                "burn_max_milli": int(round(self.burn_max * 1000.0)),
            }
