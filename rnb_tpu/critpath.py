"""Per-request critical-path extraction: blocking chains, rankings,
throughput bounds.

PR 6 made every request's latency *attributable* (phases partition the
end-to-end span) and PRs 11/13 made every subsystem *measurable* — but
nothing in the tree interprets the measurements: finding the bottleneck
is still a human scrolling Perfetto. This module recovers, for every
completed request, the **blocking chain**: the unique sequence of
segments that actually gated its completion, derived from the same
TimeCard stamps the phase attribution walks (so it works on any past
log directory) and refined by the trace-mode stamps where present.
Segments carry both a *class* — ``queue_wait`` (starved behind a
queue), ``decode``, ``hold`` (batch-fill wait), ``transfer``,
``service``, ``drain`` (publish/pickup) — and the *pipeline step* they
blocked on, so the aggregation answers "which stage, doing what, eats
the latency" instead of "somewhere in the middle".

Invariant (``parse_utils --check`` enforces it per request on any job
dir): chain segments PARTITION the end-to-end span — they are the
adjacent gaps of the time-ordered stamp sequence, so their sum equals
``last - first`` up to float rounding, hedge- and redispatch-stamped
requests included (a redispatched request's re-stamped ``runner{i}``
events sort into their true positions; a hedged request's completing
copy owns the stamps that survived).

Aggregated over a run's steady-state completions the chains yield:

* a **blocking-time ranking** — total blocked milliseconds per
  (step, class), the "what would I fix first" list;
* a per-stage **critical-path throughput bound** — ``lanes x requests
  / occupied_seconds``: the rate at which the stage's occupied
  segments (decode/transfer/service/drain — not waits) could serve
  requests, whose minimum names the stage that caps the pipeline.

Surfaced as the ``Critpath:``/``Critpath stages:`` log-meta pair, a
``# critpath`` table trailer, ``critpath_*`` BenchmarkResult fields
and ``parse_utils --explain`` — all gated on the root ``critpath``
config key (absent => byte-stable logs, the PR 6 pattern). The same
ranking rule annotates flight-recorder dumps (:func:`rank_ring_events`)
so an anomaly dump names its suspect without a separate analysis pass.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Mapping, Optional, Tuple

from rnb_tpu.trace import _step_of, _strip_suffix

#: segment classes a blocking chain may carry, in display order
SEGMENT_CLASSES = ("queue_wait", "decode", "hold", "transfer",
                   "service", "drain")

#: classes that OCCUPY a stage (its lanes are doing the request's
#: work): the per-stage throughput bound divides lane capacity by
#: these; ``queue_wait``/``hold`` are waits, not occupancy
OCCUPIED_CLASSES = ("decode", "transfer", "service", "drain")


class CritpathSettings:
    """Validated per-job knobs (root config key ``critpath``)."""

    __slots__ = ("enabled",)

    def __init__(self, enabled: bool = True):
        self.enabled = bool(enabled)

    @staticmethod
    def from_config(raw: Optional[dict]) -> Optional["CritpathSettings"]:
        """Settings from the validated config dict, or None when the
        key is absent or ``enabled`` is false (extraction fully off:
        no meta lines, no trailer, byte-stable logs)."""
        if raw is None:
            return None
        settings = CritpathSettings(enabled=raw.get("enabled", True))
        return settings if settings.enabled else None


def _digits_of(base: str) -> Optional[int]:
    """The step index embedded in a known stamp key, or None."""
    for prefix, suffix in (("runner", "_start"), ("inference", "_start"),
                           ("inference", "_finish"), ("decode", "_done"),
                           ("transfer", "_start"), ("transfer", "_done")):
        step = _step_of(base, prefix, suffix)
        if step is not None:
            return step
    return None


def classify_gap(prev_key: str, next_key: str) -> Tuple[str, int]:
    """(class, step) of the gap between two adjacent stamps.

    The same gap-walk rule as :func:`rnb_tpu.trace.phase_of`, kept
    structurally parallel so the two decompositions partition the same
    span — but returning the *pipeline step* each gap blocked on,
    which the phase names lump (every inter-stage wait is one
    ``inter_stage_queue`` phase; here it is ``(queue_wait, i)``).
    Unrecognized gaps land in ``drain`` at the last known step rather
    than being dropped: attribution must account for every
    microsecond or it lies."""
    prev_base = _strip_suffix(prev_key)
    next_base = _strip_suffix(next_key)
    step = _step_of(next_base, "runner", "_start")
    if step is not None:
        return ("queue_wait", step)
    step = _step_of(next_base, "decode", "_done")
    if step is not None:
        return ("decode", step)
    step = _step_of(next_base, "transfer", "_start")
    if step is not None:
        return ("hold", step)
    step = _step_of(next_base, "transfer", "_done")
    if step is not None:
        return ("transfer", step)
    step = _step_of(next_base, "inference", "_start")
    if step is not None:
        return ("queue_wait", step)
    step = _step_of(next_base, "inference", "_finish")
    if step is not None:
        if _step_of(prev_base, "transfer", "_done") == step:
            return ("drain", step)  # transfer done -> publish pickup
        if step == 0:
            # the un-refined loader span: decode(+transfer) in one —
            # same rule the phase attribution applies to past logs
            return ("decode", 0)
        return ("service", step)
    prev_step = _digits_of(prev_base)
    return ("drain", prev_step if prev_step is not None else 0)


def blocking_chain(timings: Mapping[str, float]
                   ) -> List[Tuple[str, int, float]]:
    """One request's blocking chain: ``[(class, step, ms), ...]`` in
    completion order, consecutive same-(class, step) gaps merged.

    ``timings`` is one TimeCard's stamp mapping (or one timing-table
    row): key -> epoch seconds; NaNs (union-schema frames) are
    dropped. The ms values sum to ``(last - first) * 1000`` exactly
    (up to float rounding) — the partition invariant."""
    stamps = [(float(t), key) for key, t in timings.items()
              if t == t]
    stamps.sort(key=lambda p: p[0])
    chain: List[Tuple[str, int, float]] = []
    for (t_prev, k_prev), (t_next, k_next) in zip(stamps, stamps[1:]):
        cls, step = classify_gap(k_prev, k_next)
        ms = (t_next - t_prev) * 1000.0
        if chain and chain[-1][0] == cls and chain[-1][1] == step:
            chain[-1] = (cls, step, chain[-1][2] + ms)
        else:
            chain.append((cls, step, ms))
    return chain


def chain_totals(timings: Mapping[str, float]
                 ) -> Dict[Tuple[str, int], float]:
    """{(class, step): total ms} over one request's blocking chain."""
    totals: Dict[Tuple[str, int], float] = {}
    for cls, step, ms in blocking_chain(timings):
        totals[(cls, step)] = totals.get((cls, step), 0.0) + ms
    return totals


def segment_key(cls: str, step: int) -> str:
    """The flat ``<class><step>`` name the ``# critpath`` trailer and
    the ranking tables print (``service1``, ``queue_wait0``)."""
    return "%s%d" % (cls, step)


def aggregate(rows: Iterable[Tuple[Mapping[str, float], bool, int]],
              lanes: Mapping[int, int]) -> Optional[Dict[str, object]]:
    """The job-level critical-path report over completed requests.

    ``rows`` yields ``(timings, hedged, redispatched)`` per request —
    the stamp mapping plus the PR 10 claim-ledger content stamps
    (``hedge_copy`` marking a completion won by the hedge clone,
    ``redispatched`` counting lane-eviction re-enqueues). ``lanes``
    maps step index -> executor instances (replica lanes included).
    Returns None when no request decomposed (fewer than 2 stamps
    everywhere)."""
    stages: Dict[int, Dict[str, Dict[str, float]]] = {}
    requests = 0
    segments = 0
    residual_us_max = 0.0
    hedged = 0
    redispatched = 0
    for timings, hedge_flag, redisp in rows:
        chain = blocking_chain(timings)
        if not chain:
            continue
        requests += 1
        segments += len(chain)
        finite = [float(t) for t in timings.values() if t == t]
        e2e_ms = (max(finite) - min(finite)) * 1000.0
        residual_us_max = max(
            residual_us_max,
            abs(sum(ms for _c, _s, ms in chain) - e2e_ms) * 1000.0)
        if hedge_flag:
            hedged += 1
        redispatched += int(redisp)
        for cls, step, ms in chain:
            entry = stages.setdefault(step, {}).setdefault(
                cls, {"total_ms": 0.0, "count": 0})
            entry["total_ms"] += ms
            entry["count"] += 1
    if not requests:
        return None
    stage_detail: Dict[str, Dict[str, object]] = {}
    bound_step = -1
    bound_vps = 0.0
    for step in sorted(stages):
        classes = {
            cls: {"total_ms": round(entry["total_ms"], 3),
                  "mean_ms": round(entry["total_ms"] / requests, 3),
                  "count": int(entry["count"])}
            for cls, entry in stages[step].items()}
        occupied_ms = sum(stages[step][cls]["total_ms"]
                          for cls in OCCUPIED_CLASSES
                          if cls in stages[step])
        step_lanes = int(lanes.get(step, 1) or 1)
        # the stage could serve `requests` in occupied_ms/lanes of
        # wall — its critical-path throughput bound; 0 occupied ms
        # (a pure-wait stage) bounds nothing
        vps = (step_lanes * requests / (occupied_ms / 1000.0)
               if occupied_ms > 0.0 else 0.0)
        stage_detail["step%d" % step] = {
            "lanes": step_lanes,
            "requests": requests,
            "occupied_ms": round(occupied_ms, 3),
            "bound_vps": round(vps, 3),
            "classes": classes,
        }
        if vps > 0.0 and (bound_step < 0 or vps < bound_vps):
            bound_step = step
            bound_vps = vps
    return {
        "requests": requests,
        "segments": segments,
        "residual_us_max": int(round(residual_us_max)),
        "hedged": hedged,
        "redispatched": redispatched,
        "bound_step": bound_step,
        "bound_vps_milli": int(round(bound_vps * 1000.0)),
        "stage_detail": stage_detail,
    }


def ranking(stage_detail: Mapping[str, Mapping[str, object]]
            ) -> List[Tuple[str, float, float]]:
    """The blocking-time ranking from a ``Critpath stages:`` payload:
    ``[(segment_name, total_ms, mean_ms)]`` sorted by total blocked
    time, largest first (ties: segment name) — the "fix this first"
    list ``parse_utils --explain`` prints."""
    rows: List[Tuple[str, float, float]] = []
    for step_key, entry in stage_detail.items():
        step = int(step_key[4:])
        for cls, stats in dict(entry.get("classes", {})).items():
            rows.append((segment_key(cls, step),
                         float(stats["total_ms"]),
                         float(stats["mean_ms"])))
    rows.sort(key=lambda r: (-r[1], r[0]))
    return rows


def trailer_totals(rows: Iterable[Mapping[str, float]]
                   ) -> Tuple[int, Dict[str, int]]:
    """(steady request count, {segment_name: total_us}) — the
    ``# critpath`` trailer's payload over one instance's rows."""
    n = 0
    totals: Dict[str, float] = {}
    for timings in rows:
        per_req = chain_totals(timings)
        if not per_req:
            continue
        n += 1
        for (cls, step), ms in per_req.items():
            key = segment_key(cls, step)
            totals[key] = totals.get(key, 0.0) + ms
    return n, {key: int(round(ms * 1000.0))
               for key, ms in totals.items()}


def rank_ring_events(events: Iterable[Tuple],
                     top: int = 12) -> List[Dict[str, object]]:
    """Ranked busy-time attribution over a flight-recorder ring
    window: collection-schema event tuples ``(name, ph, t0, dur_s,
    thread, rid, args)`` -> the ``top`` span names by total duration,
    ``[{name, busy_ms, count}, ...]``. Embedded in every flight dump's
    ``otherData.critpath`` so an anomaly dump names its suspect
    without a separate analysis pass."""
    busy: Dict[str, List[float]] = {}
    for event in events:
        name, ph, _t0, dur = event[0], event[1], event[2], event[3]
        if ph != "X":
            continue
        entry = busy.setdefault(str(name), [0.0, 0])
        entry[0] += max(0.0, float(dur)) * 1000.0
        entry[1] += 1
    ranked = sorted(busy.items(), key=lambda kv: (-kv[1][0], kv[0]))
    return [{"name": name, "busy_ms": round(ms, 3), "count": int(count)}
            for name, (ms, count) in ranked[:top]]
