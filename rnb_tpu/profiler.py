"""Device-op profiler bridge: per-op (name, start_ns, end_ns) timelines.

The TPU-native equivalent of the reference's CUPTI Activity bridge
(SURVEY.md §2.2 N1; reference utils/cupti.cpp exposes
``initialize()/flush()/report()`` and the smoke test at
test_cupti.py:1-21).  Same three-call contract here:

* :func:`initialize` — start XLA trace capture (jax.profiler);
* :func:`flush` — stop capture, forcing buffered trace data to disk;
* :func:`report` — parse the captured ``.xplane.pb`` and return
  ``[(op_name, start_ns, end_ns)]``, clearing captured state.

Parsing is done natively (native/xplane.cpp via ctypes) when the
library is built (``make -C native``); a pure-Python wire-format
walker with identical output covers environments without a toolchain.

On TPU backends the interesting planes are ``/device:TPU:*`` (XLA ops
on the core timeline); on CPU test backends there are only host
planes.  ``report(plane_filter=...)`` selects; the default prefers
device planes and falls back to everything.
"""

from __future__ import annotations

import ctypes
import glob
import os
import shutil
import tempfile
import threading
from typing import List, Optional, Tuple, Union

Interval = Tuple[str, int, int]
#: internal parse shape; surfaced by ``report(include_plane=True)``
PlaneInterval = Tuple[str, int, int, str]

_state_lock = threading.Lock()
_trace_dir: Optional[str] = None
_trace_dir_owned = False  # True only for dirs we mkdtemp'd ourselves
_capturing = False
_lib_cache = None
_lib_checked = False

DEVICE_PLANE_MARKER = "/device:"


def _xplane_lib():
    global _lib_cache, _lib_checked
    if _lib_checked:
        return _lib_cache
    _lib_checked = True
    path = os.environ.get("RNB_NATIVE_XPLANE_LIB")
    if not path:
        repo_root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
        path = os.path.join(repo_root, "native", "build",
                            "librnb_xplane.so")
    if os.environ.get("RNB_DISABLE_NATIVE") or not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.rnb_xplane_load.restype = ctypes.c_void_p
    lib.rnb_xplane_load.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.rnb_xplane_num_events.restype = ctypes.c_longlong
    lib.rnb_xplane_num_events.argtypes = [ctypes.c_void_p]
    for fn in ("rnb_xplane_event_name", "rnb_xplane_event_plane",
               "rnb_xplane_event_line"):
        getattr(lib, fn).restype = ctypes.c_char_p
        getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_longlong]
    for fn in ("rnb_xplane_event_start_ns", "rnb_xplane_event_end_ns"):
        getattr(lib, fn).restype = ctypes.c_longlong
        getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_longlong]
    lib.rnb_xplane_free.restype = None
    lib.rnb_xplane_free.argtypes = [ctypes.c_void_p]
    _lib_cache = lib
    return lib


def initialize(trace_dir: Optional[str] = None) -> None:
    """Begin capturing device activity (reference cupti.initialize)."""
    global _trace_dir, _trace_dir_owned, _capturing
    import jax
    with _state_lock:
        if _capturing:
            raise RuntimeError("profiler already initialized")
        _trace_dir_owned = trace_dir is None
        _trace_dir = trace_dir or tempfile.mkdtemp(prefix="rnb_xprof_")
        jax.profiler.start_trace(_trace_dir)
        _capturing = True


def flush() -> None:
    """Stop capture and force trace buffers to disk (cupti.flush)."""
    global _capturing
    import jax
    with _state_lock:
        if not _capturing:
            return
        jax.profiler.stop_trace()
        _capturing = False


def _xplane_files() -> List[str]:
    if _trace_dir is None:
        return []
    return sorted(glob.glob(
        os.path.join(_trace_dir, "plugins", "profile", "*",
                     "*.xplane.pb")))


def _parse_native(lib, path: str, plane_filter: str) \
        -> List[PlaneInterval]:
    handle = lib.rnb_xplane_load(path.encode(),
                                 plane_filter.encode())
    if not handle:
        return []
    try:
        n = lib.rnb_xplane_num_events(handle)
        out = []
        for i in range(n):
            name = lib.rnb_xplane_event_name(handle, i)
            plane = lib.rnb_xplane_event_plane(handle, i)
            out.append((name.decode("utf-8", "replace"),
                        int(lib.rnb_xplane_event_start_ns(handle, i)),
                        int(lib.rnb_xplane_event_end_ns(handle, i)),
                        plane.decode("utf-8", "replace")))
        return out
    finally:
        lib.rnb_xplane_free(handle)


# --- pure-Python fallback wire-format walker (same field numbers the
# native parser uses; see native/xplane.cpp header comment) ---------------

def _fields(buf: bytes):
    i, n = 0, len(buf)
    while i < n:
        key = shift = 0
        while True:
            b = buf[i]
            i += 1
            key |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        field, wire = key >> 3, key & 7
        if wire == 0:
            val = shift = 0
            while True:
                b = buf[i]
                i += 1
                val |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, val
        elif wire == 2:
            ln = shift = 0
            while True:
                b = buf[i]
                i += 1
                ln |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, buf[i:i + ln]
            i += ln
        elif wire == 5:
            i += 4
            yield field, None
        elif wire == 1:
            i += 8
            yield field, None
        else:
            raise ValueError("bad wire type %d" % wire)


def _parse_python(path: str, plane_filter: str) \
        -> List[PlaneInterval]:
    # Degrade like the native parser on malformed input: return what
    # was decoded before the corruption instead of raising.
    out: List[PlaneInterval] = []
    try:
        _parse_python_into(path, plane_filter, out)
    except (IndexError, ValueError):
        pass
    return out


def _parse_python_into(path: str, plane_filter: str,
                       out: List[PlaneInterval]) -> None:
    with open(path, "rb") as f:
        data = f.read()
    for field, plane in _fields(data):
        if field != 1 or not isinstance(plane, bytes):
            continue
        plane_name = ""
        names = {}
        lines = []
        for f2, v2 in _fields(plane):
            if f2 == 2 and isinstance(v2, bytes):
                plane_name = v2.decode("utf-8", "replace")
            elif f2 == 3 and isinstance(v2, bytes):
                lines.append(v2)
            elif f2 == 4 and isinstance(v2, bytes):
                key, val = 0, None
                for f3, v3 in _fields(v2):
                    if f3 == 1 and isinstance(v3, int):
                        key = v3
                    elif f3 == 2 and isinstance(v3, bytes):
                        val = v3
                if val is not None:
                    for f4, v4 in _fields(val):
                        if f4 == 2 and isinstance(v4, bytes):
                            names[key] = v4.decode("utf-8", "replace")
                            break
        if plane_filter and plane_filter not in plane_name:
            continue
        for line in lines:
            ts_ns = 0
            events = []
            for f2, v2 in _fields(line):
                if f2 == 3 and isinstance(v2, int):
                    ts_ns = v2
                elif f2 == 4 and isinstance(v2, bytes):
                    events.append(v2)
            for ev in events:
                mid = off_ps = dur_ps = 0
                for f3, v3 in _fields(ev):
                    if not isinstance(v3, int):
                        continue
                    if f3 == 1:
                        mid = v3
                    elif f3 == 2:
                        off_ps = v3
                    elif f3 == 3:
                        dur_ps = v3
                start = ts_ns + off_ps // 1000
                out.append((names.get(mid, "metadata:%d" % mid), start,
                            start + dur_ps // 1000, plane_name))


def report(plane_filter: Optional[str] = None,
           keep_trace: bool = False,
           include_plane: bool = False) \
        -> Union[List[Interval], List[PlaneInterval]]:
    """-> captured ``[(op_name, start_ns, end_ns)]``; clears state.

    ``plane_filter`` keeps only planes whose name contains the string.
    Default: device planes if any exist, else all planes (so the same
    smoke test runs on TPU and on the CPU test backend).  Like the
    reference's ``report()`` (utils/cupti.cpp:160-166) this drains:
    captured trace files are deleted unless ``keep_trace``.

    ``include_plane`` appends the owning plane name to each tuple —
    ``(op_name, start_ns, end_ns, plane)``. Timestamps are only
    mutually comparable WITHIN a plane: XLine bases differ across
    planes (a host-threads plane and a device plane do not share a
    clock origin), so any busy-time union over a multi-plane interval
    list conflates clocks. Consumers that aggregate (device_busy.py)
    must group by plane first.
    """
    global _trace_dir
    files = _xplane_files()
    lib = _xplane_lib()
    intervals = []
    for path in files:
        if plane_filter is not None:
            wanted = [plane_filter]
        else:
            wanted = [DEVICE_PLANE_MARKER]
        for filt in wanted:
            got = (_parse_native(lib, path, filt) if lib is not None
                   else _parse_python(path, filt))
            if plane_filter is None and not got:
                got = (_parse_native(lib, path, "") if lib is not None
                       else _parse_python(path, ""))
            intervals.extend(got)
    if not include_plane:
        intervals = [(name, t0, t1) for name, t0, t1, _plane in intervals]
    intervals.sort(key=lambda t: t[1])
    with _state_lock:
        if not keep_trace and _trace_dir and not _capturing:
            if _trace_dir_owned:
                shutil.rmtree(_trace_dir, ignore_errors=True)
            else:
                # caller-supplied dir: drain only the profile subtree
                # the capture wrote, never the caller's other artifacts
                shutil.rmtree(os.path.join(_trace_dir, "plugins",
                                           "profile"),
                              ignore_errors=True)
            _trace_dir = None
    return intervals
