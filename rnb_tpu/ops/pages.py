"""Gather-from-pages: the device-side consumption seam of the pager.

The page allocator (rnb_tpu.pager) keeps cached rows resident in one
device slab — ``(num_pages * page_rows,) + row_shape`` — and a cache
hit is a list of page references, not bytes. This module provides the
two primitives that make those references usable without any host
memcpy:

* :func:`gather_rows` — overlay slab rows onto a row pool **on
  device**: ``out[i] = slab[src_rows[i]]`` where ``src_rows[i] >= 0``,
  ``out[i] = pool[i]`` otherwise. This runs once per emission, after
  the pool's transfer and before the normalize dispatch, so hit rows
  never exist as host bytes at all (the before/after is visible as the
  ``loader.cache_gather`` hostprof section: a row memcpy in the blob
  arm, a dispatch in the paged arm). Following the house kernel
  pattern (rnb_tpu/ops/ragged.py):

  - **TPU**: a Pallas kernel over a ``PrefetchScalarGridSpec`` — the
    per-row source table is scalar-prefetched into SMEM, the slab
    BlockSpec's index_map picks each program's source page block from
    it (clamped for sentinel rows), and ``pl.when`` selects
    slab-vs-passthrough so sentinel programs never read the slab;
  - **CPU / fallback**: a masked ``jnp`` formulation
    (:func:`gather_rows_reference`) with the identical contract;
  - **interpret mode**: the Pallas body runs on CPU via
    ``interpret=True`` and tests assert it matches the reference
    bit-for-bit.

* :func:`write_rows_page` — publish rows into the slab: one donated
  jit (``donate_argnums=0``) of gather + ``dynamic_update_slice``, so
  the slab updates in place (no copy of the resident pages) and keeps
  ONE jit signature per (slab, source-pool) shape pair — the source
  index vector is always ``page_rows`` long (clamp-padded), never a
  per-entry length, so the compilestats steady window sees no new
  signatures however entries are sized.

Numerics contract: gather output rows are the exact bytes of their
source (slab row or pool row) — the primitive moves bytes, it never
computes — which is what makes paged cache hits and feature-page hits
bit-identical to the uncached path by construction.
"""

from __future__ import annotations

import functools

from rnb_tpu.ops.ragged import LANES

#: sublane rows per grid step of the gather kernel (same budget rule
#: as ragged.BLOCK_SUBLANES: far under VMEM, low grid overhead)
BLOCK_SUBLANES = 512


def _on_tpu() -> bool:
    import jax
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


# -- reference (masked jnp) -------------------------------------------
#
# jax imports stay inside the functions: rnb-lint and config parsing
# import pager/ops modules without touching a backend.

def gather_rows_reference(pool, slab, src_rows):
    """Masked-jnp twin of the Pallas gather: bit-identical contract.

    ``src_rows`` is int32 ``(pool_rows,)``; entry ``i >= 0`` selects
    slab row ``i``'s replacement, ``-1`` keeps ``pool[i]``. Sentinel
    entries are clamped before the take so no out-of-bounds row is
    ever addressed (its value is discarded by the mask).
    """
    import jax.numpy as jnp
    src = jnp.asarray(src_rows, jnp.int32)
    mask = (src >= 0).reshape((pool.shape[0],) + (1,) * (pool.ndim - 1))
    safe = jnp.clip(src, 0, slab.shape[0] - 1)
    return jnp.where(mask, jnp.take(slab, safe, axis=0,
                                    mode="clip").astype(pool.dtype),
                     pool)


@functools.lru_cache(maxsize=None)
def _gather_reference_jit():
    import jax
    return jax.jit(gather_rows_reference)


# -- Pallas kernel -----------------------------------------------------

def _gather_rows_kernel(src_ref, pool_ref, slab_ref, o_ref):
    """One (pool-row, sublane-chunk) program: copy the prefetched
    source slab block when the row has one, pass the pool block
    through otherwise — sentinel programs execute a single store."""
    from jax.experimental import pallas as pl

    row = pl.program_id(0)

    @pl.when(src_ref[row] >= 0)
    def _hit():
        o_ref[:] = slab_ref[:]

    @pl.when(src_ref[row] < 0)
    def _miss():
        o_ref[:] = pool_ref[:]


def _gather_rows_pallas(pool, slab, src_rows, interpret: bool):
    """Pallas gather over ``(rows, per_row)`` lanes: grid = (pool
    rows, sublane chunks); the source table is scalar-prefetched so
    the slab BlockSpec's index_map resolves each program's source page
    block before its body runs (clamped to block 0 for sentinels — the
    fetched block is discarded by the ``pl.when`` predicate)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = pool.shape[0]
    slab_rows = slab.shape[0]
    per_row = int(np.prod(pool.shape[1:]))
    sublanes = per_row // LANES
    flat_pool = pool.reshape(rows, sublanes, LANES)
    flat_slab = slab.reshape(slab_rows, sublanes, LANES)
    block = min(BLOCK_SUBLANES, sublanes)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(rows, pl.cdiv(sublanes, block)),
        in_specs=[
            pl.BlockSpec((1, block, LANES),
                         lambda i, j, src: (i, j, 0)),
            pl.BlockSpec((1, block, LANES),
                         lambda i, j, src: (jnp.maximum(src[i], 0),
                                            j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, LANES),
                               lambda i, j, src: (i, j, 0)),
    )
    out = pl.pallas_call(
        _gather_rows_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rows, sublanes, LANES),
                                       pool.dtype),
        interpret=interpret,
    )(jnp.asarray(src_rows, jnp.int32), flat_pool, flat_slab)
    return out.reshape(pool.shape)


def gather_rows(pool, slab, src_rows, interpret: bool = False):
    """Row pool with slab rows overlaid: ``out[i] = slab[src_rows[i]]``
    where ``src_rows[i] >= 0``, else ``pool[i]`` — on device, zero
    host bytes moved.

    ``pool`` is ``(pool_rows,) + row_shape``, ``slab`` is
    ``(slab_rows,) + row_shape`` (same trailing shape and dtype),
    ``src_rows`` int32 ``(pool_rows,)`` with ``-1`` sentinels. The
    fixed-length source table is the signature discipline: every
    gather of a given (pool, slab) pair dispatches through one
    compiled executable regardless of how many rows hit. Dispatches to
    the Pallas kernel on TPU (or under ``interpret=True`` anywhere,
    for tests) when the row byte count is lane-divisible; the jitted
    masked-jnp reference otherwise.
    """
    import numpy as np

    per_row = int(np.prod(pool.shape[1:])) if pool.ndim > 1 else 0
    if (per_row > 0 and per_row % LANES == 0
            and (interpret or _on_tpu())):
        return _gather_rows_pallas(pool, slab, src_rows, interpret)
    return _gather_reference_jit()(pool, slab,
                                   np.asarray(src_rows, np.int32))


# -- page writes -------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _page_writer_jit():
    """The one donated slab writer: gather ``page_rows`` source rows
    (clamp-padded indices, so the index vector length never varies)
    and splice them at the destination row. ``donate_argnums=0``
    updates the slab buffer in place on backends that honor donation
    (verified on the CPU backend: the buffer pointer is stable across
    writes), so publishing a page never copies the resident slab."""
    import jax

    def _write(slab, src_pool, src_idx, dst_row):
        import jax.numpy as jnp
        from jax import lax
        rows = jnp.take(src_pool, src_idx, axis=0,
                        mode="clip").astype(slab.dtype)
        start = (dst_row,) + (0,) * (slab.ndim - 1)
        return lax.dynamic_update_slice(slab, rows, start)

    return jax.jit(_write, donate_argnums=(0,))


def write_rows_page(slab, src_pool, src_idx, dst_row):
    """-> new slab value with ``src_pool[src_idx]`` written at rows
    ``[dst_row, dst_row + len(src_idx))``. ``src_idx`` must always be
    ``page_rows`` long (pad by repeating a valid index — the padded
    rows land in the page's dead tail, which no gather ever
    references); ``dst_row`` is a page-aligned row offset."""
    import numpy as np
    return _page_writer_jit()(slab, src_pool,
                              np.asarray(src_idx, np.int32),
                              np.int32(dst_row))
